"""Page export service: ZMQ ROUTER answering block-chain fetches.

Each pod binds one ROUTER socket (``TRANSFER_ENDPOINT``); peer pods connect
DEALERs and request prefix chains by block hash. Transport idioms follow
``kvevents/zmq_subscriber.py``: the stable side binds, poll with a short
timeout so shutdown stays responsive, reconnect forever with backoff on
socket errors, and never let a malformed request kill the loop.

The service itself owns no KV state — the ``handler`` callback
(``(block_hashes, max_blocks) -> list[BlockPayload]``) is supplied by the
pod server, which bridges onto the engine loop thread (the only thread
allowed to touch page pools). Responses are length-capped twice: at
``max_blocks`` (request cap is clamped to the service's own) and at
``max_reply_bytes`` of page payload, so one fetch can never wedge the
socket with an unbounded chain.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ...utils import get_logger
from .protocol import (
    BlockPayload,
    MigrationPayload,
    decode_migrate,
    decode_push,
    decode_request,
    encode_error,
    encode_migrate_ack,
    encode_push_ack,
    encode_response,
)

log = get_logger("kvcache.transfer.service")

_POLL_TIMEOUT_MS = 250
_RECONNECT_BACKOFF_S = 5.0


@dataclass
class TransferServiceConfig:
    endpoint: str = "tcp://*:5558"
    model_name: str = "unknown-model"
    #: hard cap on blocks per response (requests may ask for fewer)
    max_blocks: int = 64
    #: hard cap on page-payload bytes per response
    max_reply_bytes: int = 256 << 20


class KVTransferService:
    """Binds a ROUTER socket and serves prefix-chain fetches."""

    def __init__(
        self,
        config: TransferServiceConfig,
        handler: Callable[[list[int], int], Sequence[BlockPayload]],
        tracer=None,
        push_handler: Optional[
            Callable[[str, list[BlockPayload]], tuple[int, int]]
        ] = None,
        migrate_handler: Optional[
            Callable[[str, MigrationPayload], tuple[int, bool]]
        ] = None,
    ):
        """``tracer`` (an ``obs.Tracer``, optional): when tracing is on,
        each served fetch records a ``transfer.export`` span, parented on
        the ``traceparent`` the puller carried in the request envelope —
        the exporting peer's time joins the pulling request's trace.
        ``push_handler`` (``(source_pod, blocks) -> (accepted, headroom)``,
        optional): accepts remote-tier demotion pushes into this pod's
        remote store. None (default, ``REMOTE_TIER`` off) answers pushes
        with a tolerant error the pusher treats as "fall back to plain
        eviction" — exactly what a legacy service does.
        ``migrate_handler`` (``(source_pod, migration) -> (accepted,
        resumed)``, optional): accepts live-migrated in-flight decode
        sequences. None (default, ``FLEET_CONTROLLER`` off) answers
        migrations with a tolerant error the source treats as "resume the
        sequence locally" — again exactly the legacy answer."""
        self.config = config
        self.handler = handler
        self.tracer = tracer
        self.push_handler = push_handler
        self.migrate_handler = migrate_handler
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: observability, read by /stats
        self.requests_served = 0
        self.blocks_served = 0
        self.pushes_served = 0
        self.blocks_pushed = 0
        self.migrations_served = 0
        self.migration_blocks_accepted = 0

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="kv-transfer-service", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- internals ----------------------------------------------------------
    def _run(self) -> None:
        import zmq

        ctx = zmq.Context.instance()
        while not self._stop.is_set():
            try:
                self._serve(ctx)
            except Exception:
                log.exception(
                    "transfer service failed; rebinding",
                    backoff_s=_RECONNECT_BACKOFF_S,
                )
                if self._stop.wait(_RECONNECT_BACKOFF_S):
                    return

    def _serve(self, ctx) -> None:
        import zmq

        sock = ctx.socket(zmq.ROUTER)
        try:
            sock.bind(self.config.endpoint)
            log.info(
                "kv transfer service listening",
                endpoint=self.config.endpoint,
                max_blocks=self.config.max_blocks,
            )
            poller = zmq.Poller()
            poller.register(sock, zmq.POLLIN)
            while not self._stop.is_set():
                if not dict(poller.poll(_POLL_TIMEOUT_MS)):
                    continue
                frames = sock.recv_multipart()
                if len(frames) < 2:
                    log.debug("dropping short transfer request", n=len(frames))
                    continue
                ident, payload = frames[0], frames[-1]
                sock.send_multipart([ident, self._handle(payload)])
        finally:
            sock.close(linger=0)

    def _handle(self, payload: bytes) -> bytes:
        req = decode_request(payload)
        if req is None:
            push = decode_push(payload)
            if push is not None:
                return self._handle_push(*push)
            migrate = decode_migrate(payload)
            if migrate is not None:
                return self._handle_migrate(*migrate)
            return encode_error("malformed request")
        model, hashes, max_blocks, traceparent = req
        span = None
        if self.tracer is not None and self.tracer.enabled:
            from ...obs.tracing import parse_traceparent

            span = self.tracer.start_span(
                "transfer.export",
                parent=parse_traceparent(traceparent),
                attrs={"model": model, "requested_blocks": len(hashes)},
            )
        try:
            if model != self.config.model_name:
                return encode_error(
                    f"model mismatch: serving {self.config.model_name!r}"
                )
            cap = self.config.max_blocks
            if max_blocks is not None and max_blocks > 0:
                cap = min(cap, max_blocks)
            try:
                blocks = list(self.handler(hashes[:cap], cap))
            except Exception as e:
                log.exception("transfer handler failed")
                if span is not None:
                    span.set_attr("error", type(e).__name__)
                return encode_error(f"export failed: {type(e).__name__}")
            blocks, complete = self._cap_bytes(blocks, len(hashes))
            self.requests_served += 1
            self.blocks_served += len(blocks)
            if span is not None:
                span.set_attr("served_blocks", len(blocks))
                span.set_attr(
                    "wire_bytes", sum(b.wire_bytes for b in blocks)
                )
            return encode_response(blocks, complete)
        finally:
            if span is not None:
                span.end()

    def _handle_push(
        self, model: str, source_pod: str, blocks: list[BlockPayload]
    ) -> bytes:
        """Remote-tier demotion push: commit the blocks via the pod's
        ``push_handler`` and ack (accepted, headroom). Refusals are plain
        protocol errors — the pusher's fallback is the eviction it was
        about to do anyway, so nothing here may raise."""
        if self.push_handler is None:
            return encode_error("push unsupported (REMOTE_TIER off)")
        if model != self.config.model_name:
            return encode_error(
                f"model mismatch: serving {self.config.model_name!r}"
            )
        try:
            accepted, headroom = self.push_handler(
                source_pod, blocks[: self.config.max_blocks]
            )
        except Exception as e:
            log.exception("push handler failed")
            return encode_error(f"push failed: {type(e).__name__}")
        self.pushes_served += 1
        self.blocks_pushed += accepted
        return encode_push_ack(accepted, headroom)

    def _handle_migrate(
        self, model: str, source_pod: str, migration: MigrationPayload
    ) -> bytes:
        """Live sequence migration: install the chain and admit the
        continuation via the pod's ``migrate_handler``, ack ``(accepted,
        resumed)``. Refusals are plain protocol errors — the source's
        fallback is resuming the sequence locally (cold recompute), so
        nothing here may raise."""
        if self.migrate_handler is None:
            return encode_error("migrate unsupported (FLEET_CONTROLLER off)")
        if model != self.config.model_name:
            return encode_error(
                f"model mismatch: serving {self.config.model_name!r}"
            )
        try:
            accepted, resumed = self.migrate_handler(source_pod, migration)
        except Exception as e:
            log.exception("migrate handler failed")
            return encode_error(f"migrate failed: {type(e).__name__}")
        self.migrations_served += 1
        self.migration_blocks_accepted += accepted
        return encode_migrate_ack(accepted, resumed)

    def _cap_bytes(
        self, blocks: list[BlockPayload], n_requested: int
    ) -> tuple[list[BlockPayload], bool]:
        total = 0
        for i, blk in enumerate(blocks):
            total += blk.wire_bytes
            if total > self.config.max_reply_bytes and i > 0:
                # Truncate, never drop block 0: a response must always make
                # progress or the client would retry the same oversize ask.
                return blocks[:i], False
        return blocks, len(blocks) >= n_requested
