"""KV-cache-aware scorer plugin sketch for an inference scheduler.

Mirrors the reference's EPP plugin sketch
(``examples/kv_cache_aware_scorer/kvcache_aware_scorer.go:52-112``, which is
build-excluded upstream for the same reason this is an example): shows how a
request scheduler embeds the ``KVCacheIndexer`` as a pluggable pod *scorer* —
``get_pod_scores`` → normalize to [0, 1] per candidate pod — so KV-cache
locality can be weighted against other scorers (load, queue depth, ...).

The ``Scorer`` protocol below matches the shape scheduler frameworks expect:
``score(request, candidate_pods) -> {pod: float in [0,1]}``.

Run: ``python examples/kv_cache_aware_scorer.py``
"""

import os
import sys
from dataclasses import dataclass
from typing import Protocol, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_d_kv_cache_manager_tpu.kvcache import KVCacheIndexer, KVCacheIndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import PodEntry, TokenProcessorConfig
from llm_d_kv_cache_manager_tpu.tokenization import Tokenizer


@dataclass
class LLMRequest:
    prompt: str
    target_model: str


class Scorer(Protocol):
    """Scheduler plugin interface (the llm-d EPP ``plugins.Scorer`` analogue)."""

    def score(self, request: LLMRequest, pods: Sequence[str]) -> dict[str, float]: ...


class KVCacheAwareScorer:
    """Normalizes indexer hit-depth to [0, 1] over the candidate set
    (reference ``kvcache_aware_scorer.go:85-112``)."""

    def __init__(self, indexer: KVCacheIndexer):
        self.indexer = indexer

    def score(self, request: LLMRequest, pods: Sequence[str]) -> dict[str, float]:
        raw = self.indexer.get_pod_scores(
            request.prompt, request.target_model, pod_identifiers=pods
        )
        scores = {pod: float(raw.get(pod, 0)) for pod in pods}
        max_score = max(scores.values(), default=0.0)
        if max_score == 0.0:
            return {pod: 0.0 for pod in pods}
        return {pod: s / max_score for pod, s in scores.items()}


class CharTokenizer(Tokenizer):
    def encode(self, prompt, model_name):
        return [ord(c) for c in prompt], [(i, i + 1) for i in range(len(prompt))]


def main() -> int:
    model = "meta-llama/Llama-3.1-8B-Instruct"
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=16)),
        tokenizer=CharTokenizer(),
    )
    indexer.run()
    try:
        prompt = "you are a helpful assistant. " * 8
        request = LLMRequest(prompt=prompt, target_model=model)
        pods = ["tpu-pod-1", "tpu-pod-2", "tpu-pod-3"]

        # Warm pod-1 with the whole prefix and pod-2 with half of it.
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            [ord(c) for c in prompt], model
        )
        indexer.kv_block_index.add(keys, [PodEntry("tpu-pod-1")])
        indexer.kv_block_index.add(keys[: len(keys) // 2], [PodEntry("tpu-pod-2")])

        scorer: Scorer = KVCacheAwareScorer(indexer)
        scores = scorer.score(request, pods)
        print(f"normalized scores: {scores}")
        assert scores["tpu-pod-1"] == 1.0
        assert 0.0 < scores["tpu-pod-2"] < 1.0
        assert scores["tpu-pod-3"] == 0.0

        # For schedulers that want the whole decision (not just one scorer
        # in a blend), kvcache.BlendedRouter ships the measured-best blend:
        # index score -> routed-affinity tiebreak -> load
        # (benchmarking/results/routing_capacity.md round 4).
        from llm_d_kv_cache_manager_tpu.kvcache import (
            BlendedRouter,
            PrefixAffinityTracker,
        )

        router = BlendedRouter(
            score_fn=lambda toks, names: indexer.score_tokens(toks, model, names),
            affinity=PrefixAffinityTracker(
                len(pods), capacity_blocks=4096,
                token_processor=indexer.token_processor,
            ),
            loads_fn=lambda names: [0.0] * len(names),  # wire real queue depths
        )
        decision = router.route([ord(c) for c in prompt], pods)
        print(f"blended decision: {decision}")
        assert decision.pod == "tpu-pod-1"  # warmest prefix wins
        print("OK")
        return 0
    finally:
        indexer.shutdown()


if __name__ == "__main__":
    sys.exit(main())
