from .events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    Event,
    EventBatch,
    Heartbeat,
    IndexSnapshot,
    PodDrained,
    PrefillComplete,
    RequestAudit,
    decode_event_batch,
)
from .health import FleetHealth, FleetHealthConfig
from .pool import KVEventsPool, KVEventsPoolConfig, Message, fnv1a_32
from .zmq_subscriber import ZMQSubscriber, ZMQSubscriberConfig, parse_topic
from .publisher import ZMQPublisher, ZMQPublisherConfig

__all__ = [
    "AllBlocksCleared",
    "BlockRemoved",
    "BlockStored",
    "Event",
    "EventBatch",
    "Heartbeat",
    "IndexSnapshot",
    "PodDrained",
    "PrefillComplete",
    "RequestAudit",
    "decode_event_batch",
    "FleetHealth",
    "FleetHealthConfig",
    "KVEventsPool",
    "KVEventsPoolConfig",
    "Message",
    "fnv1a_32",
    "ZMQSubscriber",
    "ZMQSubscriberConfig",
    "parse_topic",
    "ZMQPublisher",
    "ZMQPublisherConfig",
]
