"""Remote KV-block store: the third rung of the capacity ladder.

``{tpu_hbm, host_dram}`` grew a ``remote`` tier (SURVEY §2.3's "and later
remote"): when local pressure would destroy the LAST copy of a chain, the
owning pod demotes the pages over the transfer fabric to a peer with
headroom — or a dedicated ``POD_ROLE=kvstore`` pod — and this store is
what the receiving side keeps. Blocks are held **wire-ready** (the exact
``BlockPayload`` the push carried, int8 triple and all): serving a
pull-back is a dict walk plus the ZMQ send, no page pool, no device, no
requantization round trip.

The holder publishes ``BlockStored(medium="remote")`` under its OWN pod
identity when it accepts a push (and ``BlockRemoved(medium="remote")``
when capacity LRU-drops a block), so index entries for demoted chains are
keyed to the *holder* — the pod whose death actually loses the bytes.
``evict_pod``/``PodDrained`` semantics then need no special casing: the
holder dying drops exactly its remote entries, the demoter dying drops
nothing it no longer holds.

Validation mirrors the import path's trust model: geometry (page size,
logical shape, dtype, payload byte lengths — including the int8 scale
triple's exact size) and the chain-hash self-consistency check
(``hash_block(parent, token_ids) == block_hash``), so a tampered or
truncated push registers nothing. The KV bytes themselves are covered by
the payload's carried content digest when the KV_INTEGRITY plane is
attached: a push whose bytes fail their own digest is rejected, and a
stored block that rots is caught at serve time — quarantined, removed,
and revoked fleet-wide via ``BadBlock`` — before any importer installs
it. Unattested payloads (legacy senders) keep the legacy trust model:
verifying without a digest would be the recompute demotion exists to
avoid.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ...utils import RateLimitedWarn, get_logger
from ..kvblock.token_processor import hash_block
from .protocol import BlockPayload

log = get_logger("kvcache.transfer.remote_store")
_warn = RateLimitedWarn(log)


@dataclass
class RemoteStoreConfig:
    #: capacity in pages (blocks); 0 = the store accepts nothing
    capacity_pages: int
    #: tokens per page — pushed blocks must match exactly
    page_size: int
    #: logical page slice shape (n_layers, page_size, n_kv_heads, head_dim)
    page_shape: tuple[int, ...]
    #: numpy dtype string of the LOGICAL page ("bfloat16"/"float32"/...)
    dtype: str
    #: raw f32 bytes of one page's quant-scale tensor (int8 triple check)
    scale_bytes: int
    #: root of the sha256-CBOR chain (``ChunkedTokenDatabase.init_hash``)
    init_hash: int


class RemoteBlockStore:
    """LRU store of demoted KV blocks, keyed by chain hash.

    Single-threaded by contract: lives on the engine loop (the pod's
    push/export staging already serializes there) or a bench arm's
    driver. ``on_events`` receives ``BlockStored``/``BlockRemoved``
    events with ``medium="remote"`` — the holder's locality truth.
    """

    def __init__(
        self,
        config: RemoteStoreConfig,
        on_events: Optional[Callable[[list], None]] = None,
        integrity=None,
    ):
        if config.capacity_pages < 0:
            raise ValueError("capacity_pages must be >= 0")
        self.config = config
        self.on_events = on_events
        #: KV_INTEGRITY plane (a ``BlockIntegrity``), or None = legacy
        #: trust model. The store never uses the side TABLE — a stored
        #: payload carries its own digest (``BlockPayload.digest``), so a
        #: block that is simultaneously host-resident here under a
        #: different representation cannot collide; the instance only
        #: feeds the shared check/quarantine accounting.
        self.integrity = integrity
        self._blocks: "OrderedDict[int, BlockPayload]" = OrderedDict()
        import numpy as np

        self._page_bytes = int(np.prod(config.page_shape)) * np.dtype(
            config.dtype
        ).itemsize
        self._q_page_bytes = int(np.prod(config.page_shape))
        #: monotone counters (surface via /stats "remote" block)
        self.stats = {
            "accepted": 0,
            "rejected": 0,
            "evicted": 0,
            "served": 0,
        }
        if integrity is not None:
            # Extra keys only when the knob is on: the knobs-off /stats
            # payload (which embeds this dict) stays bit-identical.
            self.stats["digest_rejected"] = 0
            self.stats["quarantined"] = 0

    def __len__(self) -> int:
        return len(self._blocks)

    def __contains__(self, h: int) -> bool:
        return h in self._blocks

    @property
    def headroom(self) -> int:
        return max(self.config.capacity_pages - len(self._blocks), 0)

    def hashes(self) -> list[int]:
        """Every resident chain hash — the ``remote`` medium of the
        holder's ``IndexSnapshot`` digest, so a resync never wipes the
        demoted entries it is supposed to protect."""
        return list(self._blocks.keys())

    def _valid(self, blk: BlockPayload) -> bool:
        cfg = self.config
        if (
            blk.block_size != cfg.page_size
            or tuple(blk.shape) != tuple(cfg.page_shape)
            or blk.dtype != cfg.dtype
            or len(blk.token_ids) != cfg.page_size
        ):
            return False
        if blk.quant is not None:
            if (
                blk.quant != "int8"
                or len(blk.k_data) != self._q_page_bytes
                or len(blk.v_data) != self._q_page_bytes
                or len(blk.k_scale) != cfg.scale_bytes
                or len(blk.v_scale) != cfg.scale_bytes
            ):
                return False
        elif (
            len(blk.k_data) != self._page_bytes
            or len(blk.v_data) != self._page_bytes
        ):
            return False
        # Chain-hash self-consistency: the hash the whole system keys on
        # must be derivable from the tokens the payload claims — a
        # tampered token list or forged hash never registers.
        parent = (
            blk.parent_block_hash
            if blk.parent_block_hash is not None
            else cfg.init_hash
        )
        return hash_block(parent, blk.token_ids) == blk.block_hash

    def accept(
        self, blocks: Sequence[BlockPayload], source_pod: str = ""
    ) -> int:
        """Commit pushed blocks; returns how many registered. Invalid
        blocks are rejected individually (unlike the import path there is
        no chain-continuity requirement — a store may hold mid-chain runs
        whose parents live elsewhere in the fleet; the pull-back walk is
        what enforces consecutiveness). Over capacity the LRU block is
        dropped, with its ``BlockRemoved(remote)`` goodbye.

        ``source_pod`` (the pusher) contextualizes reject warnings; a
        storm of rejects from one peer logs rate-limited, never one line
        per block."""
        if self.config.capacity_pages == 0:
            return 0
        from ..kvevents.events import BlockRemoved, BlockStored

        accepted = 0
        events: list = []
        for blk in blocks:
            if blk.block_hash in self._blocks:
                self._blocks.move_to_end(blk.block_hash)
                continue
            if not self._valid(blk):
                self.stats["rejected"] += 1
                _warn.warning(
                    "accept-reject",
                    "pushed KV block rejected (geometry/chain-hash)",
                    pod=source_pod or "<unknown>",
                    block=blk.block_hash,
                )
                continue
            if self.integrity is not None:
                from ..integrity import CHECK_CORRUPT, page_digest

                computed = page_digest(
                    blk.k_data, blk.v_data, blk.k_scale, blk.v_scale
                )
                if (
                    self.integrity.check_carried(
                        blk.block_hash, blk.digest, computed, "remote_accept"
                    )
                    == CHECK_CORRUPT
                ):
                    # Bytes rotted in flight: refuse to register — the
                    # block never becomes servable, so no BadBlock (there
                    # is no index entry to revoke, and the pusher's local
                    # copy is already gone either way).
                    self.stats["rejected"] += 1
                    self.stats["digest_rejected"] += 1
                    _warn.warning(
                        "accept-digest",
                        "pushed KV block failed content digest; rejected",
                        pod=source_pod or "<unknown>",
                        block=blk.block_hash,
                    )
                    continue
            while len(self._blocks) >= self.config.capacity_pages:
                old_h, _ = self._blocks.popitem(last=False)
                self.stats["evicted"] += 1
                events.append(
                    BlockRemoved(block_hashes=[old_h], medium="remote")
                )
            self._blocks[blk.block_hash] = blk
            accepted += 1
            self.stats["accepted"] += 1
            events.append(
                BlockStored(
                    block_hashes=[blk.block_hash],
                    parent_block_hash=blk.parent_block_hash,
                    token_ids=list(blk.token_ids),
                    block_size=blk.block_size,
                    medium="remote",
                )
            )
        if events and self.on_events is not None:
            self.on_events(events)
        return accepted

    def serve(
        self, hashes: Sequence[int], max_blocks: Optional[int] = None
    ) -> list[BlockPayload]:
        """Pull-back read path: the longest consecutive resident run of
        ``hashes`` (the same stop-at-first-gap rule as
        ``BlockManager.lookup_chain`` — a block behind a gap can never
        prefix-hit on the importer). Touches served blocks to MRU."""
        out: list[BlockPayload] = []
        walk = hashes if max_blocks is None else hashes[:max_blocks]
        for h in walk:
            blk = self._blocks.get(h)
            if blk is None:
                break
            if self.integrity is not None and blk.digest is not None:
                from ..integrity import CHECK_CORRUPT, page_digest

                computed = page_digest(
                    blk.k_data, blk.v_data, blk.k_scale, blk.v_scale
                )
                if (
                    self.integrity.check_carried(
                        h, blk.digest, computed, "remote_serve"
                    )
                    == CHECK_CORRUPT
                ):
                    # The stored copy rotted under us: destroy it before
                    # any importer installs it, revoke this holder's
                    # index entry, and tell the fleet. The served run
                    # breaks here regardless — consecutiveness is the
                    # contract.
                    del self._blocks[h]
                    self.stats["quarantined"] += 1
                    self.integrity.quarantine(h, tier="remote")
                    if self.on_events is not None:
                        from ..kvevents.events import BadBlock, BlockRemoved

                        self.on_events(
                            [
                                BlockRemoved(block_hashes=[h], medium="remote"),
                                BadBlock(block_hashes=[h], medium="remote"),
                            ]
                        )
                    log.warning(
                        "stored KV block failed digest check; quarantined",
                        block=h,
                    )
                    break
            self._blocks.move_to_end(h)
            out.append(blk)
        if out:
            self.stats["served"] += len(out)
        return out

    def purge(self, hashes: Sequence[int]) -> int:
        """Fleet revocation consumer: drop every listed block this store
        still holds (a peer published ``BadBlock`` for them). Emits the
        holder's own ``BlockRemoved(remote)`` goodbyes so the index
        forgets this replica too. Input-driven, not knob-gated — a legacy
        pod must also honor a revocation it receives. Returns blocks
        dropped."""
        dropped = [h for h in hashes if self._blocks.pop(h, None) is not None]
        if not dropped:
            return 0
        # Lazy key: appears only once a revocation actually lands, so a
        # legacy pod that never sees one keeps its exact /stats payload.
        self.stats["purged"] = self.stats.get("purged", 0) + len(dropped)
        if self.on_events is not None:
            from ..kvevents.events import BlockRemoved

            self.on_events([BlockRemoved(block_hashes=dropped, medium="remote")])
        log.warning(
            "purged revoked KV blocks from remote store", blocks=len(dropped)
        )
        return len(dropped)
