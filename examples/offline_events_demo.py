"""Offline KV-events demo: an in-process publisher simulating a TPU serving
pod, driving the full write path + read path.

Mirrors the reference demo (``examples/kv_events/offline/main.go:150-239``):
score (empty) → publish BlockStored → score (hits) → publish BlockRemoved
for the tail blocks → score (reduced). This is the behavioral acceptance
test for the whole pipeline.

Run: ``python examples/offline_events_demo.py``
"""

import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_d_kv_cache_manager_tpu.kvcache import KVCacheIndexer, KVCacheIndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    BlockRemoved,
    BlockStored,
    KVEventsPool,
    KVEventsPoolConfig,
    ZMQPublisher,
    ZMQPublisherConfig,
    ZMQSubscriber,
    ZMQSubscriberConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization import Tokenizer

MODEL = "meta-llama/Llama-3.1-8B-Instruct"
POD = "tpu-pod-1"
PORT = 5557


class CharTokenizer(Tokenizer):
    """Offline stand-in for the HF tokenizer (no network in the demo)."""

    def encode(self, prompt, model_name):
        return [ord(c) for c in prompt], [(i, i + 1) for i in range(len(prompt))]


def main() -> int:
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=16)),
        tokenizer=CharTokenizer(),
    )
    indexer.run()
    pool = KVEventsPool(indexer.kv_block_index, KVEventsPoolConfig())
    pool.start()
    sub = ZMQSubscriber(pool, ZMQSubscriberConfig(endpoint=f"tcp://*:{PORT}"))
    sub.start()

    prompt = "You are a helpful TPU serving assistant. " * 4
    tokens = [ord(c) for c in prompt]
    keys = indexer.token_processor.tokens_to_kv_block_keys(tokens, MODEL)
    hashes = [k.chunk_hash for k in keys]

    print(f"[demo] prompt of {len(tokens)} tokens → {len(keys)} blocks")
    print("[demo] scores before any events:", indexer.get_pod_scores(prompt, MODEL))

    pub = ZMQPublisher(
        ZMQPublisherConfig(
            endpoint=f"tcp://localhost:{PORT}", pod_identifier=POD, model_name=MODEL
        )
    )

    scores = {}
    deadline = time.time() + 20
    while time.time() < deadline and not scores:
        pub.publish([BlockStored(block_hashes=hashes, token_ids=tokens, block_size=16)])
        time.sleep(0.2)
        scores = indexer.get_pod_scores(prompt, MODEL)
    print("[demo] scores after BlockStored:", scores)
    assert scores.get(POD) == len(keys), "expected full-prefix hit"

    half = len(hashes) // 2
    pub.publish([BlockRemoved(block_hashes=hashes[half:])])
    deadline = time.time() + 10
    while time.time() < deadline:
        scores = indexer.get_pod_scores(prompt, MODEL)
        if scores.get(POD) == half:
            break
        time.sleep(0.1)
    print("[demo] scores after BlockRemoved of tail:", scores)
    assert scores.get(POD) == half, "expected reduced prefix hit"

    pub.close()
    sub.shutdown()
    pool.shutdown()
    indexer.shutdown()
    print("[demo] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
