"""Prefill (causal) attention.

Single fused einsum path that XLA tiles onto the MXU. The [s_q, s_k] score
tensor is materialized, which is fine for the chunked-prefill sizes the
engine schedules (it bounds chunk length); a Pallas flash-prefill kernel is
the planned upgrade for long unchunked prefills. GQA is handled by reshaping
query heads into (kv_head, group) blocks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def causal_prefill_attention(
    q: jnp.ndarray,  # [batch, seq, n_heads, head_dim]
    k: jnp.ndarray,  # [batch, seq, n_kv_heads, head_dim]
    v: jnp.ndarray,  # [batch, seq, n_kv_heads, head_dim]
    *,
    positions: Optional[jnp.ndarray] = None,  # [batch, seq] absolute positions
    valid: Optional[jnp.ndarray] = None,  # [batch, seq] bool — False = padding
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Causal self-attention over one contiguous chunk (prefill).

    When ``positions`` is given, the causal mask uses absolute positions so
    chunked prefill (later chunks attending into earlier KV) composes; for
    the single-chunk case the default arange mask applies. ``valid`` marks
    padding positions whose keys must never be attended.
    Returns [batch, seq, n_heads, head_dim].
    """
    b, s, n_q, d = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5

    qf = q.astype(jnp.float32).reshape(b, s, n_kv, group, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # [b, n_kv, group, s_q, s_k]
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qf, kf) * scale

    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    mask = positions[:, None, None, :, None] >= positions[:, None, None, None, :]
    if valid is not None:
        mask = mask & valid[:, None, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)

    probs = jax.nn.softmax(scores, axis=-1)
    # A fully-masked query row (padding query) softmaxes to NaN; zero it.
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, vf)
    return out.reshape(b, s, n_q, d).astype(q.dtype)


#: key-block length for the online-softmax prefill scan. 512 keeps the
#: per-block score tile MXU-sized while bounding live memory to
#: O(seq × block) instead of O(seq²).
FLASH_KEY_BLOCK = 512

_NEG_INF = -1e30


def _flash_over_keys(
    qf: jnp.ndarray,  # [b, s, n_kv, group, d] f32
    k_all: jnp.ndarray,  # [b, n_kv, T, d]
    v_all: jnp.ndarray,  # [b, n_kv, T, d]
    k_valid: jnp.ndarray,  # [b, T] bool
    k_pos: jnp.ndarray,  # [b, T] int32 (visibility: k_pos <= q_pos)
    q_pos: jnp.ndarray,  # [b, s] int32
    scale: float,
    block: int,
    return_accumulators: bool = False,
    init_state=None,
) -> jnp.ndarray:
    """Online-softmax (flash) attention over a virtual key sequence, scanned
    in key blocks so the [s, T] score matrix is never materialized — the
    memory shape XLA wants for long-context prefill on TPU (score tile
    [s, block] is reused across scan iterations).

    With ``return_accumulators`` the raw flash state ``(m, l, acc)`` is
    returned instead of the normalized output, and ``init_state`` seeds
    the scan from prior accumulators — together they let a caller chain
    exact partial attentions over disjoint key ranges (the ring-attention
    body scans each rotating payload this way, one blocked flash pass per
    ring step)."""
    b, s, n_kv, group, d = qf.shape
    T = k_all.shape[2]
    # Short key sequences (cache-cold short prompts) shrink the block to a
    # lane-aligned size instead of padding up to a full block of masked work.
    block = min(block, -(-T // 128) * 128)
    n_blocks = -(-T // block)
    pad = n_blocks * block - T
    if pad:
        k_all = jnp.pad(k_all, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_all = jnp.pad(v_all, ((0, 0), (0, 0), (0, pad), (0, 0)))
        k_valid = jnp.pad(k_valid, ((0, 0), (0, pad)))
        k_pos = jnp.pad(k_pos, ((0, 0), (0, pad)))

    kb = k_all.reshape(b, n_kv, n_blocks, block, d).transpose(2, 0, 1, 3, 4)
    vb = v_all.reshape(b, n_kv, n_blocks, block, d).transpose(2, 0, 1, 3, 4)
    valb = k_valid.reshape(b, n_blocks, block).transpose(1, 0, 2)
    posb = k_pos.reshape(b, n_blocks, block).transpose(1, 0, 2)

    if init_state is None:
        m0 = jnp.full((b, n_kv, group, s), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, n_kv, group, s), jnp.float32)
        acc0 = jnp.zeros((b, n_kv, group, s, d), jnp.float32)
    else:
        m0, l0, acc0 = init_state

    def body(carry, blk):
        m, denom, acc = carry
        kblk, vblk, vblk_valid, pblk = blk
        scores = jnp.einsum(
            "bqhgd,bhtd->bhgqt", qf, kblk.astype(jnp.float32)
        ) * scale  # [b, n_kv, g, s, block]
        mask = (
            vblk_valid[:, None, None, None, :]
            & (pblk[:, None, None, None, :] <= q_pos[:, None, None, :, None])
        )
        scores = jnp.where(mask, scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None]) * mask
        corr = jnp.exp(m - m_new)
        denom = denom * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhgqt,bhtd->bhgqd", p, vblk.astype(jnp.float32)
        )
        return (m_new, denom, acc), None

    (m, denom, acc), _ = jax.lax.scan(body, (m0, l0, acc0), (kb, vb, valb, posb))
    if return_accumulators:
        return m, denom, acc
    out = acc / jnp.where(denom > 0, denom, 1.0)[..., None]
    # [b, n_kv, g, s, d] -> [b, s, n_kv, g, d]
    return out.transpose(0, 3, 1, 2, 4)


def prefill_with_paged_context(
    q: jnp.ndarray,  # [batch, seq, n_heads, head_dim] — the fresh chunk
    k: jnp.ndarray,  # [batch, seq, n_kv_heads, head_dim]
    v: jnp.ndarray,  # [batch, seq, n_kv_heads, head_dim]
    k_pages: jnp.ndarray,  # [total_pages, page_size, n_kv_heads, head_dim]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [batch, max_ctx_pages] int32 (pad with 0)
    ctx_lens: jnp.ndarray,  # [batch] int32 — tokens of cached context
    *,
    positions: jnp.ndarray,  # [batch, seq] absolute positions of the chunk
    valid: Optional[jnp.ndarray] = None,  # [batch, seq] padding mask
    scale: Optional[float] = None,
    k_scales: Optional[jnp.ndarray] = None,  # [total_pages, n_kv] f32
    v_scales: Optional[jnp.ndarray] = None,  # (KV_QUANT_HBM: int8 pools)
) -> jnp.ndarray:
    """Chunked prefill attending to prefix-cached pages *and* causally within
    the fresh chunk.

    This is what turns a prefix-cache hit into skipped compute: the shared
    prefix's K/V already live in the page pool (written by whichever request
    computed them — RoPE is absolute so they are position-correct), and the
    request only prefills its suffix. Context tokens all precede the chunk,
    so cross-attention to them needs only the ctx_len mask, not a causal one.

    One online softmax over the virtual key sequence [context ++ chunk],
    flash-scanned in ``FLASH_KEY_BLOCK``-sized key blocks (memory stays
    O(seq × block), enabling multi-k-token prefills). Returns
    [batch, seq, n_heads, head_dim].
    """
    b, s, n_q, d = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5
    max_ctx = block_tables.shape[1] * k_pages.shape[1]

    qf = q.astype(jnp.float32).reshape(b, s, n_kv, group, d)

    # Context keys/values gathered per sequence: [b, n_kv, max_ctx, d].
    ctx_k = k_pages[block_tables]  # [b, max_ctx_pages, ps, n_kv, d]
    ctx_v = v_pages[block_tables]
    if k_scales is not None:
        # KV_QUANT_HBM=int8: pools hold codes; widen the gathered context
        # (chunk-sized, not pool-sized) with the per-page-per-head scales.
        ctx_k = ctx_k.astype(jnp.float32) * (
            k_scales[block_tables][:, :, None, :, None]
        )
        ctx_v = ctx_v.astype(jnp.float32) * (
            v_scales[block_tables][:, :, None, :, None]
        )
        ctx_k = ctx_k.astype(k.dtype)
        ctx_v = ctx_v.astype(v.dtype)
    ctx_k = jnp.moveaxis(ctx_k.reshape(b, max_ctx, n_kv, d), 1, 2)
    ctx_v = jnp.moveaxis(ctx_v.reshape(b, max_ctx, n_kv, d), 1, 2)

    # Virtual key sequence: [context ++ chunk]. Context keys are visible to
    # every query (they strictly precede the chunk): position -1 ≤ any
    # q_pos ≥ 0. Chunk keys follow causal position order.
    k_all = jnp.concatenate([ctx_k, jnp.moveaxis(k, 1, 2)], axis=2)
    v_all = jnp.concatenate([ctx_v, jnp.moveaxis(v, 1, 2)], axis=2)
    ctx_valid = jnp.arange(max_ctx)[None, :] < ctx_lens[:, None]
    chunk_valid = (
        valid if valid is not None else jnp.ones((b, s), bool)
    )
    k_valid = jnp.concatenate([ctx_valid, chunk_valid], axis=1)
    k_pos = jnp.concatenate(
        [jnp.full((b, max_ctx), -1, jnp.int32), positions.astype(jnp.int32)], axis=1
    )

    out = _flash_over_keys(
        qf, k_all, v_all, k_valid, k_pos, positions.astype(jnp.int32),
        scale, FLASH_KEY_BLOCK,
    )
    return out.reshape(b, s, n_q, d).astype(q.dtype)
