"""ZMQ PUB publisher for KV events.

Counterpart of the subscriber: used by the in-tree JAX serving engine's
block manager to announce block stores/evictions, and by demos/tests to
simulate a fleet (reference ``examples/kv_events/offline/publisher.go``).
Publishers **connect** to the subscriber's bound endpoint; each message is
3 frames ``[topic, seq (8B big-endian), msgpack payload]`` with a
monotonically increasing per-publisher sequence number.
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ...utils import get_logger
from .events import Event, EventBatch

log = get_logger("kvcache.kvevents.publisher")


@dataclass
class ZMQPublisherConfig:
    endpoint: str = "tcp://localhost:5557"
    pod_identifier: str = "local-pod"
    model_name: str = "unknown-model"
    # Rank of this publisher in a data-parallel fleet, tagged onto batches.
    data_parallel_rank: Optional[int] = None


class ZMQPublisher:
    def __init__(self, config: ZMQPublisherConfig):
        import zmq

        self.config = config
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.connect(config.endpoint)
        self._seq = 0
        self._mu = threading.Lock()
        self.topic = f"kv@{config.pod_identifier}@{config.model_name}"

    def publish(self, events: list[Event], ts: Optional[float] = None) -> int:
        """Publish one EventBatch; returns the sequence number used."""
        batch = EventBatch(
            ts=ts if ts is not None else time.time(),
            events=events,
            data_parallel_rank=self.config.data_parallel_rank,
        )
        payload = batch.to_payload()
        with self._mu:
            seq = self._seq
            self._seq += 1
            self._sock.send_multipart(
                [self.topic.encode("utf-8"), struct.pack(">Q", seq), payload]
            )
        return seq

    def close(self) -> None:
        self._sock.close(linger=100)
