from . import kvblock  # noqa: F401
from . import transfer  # noqa: F401
from .indexer import KVCacheIndexer, KVCacheIndexerConfig
from .predictor import (
    PodSignals,
    PredictionCorrector,
    TTFTPredictor,
    TTFTPredictorConfig,
)
from .router import (
    BlendedRouter,
    DisaggPlan,
    PlanError,
    PodView,
    PrefixAffinityTracker,
    RoutingDecision,
    TwoHopPlanner,
)
from .scorer import (
    KVBlockScorer,
    KVBlockScorerConfig,
    LongestPrefixScorer,
    ScoringStrategy,
    new_scorer,
)
from .sharding import (
    HashRing,
    ShardedEventsPool,
    ShardedEventsPoolConfig,
    ShardedIndex,
)

__all__ = [
    "BlendedRouter",
    "DisaggPlan",
    "PlanError",
    "PodView",
    "TwoHopPlanner",
    "PrefixAffinityTracker",
    "RoutingDecision",
    "kvblock",
    "transfer",
    "KVCacheIndexer",
    "KVCacheIndexerConfig",
    "PodSignals",
    "PredictionCorrector",
    "TTFTPredictor",
    "TTFTPredictorConfig",
    "KVBlockScorer",
    "KVBlockScorerConfig",
    "LongestPrefixScorer",
    "ScoringStrategy",
    "new_scorer",
    "HashRing",
    "ShardedEventsPool",
    "ShardedEventsPoolConfig",
    "ShardedIndex",
]
