"""llm-d-kv-cache-manager-tpu: TPU-native KV-cache-aware routing framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``llm-d-kv-cache-manager`` (reference: Go library + service providing
KV-cache-aware routing for a vLLM fleet; see /root/reference).

Components:

- ``kvcache``        — block index, token→block hashing, scorer, orchestrator
                       (parity with reference ``pkg/kvcache``).
- ``kvcache.kvevents`` — msgpack/ZMQ KV-event ingestion plane
                       (parity with reference ``pkg/kvcache/kvevents``).
- ``tokenization``   — tokenizer pool + text-prefix→token store
                       (parity with reference ``pkg/tokenization``).
- ``preprocessing``  — chat-completions templating
                       (parity with reference ``pkg/preprocessing``).
- ``server``         — the in-tree JAX paged-KV inference server (new; the
                       reference drives external vLLM pods instead).
- ``models``         — JAX model definitions (Llama-class decoders).
- ``ops``            — TPU compute kernels (Pallas paged attention, etc.).
- ``parallel``       — device-mesh / sharding helpers (tp/dp over ICI/DCN).
- ``native``         — C++ hot-path kernels (CBOR/SHA-256 block hashing).
"""

__version__ = "0.1.0"
