"""Request/sequence state for the serving engine."""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

_id_counter = itertools.count()


class SequenceStatus(Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"


@dataclass
class SamplingParams:
    max_new_tokens: int = 64
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    stop_token_ids: tuple[int, ...] = ()


@dataclass
class Sequence:
    prompt_tokens: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    seq_id: int = field(default_factory=lambda: next(_id_counter))
    request_id: Optional[str] = None

    # engine-managed state
    status: SequenceStatus = SequenceStatus.WAITING
    output_tokens: list[int] = field(default_factory=list)
    block_table: list[int] = field(default_factory=list)
    #: tokens whose K/V are resident in pages (cached prefix + processed)
    num_computed: int = 0
    #: tokens of the prompt served from the prefix cache
    num_cached_prompt: int = 0
    #: prompt tokens whose K/V are resident (cached prefix + prefilled
    #: chunks). Equals num_cached_prompt right after allocation and
    #: len(prompt_tokens) once prefill completes; strictly between the two
    #: while a sequence is mid-prefill under chunked-prefill scheduling.
    num_prefilled: int = 0
    #: total generated tokens — survives preemption (output_tokens may be
    #: folded into prompt_tokens when a sequence is preempted and recomputed)
    num_generated: int = 0
    #: length of the user's original prompt, for reporting after preemption
    user_prompt_len: int = -1
    #: prefix-cache registration bookkeeping (incremental hashing)
    num_registered_pages: int = 0
    last_chain_hash: Optional[int] = None
    arrival_time: float = field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    #: when the first prefill chunk for this sequence dispatched — the
    #: queue→compute boundary the latency decomposition (queue span /
    #: kvcache_request_queue_seconds) is derived from. Always stamped
    #: (one clock read per prefill batch; no behavior change).
    prefill_start_time: Optional[float] = None
    #: the router's verdict that placed this request here ("route_warm" /
    #: "pull" / "cold"), when the serving layer knows it — labels the
    #: latency histograms; None = derived from num_cached_prompt.
    route_action: Optional[str] = None
    #: live ``obs.tracing.Span`` for the request (serving layer owns it;
    #: child queue/prefill/decode spans are reconstructed from the
    #: timestamps above when the request resolves). None = tracing off.
    trace_span: Optional[object] = None
    #: absolute monotonic deadline (``time.monotonic()`` scale). None
    #: (default) = no deadline — bit-identical legacy behavior. An expired
    #: waiting sequence is shed before prefill; an expired running sequence
    #: finishes at the next commit point with ``finish_reason="deadline"``.
    deadline: Optional[float] = None
    #: why the request ended early, when not a normal stop/length finish:
    #: "deadline" (expired) or "abort" (client gone / operator abort).
    #: None = the normal finish reasons apply.
    finish_reason: Optional[str] = None
    #: set when the engine had to abort the request (e.g. unschedulable)
    error: Optional[str] = None
    #: speculative-decode acceptance history (drives the engine's adaptive
    #: per-sequence gate; survives preemption with the sequence)
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: memoized prompt prefix-chain hashes for the host-tier prefetch
    #: stage (hashing is O(prompt) sha256 work; a sequence may wait many
    #: steps). Invalidated when preemption folds output into the prompt.
    prefetch_hashes: Optional[list[int]] = None
    #: async KV-pull (``ASYNC_PULL``): True while a background transfer
    #: fetch is importing this sequence's warm prefix — the scheduler
    #: skips it (admitting later waiting sequences past it) until the
    #: import lands or fails, so a slow wire never stalls admission.
    #: False (default) = legacy behavior, the scheduler never checks it.
    importing: bool = False
    #: when the scheduler FIRST skipped this sequence because its import
    #: was still in flight — the hidden/exposed boundary of the pull
    #: overlap decomposition (pull time before this instant was hidden
    #: behind other work; time after it delayed this sequence's prefill).
    import_wanted_time: Optional[float] = None
    #: OBS_LIFECYCLE reuse-distance MRC: True once this request's prefix
    #: chain has been observed by the estimator. Allocation rollbacks
    #: (scheduler budget overflow) and preemption re-prefills call
    #: ``allocate`` again for the SAME request — re-observing would feed
    #: tiny artificial reuse distances and bias the curve upward, the
    #: same reason ``hit_stats`` snapshots only the first prefill.
    mrc_observed: bool = False
    #: TENANT_QOS slice key this request is charged to ("" = knob off,
    #: no tenant dimension anywhere). Unknown tenants are collapsed onto
    #: the "*" slice by the serving layer before the sequence is built.
    tenant: str = ""
    #: TENANT_QOS priority class (0 = highest). The scheduler orders the
    #: waiting queue by class and preemption only takes pages from a
    #: strictly lower class. 0 for every sequence when the knob is off,
    #: so ordering is a no-op.
    priority: int = 0
    #: TENANT_QOS weighted-fair share within the class (> 0).
    qos_weight: float = 1.0
    #: per-tenant hit-stats bookkeeping: True once this request's first
    #: successful allocation has been counted (same first-prefill-only
    #: rationale as ``mrc_observed``).
    qos_observed: bool = False

    def __post_init__(self):
        if self.user_prompt_len < 0:
            self.user_prompt_len = len(self.prompt_tokens)

    @property
    def all_tokens(self) -> list[int]:
        return self.prompt_tokens + self.output_tokens

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_tokens) + len(self.output_tokens)

    @property
    def generated_tokens(self) -> list[int]:
        """User-visible output, stable across preemption."""
        return self.all_tokens[self.user_prompt_len :]

    @property
    def prompt_remaining(self) -> int:
        """Prompt tokens still to prefill (chunked-prefill progress)."""
        return len(self.prompt_tokens) - self.num_prefilled

    def reset_allocation(self) -> None:
        """Clear all page/prefix-cache bookkeeping (single source of truth
        for rollback and preemption)."""
        self.num_computed = 0
        self.num_cached_prompt = 0
        self.num_prefilled = 0
        self.num_registered_pages = 0
        self.last_chain_hash = None

    def fold_for_preemption(self) -> None:
        """Recompute-preemption: all tokens become the new 'prompt'; the
        re-prefill will cache-hit the pages that survived eviction."""
        self.prompt_tokens = self.all_tokens
        self.output_tokens = []
        self.prefetch_hashes = None  # prompt changed: memo is stale
        self.reset_allocation()
        self.status = SequenceStatus.WAITING

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def mean_itl(self) -> Optional[float]:
        """Mean inter-token latency over the generated tokens; None when
        not measurable (unfinished, or <= 1 generated token). The one
        definition both the latency histograms and the SLO recorder feed
        from — they must never diverge."""
        if (
            self.finish_time is None
            or self.first_token_time is None
            or self.num_generated <= 1
        ):
            return None
        return max(self.finish_time - self.first_token_time, 0.0) / (
            self.num_generated - 1
        )

    def is_finished(self) -> bool:
        return self.status == SequenceStatus.FINISHED
