from .rmsnorm import rms_norm
from .rope import apply_rope, rope_frequencies
from .attention import causal_prefill_attention, prefill_with_paged_context
from .paged_attention import paged_attention, paged_attention_reference
from .sampling import sample_tokens

__all__ = [
    "sample_tokens",
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
    "causal_prefill_attention",
    "prefill_with_paged_context",
    "paged_attention",
    "paged_attention_reference",
]
