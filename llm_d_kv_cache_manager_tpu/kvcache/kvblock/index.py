"""KV-block index: interface + factory.

Parity with reference ``pkg/kvcache/kvblock/index.go``: a pluggable store
that aggregates the global KV-block locality index — which TPU server
replicas hold which blocks, on which memory tier — and answers
longest-prefix lookups for the scorer.

Semantics (mirroring ``in_memory.go:97-141``):

- ``lookup`` walks the ordered key chain. A key that is *present but has no
  pods* terminates the walk (the prefix chain is broken there); a key that is
  simply absent is skipped but the walk continues.
- An empty ``pod_filter`` means "all pods".
- Operations are thread-safe.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .keys import Key, PodEntry


class Index(ABC):
    """Backend that tracks KV-block → pod-locality mappings."""

    @abstractmethod
    def lookup(
        self, keys: Sequence[Key], pod_filter: Optional[set[str]] = None
    ) -> dict[Key, list[str]]:
        """Return pod identifiers per key, filtered to ``pod_filter`` when
        non-empty. Stops scanning at the first present-but-empty key."""

    @abstractmethod
    def add(self, keys: Sequence[Key], entries: Sequence[PodEntry]) -> None:
        """Record that each pod entry holds each key's block."""

    @abstractmethod
    def evict(self, key: Key, entries: Sequence[PodEntry]) -> None:
        """Remove pod entries for a key; drop the key once no pods remain."""

    @abstractmethod
    def evict_pod(self, pod_identifier: str) -> int:
        """Fleet self-healing sweep: remove EVERY entry belonging to
        ``pod_identifier`` (all keys, all tiers, all models), dropping keys
        whose pod set empties. Used by the dead-pod sweeper after TTL
        expiry and by ``IndexSnapshot`` replace-all-for-pod reconciliation.
        Returns the number of entries removed."""

    def size_info(self) -> Optional[dict]:
        """Occupancy snapshot for the ``kvcache_index_blocks`` /
        ``kvcache_index_pods`` gauges: ``{"blocks": <tracked block keys>,
        "pods": <distinct pods with >= 1 entry>}``. May walk the index —
        scrape-driven callers only (``/stats``, ``/metrics``). None when
        the backend cannot answer cheaply (e.g. a remote Redis)."""
        return None

    def pod_names(self) -> Optional[Sequence[str]]:
        """Distinct pod identifiers the backend can enumerate cheaply (for
        the native backend: pods ever interned, a documented superset).
        Lets ``ShardedIndex`` union pods across shards so the aggregate
        ``size_info`` stays truthful. None when enumeration would require
        a remote walk (e.g. Redis) — callers fall back to counts."""
        return None


@dataclass
class InMemoryIndexConfig:
    # Maximum number of block keys tracked (reference default 1e8,
    # in_memory.go:33).
    size: int = 100_000_000
    # Maximum pod entries per key (reference default 10, in_memory.go:34).
    pod_cache_size: int = 10


@dataclass
class CostAwareMemoryIndexConfig:
    # Total budget for estimated entry byte-cost (reference default "2GiB",
    # cost_aware_memory.go:45-49).
    max_cost_bytes: int = 2 * 1024**3


@dataclass
class NativeMemoryIndexConfig:
    """C++ two-level LRU (same semantics as InMemoryIndexConfig); requires
    the native library (``python -m llm_d_kv_cache_manager_tpu.native.build``)."""

    size: int = 100_000_000
    pod_cache_size: int = 10


@dataclass
class RedisIndexConfig:
    # URL form: redis://[user:pass@]host:port/db
    address: str = "redis://localhost:6379"
    # Injected client factory for testing / alternative clients; when None the
    # `redis` package is imported lazily.
    client: object | None = None


@dataclass
class IndexConfig:
    """Picks the first configured backend: native > in-memory > cost-aware >
    redis (extending reference ``index.go:57-97`` with the C++ backend)."""

    native_memory: Optional[NativeMemoryIndexConfig] = None
    in_memory: Optional[InMemoryIndexConfig] = field(default_factory=InMemoryIndexConfig)
    cost_aware: Optional[CostAwareMemoryIndexConfig] = None
    redis: Optional[RedisIndexConfig] = None
    enable_metrics: bool = False
    # Seconds between metrics-beat log lines; 0 disables (requires
    # enable_metrics).
    metrics_logging_interval: float = 0.0


def create_index(config: Optional[IndexConfig] = None) -> Index:
    cfg = config or IndexConfig()

    idx: Index
    if cfg.native_memory is not None:
        from .native_memory import NativeMemoryIndex

        idx = NativeMemoryIndex(cfg.native_memory)
    elif cfg.in_memory is not None:
        from .in_memory import InMemoryIndex

        idx = InMemoryIndex(cfg.in_memory)
    elif cfg.cost_aware is not None:
        from .cost_aware import CostAwareMemoryIndex

        idx = CostAwareMemoryIndex(cfg.cost_aware)
    elif cfg.redis is not None:
        from .redis_index import RedisIndex

        idx = RedisIndex(cfg.redis)
    else:
        raise ValueError("no valid index configuration provided")

    if cfg.enable_metrics:
        from ..metrics import collector
        from .instrumented import InstrumentedIndex

        collector.register()
        idx = InstrumentedIndex(idx)
        if cfg.metrics_logging_interval > 0:
            collector.start_metrics_logging(cfg.metrics_logging_interval)

    return idx
