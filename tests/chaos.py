"""Fault-injection harness for fleet self-healing tests.

The chaos suite's contract (ISSUE 3): after ANY injected fault — dropped
event batches, pod crash, network partition, delayed delivery, dead
transfer peers — the fleet must converge back to truth (index == engine
ground truth after at most one resync) and every degraded path must end in
cold prefill, never an error.

This module provides the injection points:

- ``ChaosLink``: the in-process transport between one pod's publisher and
  the indexer's event pool (the ``PoolPublisher`` idiom from
  ``test_dp_fleet.py``), with the REAL wire contract — msgpack
  ``EventBatch`` payloads and a per-publisher monotone ``seq`` that is
  consumed even for dropped batches, exactly like ``ZMQPublisher`` — plus
  fault controls: drop-next-N, partition/heal, delay-next-N with explicit
  release.
- Ground-truth helpers: ``engine_truth`` (the pod's block digest),
  ``index_view_of_pod`` (what the index believes the pod holds), and
  ``wait_until`` for convergence polling.
"""

from __future__ import annotations

import threading
import time

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import Key
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    BlockStored,
    EventBatch,
    IndexSnapshot,
    Message,
)


class ChaosLink:
    """Publisher → pool transport with fault injection.

    Duck-types enough of ``ZMQPublisher`` for ``PodServer`` injection:
    ``publish(events, ts=None) -> seq``, ``close()``, ``dropped_batches``,
    and a ``config`` carrying ``data_parallel_rank``.
    """

    def __init__(self, pool, pod_identifier, model_name, dp_rank=None):
        self.pool = pool
        self.pod_identifier = pod_identifier
        self.model_name = model_name
        self.config = type(
            "C",
            (),
            {
                "data_parallel_rank": dp_rank,
                "pod_identifier": pod_identifier,
                "model_name": model_name,
            },
        )()
        self.topic = f"kv@{pod_identifier}@{model_name}"
        self._mu = threading.Lock()
        self._seq = 0
        self.dropped_batches = 0
        self._drop_next = 0
        self._partitioned = False
        self._delay_next = 0
        self._held: list[Message] = []
        #: every block hash this link ever carried (incl. in dropped
        #: batches): the universe convergence checks compare over.
        self.seen_hashes: set[int] = set()

    # -- fault controls ------------------------------------------------------
    def drop_next(self, n: int = 1) -> None:
        """Drop the next ``n`` batches (transport loss: seq still consumed,
        as the real publisher does after bounded retries)."""
        with self._mu:
            self._drop_next += n

    def partition(self) -> None:
        """Drop everything until ``heal()`` — a network partition as the
        indexer experiences it."""
        with self._mu:
            self._partitioned = True

    def heal(self) -> None:
        with self._mu:
            self._partitioned = False

    def delay_next(self, n: int = 1) -> None:
        """Hold the next ``n`` messages instead of delivering; they keep
        their seq and deliver (late, possibly out of order relative to
        later traffic) on ``release_held()``."""
        with self._mu:
            self._delay_next += n

    def release_held(self) -> int:
        """Deliver all held messages; returns how many."""
        with self._mu:
            held, self._held = self._held, []
        for msg in held:
            self.pool.add_task(msg)
        return len(held)

    # -- publisher contract --------------------------------------------------
    def publish(self, events, ts=None) -> int:
        batch = EventBatch(
            ts=ts if ts is not None else time.time(),
            events=list(events),
            data_parallel_rank=self.config.data_parallel_rank,
        )
        payload = batch.to_payload()
        for ev in batch.events:
            if isinstance(ev, BlockStored):
                self.seen_hashes.update(int(h) for h in ev.block_hashes)
            elif isinstance(ev, IndexSnapshot):
                for hashes in ev.blocks_by_medium.values():
                    self.seen_hashes.update(int(h) for h in hashes)
        with self._mu:
            seq = self._seq
            self._seq += 1  # consumed even when the batch is lost
            if self._partitioned or self._drop_next > 0:
                if self._drop_next > 0:
                    self._drop_next -= 1
                self.dropped_batches += 1
                return -1
            delay = self._delay_next > 0
            if delay:
                self._delay_next -= 1
        msg = Message(
            topic=self.topic,
            pod_identifier=self.pod_identifier,
            model_name=self.model_name,
            payload=payload,
            seq=seq,
        )
        if delay:
            with self._mu:
                self._held.append(msg)
            return seq
        self.pool.add_task(msg)
        return seq

    def close(self) -> None:
        pass


# -- byte-corruption faults (ISSUE 19) ---------------------------------------
# Bit rot injected at rest or in flight: each helper flips bits in the
# REAL stored representation (host slot arrays, a remote store's
# wire-ready payload, an in-transit ``BlockPayload``), so the integrity
# plane's digests are exercised against exactly the bytes it guards.


def corrupt_host_slot(server_or_engine, chain_hash, byte_index=0) -> bool:
    """Flip one byte of the host-DRAM copy of ``chain_hash`` in place
    (accepts a ``PodServer`` or a bare ``Engine``). Returns False when
    the block is not host-resident. Flushes any pending page moves first
    so the digest of record predates the flip."""
    eng = getattr(server_or_engine, "engine", server_or_engine)
    eng._flush_page_moves()
    bm = eng.block_manager
    slot = bm._host_cached.get(chain_hash)
    if slot is None:
        return False
    flat = eng._host_k[slot].reshape(-1).view("uint8")
    flat[byte_index % flat.size] ^= 0xFF
    return True


def corrupt_remote_block(store, chain_hash, byte_index=0) -> bool:
    """Flip one byte of a remote store's wire-ready copy in place (rot at
    rest on the holder). Returns False when the store has no such
    block."""
    blk = store._blocks.get(chain_hash)
    if blk is None:
        return False
    data = bytearray(blk.k_data)
    data[byte_index % len(data)] ^= 0xFF
    blk.k_data = bytes(data)
    return True


def corrupt_payload(blocks, which=0, byte_index=0):
    """Flip one byte in an in-flight ``BlockPayload`` list (wire frame
    corruption between encode and install) and return the same list."""
    blk = blocks[which]
    data = bytearray(blk.v_data)
    data[byte_index % len(data)] ^= 0xFF
    blk.v_data = bytes(data)
    return blocks


# -- ground truth vs index view ---------------------------------------------
def engine_truth(server) -> set[int]:
    """Every chain hash resident on the pod, across tiers (the digest a
    resync would publish). Reads bookkeeping dicts directly — only call
    when the pod is quiescent (no in-flight requests)."""
    digest = server.engine.block_manager.block_digest()
    return {int(h) for hashes in digest.values() for h in hashes}


def index_view_of_pod(index, model_name, universe, pod) -> set[int]:
    """Subset of ``universe`` the index currently attributes to ``pod``.

    Looks keys up one at a time so a present-but-empty key cannot
    early-stop the scan over an arbitrary (unordered) universe.
    """
    view = set()
    for h in universe:
        key = Key(model_name, int(h))
        got = index.lookup([key], set())
        if pod in got.get(key, []):
            view.add(int(h))
    return view


def wait_until(predicate, timeout=10.0, interval=0.02) -> bool:
    """Poll ``predicate`` until true or timeout; returns the final value."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return bool(predicate())
