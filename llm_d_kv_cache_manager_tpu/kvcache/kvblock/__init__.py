from .keys import Key, PodEntry, DeviceTier, DEFAULT_TIER, tier_for_medium
from .index import (
    Index,
    IndexConfig,
    InMemoryIndexConfig,
    CostAwareMemoryIndexConfig,
    NativeMemoryIndexConfig,
    RedisIndexConfig,
    create_index,
)
from .in_memory import InMemoryIndex
from .cost_aware import CostAwareMemoryIndex
from .instrumented import InstrumentedIndex
from .native_memory import NativeMemoryIndex, native_available
from .token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
    DEFAULT_BLOCK_SIZE,
    hash_block,
    root_hash,
)

__all__ = [
    "Index",
    "IndexConfig",
    "InMemoryIndexConfig",
    "CostAwareMemoryIndexConfig",
    "RedisIndexConfig",
    "create_index",
    "InMemoryIndex",
    "CostAwareMemoryIndex",
    "InstrumentedIndex",
    "NativeMemoryIndexConfig",
    "NativeMemoryIndex",
    "native_available",
    "Key",
    "PodEntry",
    "DeviceTier",
    "DEFAULT_TIER",
    "tier_for_medium",
    "ChunkedTokenDatabase",
    "TokenProcessorConfig",
    "DEFAULT_BLOCK_SIZE",
    "hash_block",
    "root_hash",
]
