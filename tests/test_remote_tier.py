"""Remote-tier suite (ISSUE 13 acceptance): eviction as demotion.

- **Push protocol**: ``PushBlocks``/``PushAck`` round-trips (incl. the
  int8 quant triple), tolerant garbage handling, and the legacy frames'
  byte-for-byte stability (old services answer pushes with an error the
  pusher treats as "plain eviction").
- **Remote store**: validated accept (geometry + chain-hash
  self-consistency; tampered tokens and truncated scale triples register
  nothing), LRU capacity with ``BlockRemoved(remote)`` goodbyes,
  stop-at-first-gap serving.
- **Heartbeat headroom**: trailing-append wire field; role-less,
  headroom-less heartbeat bytes pinned bit-identical legacy; the new
  ``kvstore`` role round-trips and is excluded from EVERY scorer
  placement.
- **Demotion**: both eviction paths (HBM recycle + host-LRU drop) hand
  wire-ready payloads to the sink; knob off = no hook, bit-identical
  behavior; demote→pull-back greedy parity vs never-evicted; imports may
  recycle evictable pages only under the knob.
- **Chaos**: a partitioned demotion target degrades to plain eviction
  (generation completes, pages back to baseline, no stall); a tampered
  push over the real ZMQ fabric is rejected before anything registers.
- **Index semantics**: remote entries are keyed to the HOLDER pod, so
  ``evict_pod`` of the demoter keeps them and of the holder drops them
  (the conformance case lives in test_index_backends.py and runs across
  all five backends + ``ShardedIndex``).
"""

import time

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import (
    EventBatch,
    Heartbeat,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvevents.health import (
    FleetHealth,
    FleetHealthConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.router import (
    BlendedRouter,
    PrefixAffinityTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.transfer import (
    BlockPayload,
    KVTransferClient,
    RemoteBlockStore,
    RemoteStoreConfig,
    TransferClientConfig,
    TransferCostModel,
    TransferCostModelConfig,
    TransferError,
    protocol,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    hash_block,
)
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA, quant
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    Engine,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

PS = 4
MODEL = "tiny-llama"
SHAPE = (TINY_LLAMA.n_layers, PS, TINY_LLAMA.n_kv_heads, TINY_LLAMA.hd)
SCALE_BYTES = int(np.prod(quant.kv_scale_shape(SHAPE))) * 4


def _engine_cfg(total_pages=64, **kw):
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(
            total_pages=total_pages,
            page_size=PS,
            host_pages=kw.pop("host_pages", 0),
        ),
        scheduler=SchedulerConfig(max_prefill_batch=4),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
        **kw,
    )


def _engine(total_pages=64, on_events=None, **kw):
    return Engine(_engine_cfg(total_pages=total_pages, **kw), on_events=on_events)


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _store(capacity=64, init_hash=0, on_events=None, dtype="float32"):
    return RemoteBlockStore(
        RemoteStoreConfig(
            capacity_pages=capacity,
            page_size=PS,
            page_shape=SHAPE,
            dtype=dtype,
            scale_bytes=SCALE_BYTES,
            init_hash=init_hash,
        ),
        on_events=on_events,
    )


def _chain_payloads(init_hash, n=3, seed=0, dtype="float32"):
    """A self-consistent chain of n payload blocks with real hashes."""
    rng = np.random.default_rng(seed)
    parent = None
    out = []
    data = np.zeros(SHAPE, np.dtype(dtype)).tobytes()
    for i in range(n):
        toks = [int(t) for t in rng.integers(0, 1000, PS)]
        h = hash_block(parent if parent is not None else init_hash, toks)
        out.append(
            BlockPayload(
                block_hash=h,
                parent_block_hash=parent,
                token_ids=toks,
                block_size=PS,
                dtype=dtype,
                shape=SHAPE,
                k_data=data,
                v_data=data,
            )
        )
        parent = h
    return out


def _pod_config(pod_id, transfer_endpoint=None, total_pages=64, **kw):
    return PodServerConfig(
        model_name=MODEL,
        pod_identifier=pod_id,
        publish_events=False,
        transfer_endpoint=transfer_endpoint,
        engine=_engine_cfg(total_pages=total_pages),
        **kw,
    )


class TestPushProtocol:
    def test_push_round_trip(self):
        blocks = _chain_payloads(7, n=2)
        enc = protocol.encode_push("m", "pod-src", blocks)
        model, src, got = protocol.decode_push(enc)
        assert model == "m" and src == "pod-src"
        assert [b.block_hash for b in got] == [b.block_hash for b in blocks]
        assert got[0].token_ids == blocks[0].token_ids

    def test_push_round_trip_quant_triple(self):
        b = _chain_payloads(7, n=1)[0]
        b.quant = "int8"
        b.k_data = b.v_data = b"\x01" * int(np.prod(SHAPE))
        b.k_scale = b.v_scale = b"\x00" * SCALE_BYTES
        _, _, got = protocol.decode_push(protocol.encode_push("m", "s", [b]))
        assert got[0].quant == "int8"
        assert len(got[0].k_scale) == SCALE_BYTES

    def test_ack_round_trip(self):
        assert protocol.decode_push_ack(protocol.encode_push_ack(3, 9)) == (
            3,
            9,
            None,
        )

    def test_error_decodes_as_refusal(self):
        acc, hr, err = protocol.decode_push_ack(protocol.encode_error("no"))
        assert (acc, hr) == (0, 0) and err == "no"

    def test_garbage_decodes_to_none(self):
        assert protocol.decode_push(b"\x01\x02") is None
        assert protocol.decode_push_ack(b"\x01\x02") is None

    def test_push_is_not_a_fetch_and_vice_versa(self):
        push = protocol.encode_push("m", "s", _chain_payloads(7, n=1))
        fetch = protocol.encode_request("m", [1, 2])
        assert protocol.decode_request(push) is None
        assert protocol.decode_push(fetch) is None

    def test_legacy_response_bytes_unchanged(self):
        """The block-row refactor (shared by Blocks and PushBlocks) must
        not move a byte of the legacy response wire format."""
        import msgpack

        b = _chain_payloads(7, n=1)[0]
        expect = msgpack.packb(
            [
                "Blocks",
                True,
                [
                    [
                        b.block_hash,
                        b.parent_block_hash,
                        list(b.token_ids),
                        b.block_size,
                        b.dtype,
                        list(b.shape),
                        b.k_data,
                        b.v_data,
                    ]
                ],
            ],
            use_bin_type=True,
        )
        assert protocol.encode_response([b], True) == expect


class TestRemoteStore:
    def test_accept_and_serve_round_trip(self):
        store = _store(init_hash=7)
        chain = _chain_payloads(7, n=3)
        assert store.accept(chain) == 3
        hashes = [b.block_hash for b in chain]
        assert [b.block_hash for b in store.serve(hashes)] == hashes
        assert store.stats["accepted"] == 3 and store.stats["served"] == 3

    def test_serve_stops_at_first_gap(self):
        store = _store(init_hash=7)
        chain = _chain_payloads(7, n=3)
        store.accept([chain[0], chain[2]])  # hole at block 1
        hashes = [b.block_hash for b in chain]
        assert [b.block_hash for b in store.serve(hashes)] == [hashes[0]]

    def test_tampered_tokens_rejected(self):
        store = _store(init_hash=7)
        b = _chain_payloads(7, n=1)[0]
        b.token_ids = list(b.token_ids)
        b.token_ids[0] ^= 1
        assert store.accept([b]) == 0
        assert store.stats["rejected"] == 1 and len(store) == 0

    def test_truncated_scale_triple_rejected(self):
        store = _store(init_hash=7)
        b = _chain_payloads(7, n=1)[0]
        b.quant = "int8"
        b.k_data = b.v_data = b"\x01" * int(np.prod(SHAPE))
        b.k_scale = b"\x00" * (SCALE_BYTES - 4)  # truncated
        b.v_scale = b"\x00" * SCALE_BYTES
        assert store.accept([b]) == 0
        assert store.stats["rejected"] == 1

    def test_wrong_geometry_rejected(self):
        store = _store(init_hash=7)
        b = _chain_payloads(7, n=1)[0]
        b.block_size = PS * 2
        assert store.accept([b]) == 0

    def test_lru_capacity_with_remote_goodbyes(self):
        events = []
        store = _store(capacity=2, init_hash=7, on_events=events.extend)
        chain = _chain_payloads(7, n=3)
        assert store.accept(chain) == 3
        assert len(store) == 2 and store.stats["evicted"] == 1
        assert store.headroom == 0
        stored = [e for e in events if type(e).__name__ == "BlockStored"]
        removed = [e for e in events if type(e).__name__ == "BlockRemoved"]
        assert len(stored) == 3 and len(removed) == 1
        assert all(e.medium == "remote" for e in stored + removed)
        assert removed[0].block_hashes == [chain[0].block_hash]

    def test_duplicate_accept_refreshes_recency(self):
        store = _store(capacity=2, init_hash=7)
        chain = _chain_payloads(7, n=2)
        store.accept(chain)
        store.accept([chain[0]])  # touch block 0 to MRU
        extra = _chain_payloads(7, n=1, seed=9)
        store.accept(extra)  # evicts block 1, not block 0
        assert chain[0].block_hash in store
        assert chain[1].block_hash not in store

    def test_zero_capacity_accepts_nothing(self):
        store = _store(capacity=0, init_hash=7)
        assert store.accept(_chain_payloads(7, n=1)) == 0


class TestHeartbeatHeadroom:
    def test_legacy_heartbeat_bytes_pinned(self):
        import msgpack

        payload = EventBatch(ts=1.5, events=[Heartbeat(dropped_batches=5)])
        assert payload.to_payload() == msgpack.packb(
            [1.5, [["Heartbeat", 5]]], use_bin_type=True
        )

    def test_role_heartbeat_bytes_pinned(self):
        import msgpack

        payload = EventBatch(
            ts=0.0, events=[Heartbeat(0, role="prefill")]
        ).to_payload()
        assert payload == msgpack.packb(
            [0.0, [["Heartbeat", 0, False, "prefill"]]], use_bin_type=True
        )

    def test_headroom_round_trip_roleless(self):
        hb = decode_event_batch(
            EventBatch(ts=0.0, events=[Heartbeat(1, headroom=42)]).to_payload()
        ).events[0]
        assert hb.headroom == 42
        assert hb.role is None  # the "mixed" filler decodes back to None
        assert hb.draining is False

    def test_headroom_round_trip_kvstore_role(self):
        hb = decode_event_batch(
            EventBatch(
                ts=0.0, events=[Heartbeat(0, role="kvstore", headroom=7)]
            ).to_payload()
        ).events[0]
        assert hb.role == "kvstore" and hb.headroom == 7

    def test_bad_headroom_tolerated(self):
        import msgpack

        raw = msgpack.packb(
            [0.0, [["Heartbeat", 0, False, "mixed", "junk"]]],
            use_bin_type=True,
        )
        hb = decode_event_batch(raw).events[0]
        assert hb.headroom is None and hb.role is None


class TestHealthKvstore:
    def test_kvstore_excluded_from_every_placement(self):
        fh = FleetHealth(FleetHealthConfig())
        fh.observe_heartbeat("kv-0", 0, role="kvstore", headroom=9)
        scores = {"kv-0": 10, "pod-a": 2}
        for placement in (None, "prefill", "decode"):
            out = fh.filter_scores(dict(scores), placement)
            assert "kv-0" not in out and out["pod-a"] == 2, placement

    def test_pull_source_placement_keeps_kvstore_scorable(self):
        """The remote read path: a FleetHealth-wired scorer must answer a
        holder-only query (the serving filter rightly blanks kvstore pods
        from every OTHER placement) — without this the bring-back arm
        could never fire in a production-wired fleet."""
        fh = FleetHealth(FleetHealthConfig())
        fh.observe_heartbeat("kv-0", 0, role="kvstore", headroom=9)
        scores = {"kv-0": 10}
        assert fh.filter_scores(dict(scores), "pull_source") == scores
        # Liveness still gates pull sources: a drained holder's bytes are
        # gone, pulling from it would just burn the timeout.
        fh.observe_drained("kv-0")
        assert fh.filter_scores(dict(scores), "pull_source") == {}

    def test_roleblind_fast_path_without_kvstore(self):
        fh = FleetHealth(FleetHealthConfig())
        fh.observe_heartbeat("pod-a", 0, role="prefill")
        scores = {"pod-a": 3, "pod-b": 1}
        # placement=None stays role-blind on kvstore-less fleets (prefill
        # pods remain eligible — the legacy contract).
        assert fh.filter_scores(dict(scores), None) == scores

    def test_headroom_tracking_and_targets(self):
        fh = FleetHealth(FleetHealthConfig())
        fh.observe_heartbeat("kv-0", 0, role="kvstore", headroom=16)
        fh.observe_heartbeat("pod-a", 0, headroom=4)
        fh.observe_heartbeat("pod-b", 0)  # never advertised
        assert fh.headroom_of("kv-0") == 16
        assert fh.headroom_of("pod-b") is None
        assert fh.remote_targets() == {"kv-0": 16, "pod-a": 4}
        # A draining pod stops being a target.
        fh.observe_heartbeat("pod-a", 0, draining=True, headroom=4)
        assert "pod-a" not in fh.remote_targets()

    def test_headroom_absence_keeps_last_value(self):
        fh = FleetHealth(FleetHealthConfig())
        fh.observe_heartbeat("pod-a", 0, headroom=8)
        fh.observe_heartbeat("pod-a", 0)  # legacy heartbeat, no field
        assert fh.headroom_of("pod-a") == 8


class TestCostModelRemote:
    def _model(self, **kw):
        return TransferCostModel(
            TransferCostModelConfig(block_bytes=1000, block_size=PS, **kw)
        )

    def test_abstains_until_rates_measured(self):
        m = self._model()
        assert m.decide_remote(100, 8, 0.0) == "route_warm"
        m.seed_rates(transfer_bytes_s=1e9)
        assert m.decide_remote(100, 8, 0.0) == "route_warm"

    def test_pull_beats_recompute_on_fast_link(self):
        m = self._model()
        m.seed_rates(transfer_bytes_s=1e9, prefill_tokens_s=100.0)
        assert m.decide_remote(33, 8, target_load=0.0) == "pull"

    def test_slow_link_falls_back_to_recompute(self):
        m = self._model()
        m.seed_rates(transfer_bytes_s=100.0, prefill_tokens_s=1e6)
        assert m.decide_remote(33, 8, target_load=0.0) == "route_warm"

    def test_warm_local_hit_wins(self):
        m = self._model()
        m.seed_rates(transfer_bytes_s=1e9, prefill_tokens_s=100.0)
        # Local pod already holds the whole usable prefix: nothing to move.
        assert (
            m.decide_remote(33, 8, target_load=0.0, warm_blocks=8, warm_load=0.0)
            == "route_warm"
        )


class TestRouterRemoteArm:
    def _router(self, scores, remote, loads=(0.0, 0.0), cost_model=None):
        return BlendedRouter(
            score_fn=lambda toks, pods: dict(scores),
            affinity=PrefixAffinityTracker(2, capacity_blocks=64),
            loads_fn=lambda pods: list(loads),
            cost_model=cost_model,
            remote_score_fn=(lambda toks: dict(remote)) if remote is not None else None,
            remote_endpoint_of=lambda p: f"tcp://{p}:5558",
        )

    def _cm(self):
        m = TransferCostModel(
            TransferCostModelConfig(block_bytes=1000, block_size=PS)
        )
        m.seed_rates(transfer_bytes_s=1e9, prefill_tokens_s=100.0)
        return m

    def test_remote_pull_fires_on_cold_fleet(self):
        r = self._router({"p0": 0, "p1": 0}, {"kv-0": 9}, cost_model=self._cm())
        d = r.route(list(range(40)), ["p0", "p1"])
        assert d.action == "pull"
        assert d.pull_source == "tcp://kv-0:5558" and d.pull_blocks == 9

    def test_local_warmth_dominates_equal_remote(self):
        r = self._router({"p0": 9, "p1": 0}, {"kv-0": 9}, cost_model=self._cm())
        d = r.route(list(range(40)), ["p0", "p1"])
        assert d.action == "route_warm" and d.pod == "p0"

    def test_no_cost_model_keeps_legacy(self):
        r = self._router({"p0": 0, "p1": 0}, {"kv-0": 9}, cost_model=None)
        d = r.route(list(range(40)), ["p0", "p1"])
        assert d.action == "route_warm" and d.pull_source is None

    def test_no_remote_fn_is_legacy(self):
        r = BlendedRouter(
            score_fn=lambda toks, pods: {"p0": 0, "p1": 0},
            affinity=PrefixAffinityTracker(2, capacity_blocks=64),
            loads_fn=lambda pods: [0.0, 0.0],
            cost_model=self._cm(),
        )
        d = r.route(list(range(40)), ["p0", "p1"])
        assert d.action == "route_warm"


class TestDemotionEngine:
    def test_knob_off_no_hook(self):
        eng = _engine(total_pages=12)
        assert eng.block_manager._demote is None
        assert eng.remote_store is None and eng.remote_headroom is None

    def test_hbm_eviction_demotes_last_copy(self):
        eng = _engine(total_pages=12, remote_tier=True)
        payloads = []
        eng.on_demotion = payloads.extend
        for i in range(4):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        assert payloads and eng.remote_stats["demoted_blocks"] == len(payloads)
        # Every payload is self-consistent: a fresh store accepts it all.
        store = _store(
            capacity=256, init_hash=eng.block_manager.token_db.init_hash
        )
        assert store.accept(payloads) == len(
            {b.block_hash for b in payloads}
        )
        assert store.stats["rejected"] == 0

    def test_no_sink_means_plain_eviction(self):
        eng = _engine(total_pages=12, remote_tier=True)  # on_demotion unset
        for i in range(4):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        assert eng.remote_stats["demoted_blocks"] == 0

    def test_outputs_identical_knob_on_vs_off(self):
        outs = {}
        for remote in (False, True):
            eng = _engine(total_pages=12, remote_tier=remote)
            eng.on_demotion = lambda ps: None
            got = []
            for i in range(4):
                seq = eng.add_request(
                    _prompt(i, 16), SamplingParams(max_new_tokens=4)
                )
                eng.run_until_complete()
                got.append(list(seq.generated_tokens))
            outs[remote] = got
        assert outs[False] == outs[True]

    def test_host_lru_drop_demotes(self):
        # Tiny host tier: spills land there, then host-LRU drops demote.
        eng = _engine(
            total_pages=12,
            remote_tier=True,
            host_pages=2,
            host_tier_policy="always",
        )
        payloads = []
        eng.on_demotion = payloads.extend
        for i in range(5):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        assert eng.block_manager.host_stats["host_evicted"] > 0
        assert payloads
        store = _store(
            capacity=256, init_hash=eng.block_manager.token_db.init_hash
        )
        store.accept(payloads)
        assert store.stats["rejected"] == 0

    def test_int8_demotion_ships_quant_triple(self):
        eng = _engine(total_pages=12, remote_tier=True, kv_quant="int8")
        payloads = []
        eng.on_demotion = payloads.extend
        for i in range(4):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        assert payloads
        assert all(b.quant == "int8" for b in payloads)
        assert all(len(b.k_scale) == SCALE_BYTES for b in payloads)
        # Quantized payloads are ~half the wire bytes of full fp32 pages.
        full = 2 * int(np.prod(SHAPE)) * 4
        assert all(b.wire_bytes < full * 0.6 for b in payloads)
        store = _store(
            capacity=256, init_hash=eng.block_manager.token_db.init_hash
        )
        store.accept(payloads)
        assert store.stats["rejected"] == 0

    def test_demote_pull_back_greedy_parity(self):
        """The round trip the tier exists for: evict→demote→store→pull
        back→serve warm, token-identical to a never-evicted engine."""
        base = _engine(total_pages=64)
        want = {}
        for i in range(5):
            seq = base.add_request(
                _prompt(i, 16), SamplingParams(max_new_tokens=4)
            )
            base.run_until_complete()
            want[i] = list(seq.generated_tokens)

        eng = _engine(total_pages=12, remote_tier=True)
        store = _store(
            capacity=256, init_hash=eng.block_manager.token_db.init_hash
        )
        eng.on_demotion = store.accept
        for i in range(5):
            seq = eng.add_request(
                _prompt(i, 16), SamplingParams(max_new_tokens=4)
            )
            eng.run_until_complete()
            assert list(seq.generated_tokens) == want[i]
        # Prompt 0's chain is long gone locally; bring it back.
        hashes = eng.block_manager.token_db.prefix_hashes(_prompt(0, 16))
        assert not any(eng.block_manager.is_block_resident(h) for h in hashes)
        served = store.serve(hashes)
        assert served
        assert eng.import_kv_blocks(served) == len(served)
        seq = eng.add_request(_prompt(0, 16), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        assert seq.num_cached_prompt >= PS
        assert list(seq.generated_tokens) == want[0]

    def test_import_recycles_evictable_only_with_knob(self):
        """allow_evict rides the remote_tier knob: the same full-pool
        import installs under the knob (victims demote) and stops without
        it (the PR 2 never-evict contract, unchanged)."""
        chain = None
        for remote in (True, False):
            eng = _engine(total_pages=12, remote_tier=remote)
            eng.on_demotion = lambda ps: None
            # Fill the pool with evictable warmth, leaving no free pages.
            for i in range(4):
                eng.add_request(
                    _prompt(i, 16), SamplingParams(max_new_tokens=4)
                )
                eng.run_until_complete()
            free = len(eng.block_manager._free)
            if chain is None:
                donor = _engine(total_pages=64)
                donor.add_request(
                    _prompt(99, 16), SamplingParams(max_new_tokens=1)
                )
                donor.run_until_complete()
                hashes = donor.block_manager.token_db.prefix_hashes(
                    _prompt(99, 16)
                )
                chain = donor.export_kv_blocks(hashes)
                assert chain
            assert free < len(chain), "pool not saturated enough to test"
            installed = eng.import_kv_blocks(list(chain))
            if remote:
                assert installed == len(chain)  # recycled evictable pages
            else:
                assert installed == free  # stopped at the free-page wall

    def test_remote_store_serves_exports_and_digest(self):
        events = []
        eng = _engine(
            total_pages=32,
            remote_tier=True,
            remote_store_pages=16,
            on_events=events.append,
        )
        chain = _chain_payloads(
            eng.block_manager.token_db.init_hash, n=3, seed=3
        )
        accepted, headroom = eng.accept_remote_blocks("peer", chain)
        assert accepted == 3 and headroom == 13
        assert eng.remote_headroom == 13
        # The holder's own BlockStored(remote) events flushed immediately.
        flat = [e for batch in events for e in batch]
        stored = [e for e in flat if type(e).__name__ == "BlockStored"]
        assert stored and all(e.medium == "remote" for e in stored)
        # Digest grows the remote medium (resync keeps demoted entries).
        digest = eng.block_digest()
        assert set(digest["remote"]) == {b.block_hash for b in chain}
        # The export path serves the store's blocks (pull-back read path).
        hashes = [b.block_hash for b in chain]
        out = eng.export_kv_blocks(hashes)
        assert [b.block_hash for b in out] == hashes


class TestPushOverZMQ:
    def _wait(self, cond, timeout=20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.05)
        return False

    def test_demote_push_pull_back_over_fabric(self):
        from conftest import free_tcp_port

        endpoint = f"tcp://127.0.0.1:{free_tcp_port()}"
        holder = PodServer(
            _pod_config(
                "kv-holder",
                transfer_endpoint=endpoint,
                pod_role="kvstore",
                remote_tier=True,
                remote_store_pages=128,
            )
        )
        demoter = PodServer(
            _pod_config(
                "demoter",
                total_pages=12,
                remote_tier=True,
                remote_peers=endpoint,
            )
        )
        holder.start()
        demoter.start()
        try:
            outs = {}
            for i in range(5):
                seq = demoter.generate(
                    _prompt(i, 16),
                    SamplingParams(max_new_tokens=4),
                    timeout=60,
                )
                outs[i] = list(seq.generated_tokens)
            assert self._wait(
                lambda: holder.engine.remote_store is not None
                and len(holder.engine.remote_store) > 0
            ), "demotions never reached the holder"
            # kvstore pods never serve requests.
            with pytest.raises(ValueError):
                holder.submit(_prompt(0, 16))
            # Pull the demoted chain back over the same fabric and serve
            # prompt 0 warm with identical tokens.
            hashes = demoter.engine.block_manager.token_db.prefix_hashes(
                _prompt(0, 16)
            )
            self._wait(
                lambda: any(
                    h in holder.engine.remote_store for h in hashes[:1]
                )
            )
            if any(h in holder.engine.remote_store for h in hashes[:1]):
                n = demoter.pull_prefix(_prompt(0, 16), endpoint)
                assert n >= 1
            seq = demoter.generate(
                _prompt(0, 16), SamplingParams(max_new_tokens=4), timeout=60
            )
            assert list(seq.generated_tokens) == outs[0]
        finally:
            demoter.shutdown()
            holder.shutdown()

    def test_tampered_push_rejected_over_wire(self):
        from conftest import free_tcp_port

        endpoint = f"tcp://127.0.0.1:{free_tcp_port()}"
        holder = PodServer(
            _pod_config(
                "kv-holder2",
                transfer_endpoint=endpoint,
                remote_tier=True,
                remote_store_pages=16,
            )
        )
        holder.start()
        client = KVTransferClient(
            TransferClientConfig(endpoint=endpoint, timeout_s=5.0)
        )
        try:
            init = holder.engine.block_manager.token_db.init_hash
            good = _chain_payloads(init, n=1, seed=1)[0]
            bad = _chain_payloads(init, n=1, seed=2)[0]
            bad.token_ids = list(bad.token_ids)
            bad.token_ids[0] ^= 1  # breaks the chain-hash check
            trunc = _chain_payloads(init, n=1, seed=3)[0]
            trunc.quant = "int8"
            trunc.k_data = trunc.v_data = b"\x01" * int(np.prod(SHAPE))
            trunc.k_scale = b"\x00" * (SCALE_BYTES - 4)
            trunc.v_scale = b"\x00" * SCALE_BYTES
            accepted, headroom = client.push_blocks(
                MODEL, "attacker", [good, bad, trunc]
            )
            assert accepted == 1 and headroom == 15
            store = holder.engine.remote_store
            assert good.block_hash in store
            assert bad.block_hash not in store
            assert trunc.block_hash not in store
            assert store.stats["rejected"] == 2
        finally:
            client.close()
            holder.shutdown()

    def test_push_to_legacy_service_refused(self):
        from conftest import free_tcp_port

        endpoint = f"tcp://127.0.0.1:{free_tcp_port()}"
        pod = PodServer(_pod_config("plain", transfer_endpoint=endpoint))
        pod.start()
        client = KVTransferClient(
            TransferClientConfig(endpoint=endpoint, timeout_s=5.0)
        )
        try:
            init = pod.engine.block_manager.token_db.init_hash
            with pytest.raises(TransferError, match="push unsupported"):
                client.push_blocks(MODEL, "src", _chain_payloads(init, n=1))
        finally:
            client.close()
            pod.shutdown()


class TestDemotionTargets:
    def test_zero_headroom_peer_ranks_last_but_stays_a_target(self):
        """A full holder still accepts by LRU rotation; the first
        headroom=0 ack must not turn demotion off for the process
        lifetime."""
        pod = PodServer(
            _pod_config(
                "ranker",
                remote_tier=True,
                remote_peers="tcp://a:1,tcp://b:2",
            )
        )
        try:
            with pod._mu:
                pod._peer_headroom["tcp://a:1"] = 0  # acked full
                pod._peer_headroom["tcp://b:2"] = 5
            assert pod._demotion_targets() == ["tcp://b:2", "tcp://a:1"]
            with pod._mu:
                pod._peer_headroom["tcp://b:2"] = 0
            # Every holder full: demotion still targets them (LRU
            # rotation on the holder side), never silently stops.
            assert pod._demotion_targets() == ["tcp://a:1", "tcp://b:2"]
        finally:
            pod.shutdown()

    def test_full_store_accepts_by_rotation(self):
        store = _store(capacity=2, init_hash=7)
        store.accept(_chain_payloads(7, n=2))
        assert store.headroom == 0
        fresh = _chain_payloads(7, n=2, seed=5)
        assert store.accept(fresh) == 2  # rotated, not refused
        assert fresh[1].block_hash in store


class TestDemotionChaos:
    def test_partitioned_target_degrades_to_plain_eviction(self):
        """A dead/unreachable demotion target must cost bounded timeouts,
        never a stalled engine: generation completes, pages return to
        baseline, the failures are counted."""
        from conftest import free_tcp_port

        dead = f"tcp://127.0.0.1:{free_tcp_port()}"  # nothing listens
        pod = PodServer(
            _pod_config(
                "lonely",
                total_pages=12,
                remote_tier=True,
                remote_peers=dead,
                transfer_timeout_s=0.3,
                transfer_breaker_failures=1,
            )
        )
        pod.start()
        baseline = pod.engine.block_manager.num_free
        try:
            t0 = time.monotonic()
            for i in range(4):
                seq = pod.generate(
                    _prompt(i, 16),
                    SamplingParams(max_new_tokens=4),
                    timeout=60,
                )
                assert seq.num_generated == 4
            assert time.monotonic() - t0 < 45
            # Pusher drains its queue into failures (plain eviction).
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                with pod._mu:
                    if (
                        not pod._demote_queue
                        and pod.demote_failed_blocks > 0
                    ):
                        break
                time.sleep(0.05)
            assert pod.demote_failed_blocks > 0
            assert pod.demote_pushed_blocks == 0
            assert pod.engine.block_manager.num_free == baseline
        finally:
            pod.shutdown()


class TestKnobsOffParity:
    def test_defaults_off(self, monkeypatch):
        for var in (
            "REMOTE_TIER",
            "REMOTE_STORE_PAGES",
            "REMOTE_PEERS",
            "REMOTE_DEMOTE_QUEUE",
        ):
            monkeypatch.delenv(var, raising=False)
        cfg = PodServerConfig.from_env()
        assert cfg.remote_tier is False
        assert cfg.remote_store_pages == 0
        assert cfg.remote_peers == ""
        assert cfg.engine.remote_tier is False
        assert EngineConfig.__dataclass_fields__["remote_tier"].default is False

    def test_stats_payload_has_no_remote_block(self):
        pod = PodServer(_pod_config("legacy"))
        pod.start()
        try:
            import asyncio

            from aiohttp.test_utils import TestClient, TestServer

            async def go():
                client = TestClient(TestServer(pod.build_app()))
                await client.start_server()
                try:
                    resp = await client.get("/stats")
                    return await resp.json()
                finally:
                    await client.close()

            payload = asyncio.new_event_loop().run_until_complete(go())
            assert "remote" not in payload
            assert set(payload["transfer"].keys()) == {
                "exported_blocks",
                "imported_blocks",
                "import_rejected",
                "endpoint",
                "pulls",
                "pull_failures",
                "breaker_skips",
                "breakers",
                "requests_served",
            }
        finally:
            pod.shutdown()

    def test_stats_remote_block_gated_on(self):
        pod = PodServer(
            _pod_config("rt", remote_tier=True, remote_store_pages=8)
        )
        pod.start()
        try:
            import asyncio

            from aiohttp.test_utils import TestClient, TestServer

            async def go():
                client = TestClient(TestServer(pod.build_app()))
                await client.start_server()
                try:
                    resp = await client.get("/stats")
                    return await resp.json()
                finally:
                    await client.close()

            payload = asyncio.new_event_loop().run_until_complete(go())
            assert payload["remote"]["store_pages"] == 8
            assert payload["remote"]["headroom"] == 8
        finally:
            pod.shutdown()

    def test_remote_tier_entries_keyed_to_holder_in_index(self):
        """End-to-end event-plane check: the HOLDER publishes the remote
        BlockStored, so evicting the DEMOTER keeps the entry and evicting
        the holder drops it (the death semantics the tier depends on)."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
            InMemoryIndexConfig,
            Key,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
            KVEventsPool,
            KVEventsPoolConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import (
            BlockStored,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents.pool import Message

        index = InMemoryIndex(InMemoryIndexConfig())
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        try:
            batch = EventBatch(
                ts=0.0,
                events=[
                    BlockStored(
                        block_hashes=[11],
                        token_ids=list(range(PS)),
                        block_size=PS,
                        medium="remote",
                    )
                ],
            )
            pool.add_task(
                Message(
                    topic="kv@kv-holder@m",
                    pod_identifier="kv-holder",
                    model_name="m",
                    payload=batch.to_payload(),
                    seq=0,
                )
            )
            assert pool.drain(5)
            key = Key("m", 11)
            assert index.lookup([key], set())[key] == ["kv-holder"]
            # The demoter dying is irrelevant to the holder's entry...
            index.evict_pod("demoter")
            assert index.lookup([key], set())[key] == ["kv-holder"]
            # ...the holder dying drops it.
            index.evict_pod("kv-holder")
            assert index.lookup([key], set()).get(key, []) == []
        finally:
            pool.shutdown()
