"""Fleet smoke: pod-server subprocess + scoring service, full wire protocol.

The executable analogue of the reference's cluster smoke script
(``tests/kind-vllm-cpu.sh``) without needing a cluster: a real pod server
(tiny model, Pallas interpreter mode, real ZMQ PUB) serves a completion over
HTTP; its BlockStored events cross a TCP ZMQ hop into the scoring service's
SUB-bound subscriber; the indexer then scores the pod for the same prompt —
the complete closed loop every deployment relies on.

Run (CPU is fine):
    JAX_PLATFORMS=cpu python examples/fleet_demo.py
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCORE_PORT = int(os.environ.get("DEMO_SCORE_PORT", 8287))
POD_PORT = int(os.environ.get("DEMO_POD_PORT", 8288))
ZMQ_PORT = int(os.environ.get("DEMO_ZMQ_PORT", 5701))
MODEL = "tiny-llama"
PROMPT = ("the quick brown fox jumps over the lazy dog; pack my box with " + "x" * 64)[:64]


def post(url, payload, timeout=300):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(), {"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from aiohttp import web

    from llm_d_kv_cache_manager_tpu.server.api import ScoringService, ServiceConfig
    from llm_d_kv_cache_manager_tpu.tokenization import Tokenizer

    class CharTokenizer(Tokenizer):
        def encode(self, prompt, model_name):
            return [ord(c) for c in prompt], [(i, i + 1) for i in range(len(prompt))]

    svc = ScoringService(
        ServiceConfig(block_size=16, zmq_endpoint=f"tcp://*:{ZMQ_PORT}"),
        tokenizer=CharTokenizer(),
    )
    svc.start()

    # Serve the scoring app on a dedicated thread so this (main) thread's
    # blocking HTTP calls cannot deadlock it.
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()

    async def _serve():
        runner = web.AppRunner(svc.build_app())
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", SCORE_PORT).start()
        return runner

    runner = asyncio.run_coroutine_threadsafe(_serve(), loop).result(timeout=30)
    print(f"[demo] scoring service on :{SCORE_PORT}, events SUB on :{ZMQ_PORT}")

    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "MODEL_NAME": MODEL,
        "POD_IDENTIFIER": "tpu-pod-A",
        "ZMQ_ENDPOINT": f"tcp://localhost:{ZMQ_PORT}",
        "BLOCK_SIZE": "16",
        "TOTAL_PAGES": "128",
        "MAX_MODEL_LEN": "128",
        "DECODE_BATCH_SIZE": "4",
        "HTTP_PORT": str(POD_PORT),
        "INTERPRET": "1",
    }
    # Child output goes to a file, not a pipe: an undrained pipe fills at
    # ~64KB of chatty logging and blocks the child mid-write.
    import tempfile

    pod_log = tempfile.NamedTemporaryFile(
        prefix="fleet-demo-pod-", suffix=".log", delete=False
    )
    pod = subprocess.Popen(
        [sys.executable, "-m", "llm_d_kv_cache_manager_tpu.server.serve"],
        cwd=REPO,
        env=env,
        stdout=pod_log,
        stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 120
        while True:
            try:
                assert get(f"http://127.0.0.1:{POD_PORT}/healthz")["status"] == "ok"
                break
            except Exception:
                if pod.poll() is not None:
                    print(open(pod_log.name).read())
                    raise RuntimeError("pod server died during startup")
                if time.time() > deadline:
                    raise RuntimeError("pod server never became healthy")
                time.sleep(0.5)
        print("[demo] pod server healthy")
        time.sleep(1.5)  # ZMQ slow-joiner: let the SUB see the PUB

        ids = [ord(c) for c in PROMPT]
        out = post(
            f"http://127.0.0.1:{POD_PORT}/v1/completions",
            {"prompt_token_ids": ids, "max_tokens": 4},
        )
        assert len(out["choices"][0]["token_ids"]) == 4, out
        print(f"[demo] completion ok: ttft={out['ttft_s']:.3f}s")

        expect = len(PROMPT) // 16
        deadline = time.time() + 30
        scores = {}
        while time.time() < deadline:
            scores = post(
                f"http://127.0.0.1:{SCORE_PORT}/score_completions",
                {"prompt": PROMPT, "model": MODEL},
                timeout=30,
            )["scores"]
            if scores.get("tpu-pod-A", 0) >= expect:
                break
            time.sleep(0.3)
        assert scores.get("tpu-pod-A", 0) >= expect, f"scores never warmed: {scores}"
        print(f"[demo] routing scores after serving: {scores}")
        print("[demo] PASSED")
        return 0
    finally:
        pod.send_signal(signal.SIGTERM)
        try:
            pod.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pod.kill()
        asyncio.run_coroutine_threadsafe(runner.cleanup(), loop).result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        svc.shutdown()


if __name__ == "__main__":
    sys.exit(main())
