"""Prefix-store interface (reference ``pkg/tokenization/prefixstore/indexer.go:39-48``)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Sequence

#: (low, high) byte offsets of a token within the original prompt string.
Offset = tuple[int, int]


@dataclass
class Config:
    # Maximum number of blocks per model cache (reference lru_store.go:33).
    cache_size: int = 500_000
    # Prompt bytes per block (reference lru_store.go:31).
    block_size: int = 256
    # Trie-store node budget per model (ContainedTokenStore only; one node
    # per prompt character, so this is a character — not block — capacity).
    # ~1M nodes is a comparable memory footprint to the LRU defaults above.
    trie_max_nodes: int = 1_000_000


class Indexer(ABC):
    """Caches text-prefix → tokens so repeated shared prefixes skip the
    tokenizer."""

    @abstractmethod
    def add_tokenization(
        self,
        model_name: str,
        prompt: str,
        tokens: Sequence[int],
        offsets: Sequence[Offset],
    ) -> None:
        """Record the full tokenization of ``prompt``. ``offsets`` are byte
        offsets into the UTF-8 encoding of ``prompt``, parallel to
        ``tokens``."""

    @abstractmethod
    def find_longest_contained_tokens(
        self, prompt: str, model_name: str
    ) -> tuple[list[int], float]:
        """Return (tokens, covered-byte ratio) for the longest cached prefix
        of ``prompt``."""
