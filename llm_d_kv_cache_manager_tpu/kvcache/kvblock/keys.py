"""Core KV-block identity types.

Parity with reference ``pkg/kvcache/kvblock/index.go:128-144`` (``Key``,
``PodEntry``), retargeted to a TPU fleet: device tiers are
``{tpu_hbm, host_dram}`` instead of the reference's hardcoded ``"gpu"``
(``pkg/kvcache/kvevents/pool.go:247``).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class DeviceTier(str, Enum):
    """Where a KV block physically lives on a TPU host."""

    TPU_HBM = "tpu_hbm"
    HOST_DRAM = "host_dram"
    # Remote/offloaded tier reserved for cross-host block migration.
    REMOTE = "remote"

    def __str__(self) -> str:  # noqa: D105
        return self.value


#: Default tier recorded for events that carry no ``Medium`` field.
DEFAULT_TIER = DeviceTier.TPU_HBM

#: Mapping from event ``Medium`` strings to tiers. The serving engine tags
#: events with these strings; unknown mediums fall back to DEFAULT_TIER.
MEDIUM_TO_TIER = {
    "": DEFAULT_TIER,
    "tpu_hbm": DeviceTier.TPU_HBM,
    "hbm": DeviceTier.TPU_HBM,
    "gpu": DeviceTier.TPU_HBM,  # reference engines tag accelerator memory "gpu"
    "host_dram": DeviceTier.HOST_DRAM,
    "cpu": DeviceTier.HOST_DRAM,
    "remote": DeviceTier.REMOTE,
}


def tier_for_medium(medium: str | None) -> DeviceTier:
    """Absent medium → default tier; *unknown* medium fails safe to the
    slowest local tier so the scorer never overstates locality."""
    if medium is None:
        return DEFAULT_TIER
    return MEDIUM_TO_TIER.get(medium.lower(), DeviceTier.HOST_DRAM)


@dataclass(frozen=True, slots=True)
class Key:
    """Identity of one KV block: (model, chunk hash).

    ``chunk_hash`` is the uint64 chained sha256-CBOR prefix hash of the block
    (see ``token_processor.py``).
    """

    model_name: str
    chunk_hash: int  # uint64

    def __str__(self) -> str:
        return f"{self.model_name}@{self.chunk_hash}"


@dataclass(frozen=True, slots=True)
class PodEntry:
    """One locality record: which pod (TPU server replica) holds the block,
    and on which memory tier."""

    pod_identifier: str
    device_tier: DeviceTier = DEFAULT_TIER

    def __str__(self) -> str:
        return f"{self.pod_identifier}@{self.device_tier}"
