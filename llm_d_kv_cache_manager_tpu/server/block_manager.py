"""Paged KV block allocator with prefix caching and KV-event emission.

The TPU-side counterpart of what vLLM's block manager does for the reference
ecosystem, designed so the routing indexer can track this engine's cache:

- Pages are fixed-size (``page_size`` tokens). Page 0 is reserved as the
  padding target for block tables (the decode kernel requires valid ids in
  padded slots) and never allocated.
- **Prefix caching**: a page holding a *full* block of tokens is registered
  under its chained sha256-CBOR block hash — computed by the same
  ``ChunkedTokenDatabase`` the indexer uses, so engine-emitted event hashes
  and indexer read-path hashes are identical by construction (the reference
  needed deployment-time seed alignment instead,
  ``token_processor.go:37-40``).
- Cached pages are ref-counted; freed pages with a hash go to an LRU of
  evictable pages and are only recycled (and their ``BlockRemoved`` emitted)
  when the free pool runs dry.
- Every transition emits KV events through ``on_events``:
  ``BlockStored`` when a full page is registered, ``BlockRemoved`` when an
  evictable page is recycled — the engine forwards them to the ZMQ
  publisher (write path of SURVEY §3.2).

The allocator is *width-agnostic*: it tracks page identity, hashes, and
tier membership (HBM / host DRAM / remote) but never touches page bytes,
so the same lifecycle drives full-width bf16 pools and the int8 pools of
``KV_QUANT_HBM`` — storage width is the engine's concern (its movers ship
codes + scales between tiers; see ``Engine._flush_page_moves``).
"""

from __future__ import annotations

import bisect
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional, Sequence as Seq

from ..kvcache.kvblock import ChunkedTokenDatabase, TokenProcessorConfig
from ..kvcache.kvevents.events import BadBlock, BlockRemoved, BlockStored, Event
from ..utils import get_logger
from .sequence import Sequence

log = get_logger("server.block_manager")


class AllocationError(RuntimeError):
    """Raised when the pool cannot satisfy an allocation even after evicting."""


@dataclass
class BlockManagerConfig:
    total_pages: int = 1024
    page_size: int = 16
    hash_seed: str = ""
    # Emit one BlockStored per batch of freshly-filled pages.
    emit_events: bool = True
    #: host-DRAM offload tier capacity in pages (0 = disabled). Evicted
    #: HBM pages spill here instead of vanishing; prefix hits restore them.
    host_pages: int = 0


@dataclass
class _PageInfo:
    ref_count: int = 0
    chain_hash: Optional[int] = None
    #: token ids of the full block (kept for BlockStored events)
    token_ids: tuple[int, ...] = ()
    parent_hash: Optional[int] = None
    #: TENANT_QOS slice the allocating sequence was charged to ("" =
    #: knob off, or untenanted work like imports). Rides with the block
    #: across tiers so host-cached pages stay attributed.
    tenant: str = ""


class BlockManager:
    def __init__(
        self,
        config: BlockManagerConfig,
        on_events: Optional[Callable[[list[Event]], None]] = None,
    ):
        if config.total_pages < 2:
            raise ValueError("total_pages must be >= 2 (page 0 is reserved)")
        self.config = config
        self.token_db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=config.page_size, hash_seed=config.hash_seed)
        )
        self.on_events = on_events
        # page id -> info, for allocated pages only
        self._pages: dict[int, _PageInfo] = {}
        self._free: list[int] = list(range(config.total_pages - 1, 0, -1))  # pop() -> 1,2,..
        # chain_hash -> page id (live cached pages, referenced or evictable)
        self._cached: dict[int, int] = {}
        # evictable cached pages (ref_count == 0), LRU order
        self._evictable: OrderedDict[int, None] = OrderedDict()  # page ids
        self._pending_events: list[Event] = []
        # -- host-DRAM tier (SURVEY §2.3 device-tier mapping) --------------
        # The engine attaches the actual KV movers via attach_host_pool();
        # this class only does the tiering bookkeeping.
        self._copy_out = None  # (device_page, host_slot) -> None
        self._copy_in = None  # (host_slot, device_page) -> None
        self._restore_policy = None  # (n_pages) -> bool; None = always
        #: remote-tier demotion hook (REMOTE_TIER): called when an
        #: eviction is about to destroy the LAST local copy of a block —
        #: ``(info, tier, idx)`` with tier "tpu_hbm" (idx = device page,
        #: contents intact until the next dispatch) or "host_dram" (idx =
        #: host slot, caller must snapshot NOW — the slot is reused
        #: immediately). None (default) = plain eviction, bit-identical
        #: legacy behavior.
        self._demote = None
        #: KV-capacity observability (OBS_LIFECYCLE, obs/lifecycle.py):
        #: ``_lifecycle`` records each cached block's tier transitions,
        #: ``_mrc`` samples reuse distances off the allocate-time prefix
        #: walk. Both None (default) = no extra work on any path.
        self._lifecycle = None
        self._mrc = None
        # -- TENANT_QOS (attach_qos; all None/empty = knob off, every
        # path below is bit-identical legacy). Engine-thread-only state,
        # like the page pool itself.
        self._qos = None
        #: tenant slice charged for allocations in flight (set at the top
        #: of allocate/append_slot/reserve_slots from the sequence).
        self._alloc_tenant = ""
        #: evictable HBM pages currently charged per tenant slice — the
        #: numerator of the cache_share cap.
        self._tenant_evictable: dict[str, int] = {}
        #: lazily-built per-tenant reuse-distance estimators (the /debug/
        #: mrc tenant slices); factory installed only when OBS_LIFECYCLE
        #: is also on.
        self._tenant_mrc_factory = None
        self._tenant_mrc: dict = {}
        #: per-tenant first-prefill hit accounting (requests /
        #: prompt_tokens / cached_tokens / capped_evictions), for /stats.
        self.tenant_stats: dict[str, dict[str, int]] = {}
        #: KV_INTEGRITY plane (attach_integrity; both None = knob off,
        #: every path below is bit-identical legacy). ``_integrity`` is
        #: the digest side table, ``_host_verify(slot, h, reason)`` the
        #: engine's host-slot digest check.
        self._integrity = None
        self._host_verify = None
        #: rotating scrub position (last host slot verified by the
        #: background scrubber; engine-thread-only like the pools)
        self._scrub_cursor = -1
        self._host_free: list[int] = list(range(config.host_pages - 1, -1, -1))
        self._host_cached: dict[int, int] = {}  # chain_hash -> host slot
        self._host_info: dict[int, _PageInfo] = {}  # host slot -> metadata
        self._host_lru: OrderedDict[int, None] = OrderedDict()  # host slots
        #: host-tier accounting (monotone; /stats + kvcache_host_* feed):
        #: spilled/restored = device↔host page moves, prefetched = the
        #: subset of restores issued AHEAD of allocate by the prefetch
        #: stage, host_evicted = host-LRU drops, spill_declined = spills
        #: the recompute-vs-restore cost model refused.
        self.host_stats = {
            "spilled": 0,
            "restored": 0,
            "prefetched": 0,
            "host_evicted": 0,
            "spill_declined": 0,
        }

    def attach_host_pool(self, copy_out, copy_in, restore_policy=None) -> None:
        """Install the engine's device↔host page movers, enabling the
        host-DRAM offload tier (``config.host_pages`` > 0).

        ``restore_policy(n_pages) -> bool``, when given, is the
        recompute-vs-restore cost model: consulted once per contiguous
        host-cached run during ``allocate``, it answers whether restoring
        ``n_pages`` beats recomputing their tokens (the engine answers
        from online-measured restore/prefill rates). ``None`` keeps the
        always-restore behavior."""
        self._copy_out = copy_out
        self._copy_in = copy_in
        self._restore_policy = restore_policy

    def attach_demoter(self, demote_fn) -> None:
        """Install the engine's remote-tier demotion hook (``REMOTE_TIER``
        knob): ``demote_fn(info, tier, idx)`` fires whenever eviction
        would destroy the last local copy of a cached block, BEFORE the
        ``BlockRemoved`` is emitted. The hook only queues (the engine
        batches payload builds with the page-move flush); it must never
        block or raise."""
        self._demote = demote_fn

    def attach_lifecycle(self, ledger=None, mrc=None) -> None:
        """Attach the ``OBS_LIFECYCLE`` instruments (obs/lifecycle.py):
        ``ledger`` (a ``BlockLifecycleLedger``) records tier transitions
        at every allocate/spill/restore/prefetch/demote/import/evict;
        ``mrc`` (a ``ReuseDistanceEstimator``) observes the full
        prefix-hash chain of every ``allocate`` lookup. Either may be
        None; unattached (the default) no path here changes."""
        self._lifecycle = ledger
        self._mrc = mrc

    def attach_qos(self, qos, mrc_factory=None) -> None:
        """Attach the TENANT_QOS policy (``server/qos.py``): evictable
        pages are charged to the allocating tenant, tenants over their
        ``cache_share`` recycle their OWN LRU page instead of other
        tenants' warm prefixes, and — when ``mrc_factory`` is given
        (OBS_LIFECYCLE also on) — each tenant slice feeds its own
        reuse-distance estimator for /debug/mrc."""
        self._qos = qos
        self._tenant_mrc_factory = mrc_factory

    def attach_integrity(self, integrity, host_verify) -> None:
        """Attach the ``KV_INTEGRITY`` plane (``kvcache/integrity.py``):
        ``integrity`` is the content-digest side table; ``host_verify(slot,
        h, reason) -> bool`` is the engine's check — it recomputes the
        digest over the host-tier arrays for ``slot``, records the outcome
        (``reason`` maps to the metric's path label), quarantines on
        mismatch, and returns False only for a CORRUPT copy (unverified
        passes — absence of evidence never truncates a chain). On a False
        return this class runs the recovery choreography: free the slot,
        emit ``BlockRemoved`` + ``BadBlock``, and let the caller's chain
        walk break — cold recompute IS the recovery. Unattached (the
        default) no path here changes."""
        self._integrity = integrity
        self._host_verify = host_verify

    def _quarantine_host_slot(self, slot: int, info: _PageInfo) -> None:
        """Destroy a host-tier copy that failed its digest check (the
        caller already removed the slot from cached/info/lru maps — or is
        about to; this finishes the choreography): the slot returns to the
        free list, the ledger records the quarantine, and the fleet learns
        via ``BlockRemoved`` (index entry) + ``BadBlock`` (revocation +
        replica purge). Deliberately NOT counted as ``host_evicted`` —
        that stat means capacity pressure, and a corruption storm must not
        masquerade as one."""
        h = info.chain_hash
        self._host_free.append(slot)
        self._record_lifecycle(h, "none", "quarantine", tenant=info.tenant)
        self._emit(BlockRemoved(block_hashes=[h], medium="host_dram"))
        self._emit(BadBlock(block_hashes=[h], medium="host_dram"))
        log.warning(
            "host KV copy failed digest check; quarantined",
            block=h,
            slot=slot,
        )

    def quarantine_host_block(self, h) -> bool:
        """Remove block ``h``'s host-tier copy through the quarantine
        choreography (engine loop only). Returns True when a copy was
        resident and has been destroyed; False when the host tier holds
        no copy (nothing to do)."""
        slot = self._host_cached.pop(h, None)
        if slot is None:
            return False
        info = self._host_info.pop(slot)
        self._host_lru.pop(slot, None)
        self._quarantine_host_slot(slot, info)
        return True

    def scrub_host_tier(self, max_pages: int) -> int:
        """Background integrity scrub: verify up to ``max_pages`` resident
        host-tier slots against their write-time digests, rotating through
        the tier across calls so every slot is eventually covered. Corrupt
        copies get the full quarantine choreography (slot freed,
        ``BlockRemoved`` + ``BadBlock`` emitted). Returns slots checked.
        Caller must be the engine loop (page-pool ownership rule)."""
        if self._host_verify is None or max_pages <= 0:
            return 0
        slots = sorted(self._host_info)
        if not slots:
            return 0
        start = bisect.bisect_right(slots, self._scrub_cursor)
        order = slots[start:] + slots[:start]
        checked = 0
        for slot in order[: max(max_pages, 0)]:
            info = self._host_info.get(slot)
            if info is None:
                continue
            self._scrub_cursor = slot
            checked += 1
            if not self._host_verify(slot, info.chain_hash, "scrub"):
                self.quarantine_host_block(info.chain_hash)
        if checked and self._integrity is not None:
            self._integrity.note_scrubbed(checked)
        return checked

    def _record_lifecycle(
        self, chain_hash, tier: str, reason: str, tenant: str = ""
    ) -> None:
        if self._lifecycle is not None and chain_hash is not None:
            self._lifecycle.record(chain_hash, tier, reason, tenant=tenant)

    def _evict_count(self, info: _PageInfo, delta: int) -> None:
        """Maintain the per-tenant evictable-page counts (no-op with the
        QoS knob off, and for untenanted pages)."""
        if self._qos is None or not info.tenant:
            return
        n = self._tenant_evictable.get(info.tenant, 0) + delta
        if n > 0:
            self._tenant_evictable[info.tenant] = n
        else:
            self._tenant_evictable.pop(info.tenant, None)

    def _qos_evict_victim(self) -> Optional[int]:
        """Cache-share cap (TENANT_QOS): when the allocating tenant's
        evictable pages already meet its configured share of the pool,
        the recycle victim is that tenant's own LRU evictable page — its
        churn cannot evict another tenant's hot prefix. Under the cap
        (or uncapped, or untenanted) returns None: global LRU applies."""
        t = self._alloc_tenant
        if not t:
            return None
        cap = self._qos.cache_cap_pages(t, self.config.total_pages - 1)
        if cap is None or self._tenant_evictable.get(t, 0) < cap:
            return None
        for page in self._evictable:  # LRU order
            if self._pages[page].tenant == t:
                st = self.tenant_stats.get(t)
                if st is not None:
                    st["capped_evictions"] += 1
                return page
        return None

    @property
    def num_host_cached_pages(self) -> int:
        return len(self._host_cached)

    def _host_alloc_slot(self) -> Optional[int]:
        """Free host slot, evicting the LRU host-cached page if needed.
        Returns None when every slot is in flight (e.g. the single slot is
        mid-restore) — the caller then simply skips the spill."""
        if self._host_free:
            return self._host_free.pop()
        if not self._host_lru:
            return None
        slot, _ = self._host_lru.popitem(last=False)
        info = self._host_info.pop(slot)
        del self._host_cached[info.chain_hash]
        self.host_stats["host_evicted"] += 1
        if self._demote is not None:
            # Host-LRU drop destroys the only copy (tiers are disjoint:
            # a host-cached block is never simultaneously HBM-cached) —
            # demote it instead of losing it. The hook snapshots the slot
            # NOW; the caller reuses it immediately after.
            self._demote(info, "host_dram", slot)
            self._record_lifecycle(
                info.chain_hash, "remote", "demote", tenant=info.tenant
            )
        else:
            if self._integrity is not None:
                # Plain capacity eviction destroys the stored bytes the
                # digest described; the demote path instead hands the
                # entry's fate to the engine's payload build (which
                # verifies against it before shipping).
                self._integrity.drop(info.chain_hash)
            self._record_lifecycle(
                info.chain_hash, "none", "evict", tenant=info.tenant
            )
        self._emit(BlockRemoved(block_hashes=[info.chain_hash], medium="host_dram"))
        return slot

    def _try_offload(self, page: int, info: _PageInfo) -> None:
        """Spill an HBM page being recycled into the host-DRAM tier."""
        if (
            self._copy_out is None
            or self.config.host_pages == 0
            or info.chain_hash in self._host_cached
        ):
            return
        # A spill only ever pays off as a later restore; when the cost
        # model says restoring loses to recompute on this link, the
        # device→host copy is pure waste — skip it (measured: under
        # thrash, ungated spills alone collapse throughput ~15× on the
        # dev tunnel even with every restore declined, results/
        # tiering.md round 5). Optimistic until both rates have samples,
        # so the model can bootstrap from real early spills+restores.
        if self._restore_policy is not None and not self._restore_policy(1):
            self.host_stats["spill_declined"] += 1
            return
        slot = self._host_alloc_slot()
        if slot is None:
            return
        self.host_stats["spilled"] += 1
        self._copy_out(page, slot)
        self._host_cached[info.chain_hash] = slot
        self._host_info[slot] = info
        self._host_lru[slot] = None
        self._record_lifecycle(
            info.chain_hash, "host_dram", "spill", tenant=info.tenant
        )
        self._emit(
            BlockStored(
                block_hashes=[info.chain_hash],
                parent_block_hash=info.parent_hash,
                token_ids=list(info.token_ids),
                block_size=self.config.page_size,
                medium="host_dram",
            )
        )

    # -- introspection ------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._evictable)

    @property
    def num_cached_pages(self) -> int:
        return len(self._cached)

    # -- event plumbing -----------------------------------------------------
    def _emit(self, ev: Event) -> None:
        if self.config.emit_events:
            self._pending_events.append(ev)

    def flush_events(self) -> list[Event]:
        """Drain pending events (engine calls once per step and publishes)."""
        evs, self._pending_events = self._pending_events, []
        if evs and self.on_events is not None:
            self.on_events(evs)
        return evs

    # -- low-level page ops -------------------------------------------------
    def _pop_free_page(self) -> int:
        if self._free:
            page = self._free.pop()
            self._pages[page] = _PageInfo(ref_count=1, tenant=self._alloc_tenant)
            return page
        # Recycle the least-recently-used evictable cached page, spilling
        # it to the host-DRAM tier first when one is attached. With
        # TENANT_QOS cache-share caps, an over-cap tenant recycles its
        # own LRU page instead (see _qos_evict_victim).
        if self._evictable:
            page = self._qos_evict_victim() if self._qos is not None else None
            if page is None:
                page, _ = self._evictable.popitem(last=False)
            else:
                del self._evictable[page]
            info = self._pages[page]
            assert info.ref_count == 0 and info.chain_hash is not None
            self._evict_count(info, -1)
            del self._cached[info.chain_hash]
            self._try_offload(page, info)
            if info.chain_hash not in self._host_cached:
                if self._demote is not None:
                    # The host tier didn't keep a copy (absent, full, or
                    # the cost model declined the spill): this recycle
                    # destroys the last local copy — demote over the
                    # fabric instead. The hook queues a snapshot of the
                    # page, whose contents stay intact until the next
                    # device dispatch (the same window the host-tier
                    # offload gather relies on).
                    self._demote(info, "tpu_hbm", page)
                    self._record_lifecycle(
                        info.chain_hash, "remote", "demote", tenant=info.tenant
                    )
                else:
                    self._record_lifecycle(
                        info.chain_hash, "none", "evict", tenant=info.tenant
                    )
            self._emit(BlockRemoved(block_hashes=[info.chain_hash], medium="tpu_hbm"))
            self._pages[page] = _PageInfo(ref_count=1, tenant=self._alloc_tenant)
            return page
        raise AllocationError("KV page pool exhausted")

    def _incref(self, page: int) -> None:
        info = self._pages[page]
        if info.ref_count == 0 and page in self._evictable:
            del self._evictable[page]
            self._evict_count(info, -1)
        info.ref_count += 1

    def _decref(self, page: int) -> None:
        info = self._pages[page]
        info.ref_count -= 1
        assert info.ref_count >= 0
        if info.ref_count == 0:
            if info.chain_hash is not None:
                # Stays cached & evictable: warm for future prefix hits.
                self._evictable[page] = None
                self._evictable.move_to_end(page)
                self._evict_count(info, +1)
            else:
                del self._pages[page]
                self._free.append(page)

    def _try_restore(self, h: int, reason: str = "restore") -> Optional[int]:
        """Swap a host-DRAM-cached block back into an HBM page (prefix hit
        on the offload tier). Returns the device page, or None.
        ``reason`` labels the lifecycle record: "restore" (blocking, from
        allocate) or "prefetch" (ahead of the scheduler)."""
        slot = self._host_cached.get(h)
        if slot is None or self._copy_in is None:
            return None
        # Claim the slot before _pop_free_page: recycling an HBM page can
        # itself offload into the host tier and evict the host LRU — which
        # must never be the very slot being restored.
        del self._host_cached[h]
        info = self._host_info.pop(slot)
        self._host_lru.pop(slot, None)
        if self._host_verify is not None and not self._host_verify(
            slot, h, reason
        ):
            # Corrupt host copy caught BEFORE any byte reaches HBM: the
            # chain walk breaks here (the caller sees a plain miss) and
            # the suffix recomputes cold — greedy decode stays
            # token-identical because the recompute writes fresh correct
            # pages under the same hashes.
            self._quarantine_host_slot(slot, info)
            return None
        try:
            page = self._pop_free_page()
        except AllocationError:
            # No HBM page available: put the block back in the host tier
            # untouched (freeing the slot here would drop the KV copy while
            # the index still believes this replica holds it).
            self._host_cached[h] = slot
            self._host_info[slot] = info
            self._host_lru[slot] = None
            return None
        self._copy_in(slot, page)
        self._host_free.append(slot)
        if self._integrity is not None:
            # The digest described the host-slot representation, which is
            # gone (HBM is trusted); a later re-spill re-records.
            self._integrity.drop(h)
        self.host_stats["restored"] += 1
        info.ref_count = 0
        self._pages[page] = info
        self._cached[h] = page
        self._evictable[page] = None  # ref 0 until the caller increfs
        self._evict_count(info, +1)
        self._record_lifecycle(h, "tpu_hbm", reason, tenant=info.tenant)
        self._emit(BlockRemoved(block_hashes=[h], medium="host_dram"))
        self._emit(
            BlockStored(
                block_hashes=[h],
                parent_block_hash=info.parent_hash,
                token_ids=list(info.token_ids),
                block_size=self.config.page_size,
                medium="tpu_hbm",
            )
        )
        return page

    def prefetch_chain(self, hashes: Seq[int], max_pages: int) -> int:
        """Bring host-cached blocks of a prefix chain back into HBM AHEAD
        of allocate (the prefetch stage): walks ``hashes`` like ``allocate``
        does, restoring up to ``max_pages`` host hits into ref-0 evictable
        HBM pages so the device↔host copies overlap the current step and
        the later ``allocate`` sees plain warm pages. HBM-resident chain
        pages are touched to MRU while walking — a prefetch must never
        recycle an earlier page of the very chain it is warming. Restores
        respect the recompute-vs-restore cost model with the same
        run-at-a-time consultation as ``allocate`` (a declined run stops
        the walk: allocate will stop there too). Returns pages restored."""
        restored = 0
        restore_until = -1
        for i, h in enumerate(hashes):
            page = self._cached.get(h)
            if page is not None:
                if page in self._evictable:
                    self._evictable.move_to_end(page)
                continue
            if h not in self._host_cached:
                break
            if restored >= max_pages:
                break
            if self._restore_policy is not None and i > restore_until:
                run = 0
                while (
                    i + run < len(hashes)
                    and hashes[i + run] in self._host_cached
                ):
                    run += 1
                if not self._restore_policy(run):
                    break
                restore_until = i + run - 1
            if self._try_restore(h, reason="prefetch") is None:
                break  # no HBM page available: stop, allocate will block
            restored += 1
        if restored:
            self.host_stats["prefetched"] += restored
        return restored

    # -- fleet self-healing (kvcache/kvevents resync) -----------------------
    def block_digest(self) -> dict[str, list[int]]:
        """Resync digest: every chain hash currently resident, per tier —
        the ground truth an ``IndexSnapshot`` replaces the indexer's view
        with. Caller must be the engine loop (page-pool ownership rule)."""
        return {
            "tpu_hbm": list(self._cached.keys()),
            "host_dram": list(self._host_cached.keys()),
        }

    def hot_chains(self, limit: int) -> list[list[int]]:
        """The longest HBM-resident prefix chains, in chain (root→leaf)
        order — the donor-side warm sets fleet scale-up revival pulls onto
        a new pod. A chain is read leaf-back via ``parent_hash`` links and
        truncated at the first non-resident ancestor (the export path's
        consecutive-run rule would stop there anyway). Caller must be the
        engine loop (page-pool ownership rule)."""
        if limit <= 0:
            return []
        parents = {
            self._pages[p].parent_hash
            for p in self._cached.values()
            if self._pages[p].parent_hash is not None
        }
        chains: list[list[int]] = []
        for h, page in self._cached.items():
            if h in parents:
                continue  # interior block; its leaf's walk covers it
            chain: list[int] = []
            cur: Optional[int] = h
            while cur is not None:
                p = self._cached.get(cur)
                if p is None:
                    break  # ancestor evicted: the resident run starts here
                chain.append(cur)
                cur = self._pages[p].parent_hash
            chain.reverse()
            chains.append(chain)
        chains.sort(key=len, reverse=True)
        return chains[:limit]

    # -- cross-pod transfer (kvcache/transfer) ------------------------------
    def is_block_resident(self, h: int) -> bool:
        """True when ``h`` lives in either tier (HBM page or host slot)."""
        return h in self._cached or h in self._host_cached

    def lookup_chain(
        self, hashes: Seq[int], max_blocks: Optional[int] = None
    ) -> list[tuple[int, _PageInfo, str, int]]:
        """Export read path: walk a chained-hash prefix and return the
        longest consecutive resident run as ``(hash, info, tier, idx)``
        tuples — tier ``"tpu_hbm"`` (idx = device page) or ``"host_dram"``
        (idx = host slot). Stops at the first non-resident hash: a block
        behind a chain gap can never serve a prefix hit on the importer,
        so shipping it would be pure waste."""
        out: list[tuple[int, _PageInfo, str, int]] = []
        walk = hashes if max_blocks is None else hashes[:max_blocks]
        for h in walk:
            page = self._cached.get(h)
            if page is not None:
                out.append((h, self._pages[page], "tpu_hbm", page))
                continue
            slot = self._host_cached.get(h)
            if slot is not None:
                out.append((h, self._host_info[slot], "host_dram", slot))
                continue
            break
        return out

    def install_imported_block(
        self,
        h: int,
        parent_hash: Optional[int],
        token_ids: Seq[int],
        allow_evict: bool = False,
    ) -> Optional[int]:
        """Commit a transferred block as a prefix-cache page: allocate a
        page, register it under ``h`` (ref 0, evictable — imports are
        warmth, not work-in-flight) and emit ``BlockStored`` so the global
        index learns this replica now holds the block. Returns the device
        page the caller must write the KV bytes into, or ``None`` when the
        block is already resident in some tier (nothing to do).

        By default only genuinely FREE pages are used — an import never
        evicts locally-warm pages (raises ``AllocationError`` instead):
        evicting proven-warm state for speculative remote warmth would let
        a pull storm thrash the very cache the transfer plane exists to
        protect. ``allow_evict=True`` (the ``REMOTE_TIER`` import path)
        relaxes this to the normal eviction ladder: with a demoter
        attached, the recycled victim spills to host or demotes over the
        fabric, so making room for routed-for warmth is LOSSLESS — the
        original rationale no longer applies. Imported pages land at the
        evictable MRU end, so a multi-block import never recycles its own
        chain."""
        if self.is_block_resident(h):
            return None
        if self._free:
            page = self._free.pop()
        elif allow_evict:
            # Imports are fleet warmth, not tenant work: never charge
            # them to (or cap them by) whatever tenant allocated last.
            self._alloc_tenant = ""
            page = self._pop_free_page()  # recycles LRU; victim spills/demotes
        else:
            raise AllocationError("no free pages for imported KV block")
        info = _PageInfo(
            ref_count=0,
            chain_hash=h,
            token_ids=tuple(int(t) for t in token_ids),
            parent_hash=parent_hash,
        )
        self._pages[page] = info
        self._cached[h] = page
        self._evictable[page] = None
        self._evictable.move_to_end(page)
        self._evict_count(info, +1)
        self._record_lifecycle(h, "tpu_hbm", "import")
        self._emit(
            BlockStored(
                block_hashes=[h],
                parent_block_hash=parent_hash,
                token_ids=list(info.token_ids),
                block_size=self.config.page_size,
                medium="tpu_hbm",
            )
        )
        return page

    # -- sequence lifecycle -------------------------------------------------
    def allocate(self, seq: Sequence) -> int:
        """Allocate pages for a sequence's prompt, reusing prefix-cached
        pages. Sets ``seq.block_table`` / ``seq.num_cached_prompt``; returns
        the number of prompt tokens served from cache."""
        assert not seq.block_table, "sequence already allocated"
        self._alloc_tenant = seq.tenant
        tokens = seq.prompt_tokens
        ps = self.config.page_size
        hashes = self.token_db.prefix_hashes(tokens)
        observe_tenant = (
            self._tenant_mrc_factory is not None and bool(seq.tenant)
        )
        if (self._mrc is not None or observe_tenant) and not seq.mrc_observed:
            # The MRC's access stream: every full block this lookup walks
            # — hits AND misses (the misses register below and become
            # future reuse), in chain order. Once per REQUEST, not per
            # allocate call: rollback retries and preemption re-prefills
            # re-walk the same chain, and double-observing it would feed
            # tiny artificial reuse distances (the hit_stats
            # first-prefill-only rule, applied to the curve). The tenant
            # slices (TENANT_QOS + OBS_LIFECYCLE) see the same stream,
            # restricted to their own requests.
            seq.mrc_observed = True
            if self._mrc is not None:
                self._mrc.observe_chain(hashes)
            if observe_tenant:
                est = self._tenant_mrc.get(seq.tenant)
                if est is None:
                    est = self._tenant_mrc[seq.tenant] = self._tenant_mrc_factory()
                est.observe_chain(hashes)

        block_table: list[int] = []
        cached_tokens = 0
        restore_until = -1  # hash index below which restores are approved
        for i, h in enumerate(hashes):
            page = self._cached.get(h)
            if (
                page is None
                and self._restore_policy is not None
                and i > restore_until
                and h in self._host_cached
            ):
                # First touch of a contiguous host-cached run: consult the
                # recompute-vs-restore cost model ONCE for the whole run.
                # (Modeled per-run, not per-prompt: declining only forces
                # recompute of these blocks — allocate stops here either
                # way, so anything beyond the run is recomputed regardless.)
                run = 0
                while (
                    i + run < len(hashes)
                    and hashes[i + run] in self._host_cached
                ):
                    run += 1
                if not self._restore_policy(run):
                    break  # cheaper to recompute than to DMA the run in
                restore_until = i + run - 1
            if page is None:
                page = self._try_restore(h)
            if page is None:
                break
            self._incref(page)
            block_table.append(page)
            cached_tokens += ps
        # Never serve the *entire* prompt from cache: the engine needs at
        # least one fresh position to produce first-token logits.
        if cached_tokens >= len(tokens) and block_table:
            page = block_table.pop()
            self._decref(page)
            cached_tokens -= ps

        n_pages_needed = -(-len(tokens) // ps)
        try:
            while len(block_table) < n_pages_needed:
                block_table.append(self._pop_free_page())
        except AllocationError:
            for page in block_table:
                self._decref(page)
            raise

        seq.block_table = block_table
        seq.num_cached_prompt = cached_tokens
        seq.num_computed = cached_tokens
        seq.num_prefilled = cached_tokens
        # Cache-hit pages are already registered; continue the hash chain
        # from the last reused page.
        n_reused = cached_tokens // ps
        seq.num_registered_pages = n_reused
        seq.last_chain_hash = (
            self._pages[block_table[n_reused - 1]].chain_hash if n_reused else None
        )
        if self._qos is not None and seq.tenant and not seq.qos_observed:
            # Per-tenant hit accounting, first successful prefill only
            # (the hit_stats rule): rollbacks raise above, preemption
            # re-prefills have qos_observed already set.
            seq.qos_observed = True
            st = self.tenant_stats.setdefault(
                seq.tenant,
                {
                    "requests": 0,
                    "prompt_tokens": 0,
                    "cached_tokens": 0,
                    "capped_evictions": 0,
                },
            )
            st["requests"] += 1
            st["prompt_tokens"] += len(tokens)
            st["cached_tokens"] += cached_tokens
        return cached_tokens

    def can_allocate(self, seq: Sequence) -> bool:
        # Conservative: ignores prefix-cache hits (which only reduce demand).
        ps = self.config.page_size
        return -(-len(seq.prompt_tokens) // ps) <= self.num_free

    def append_slot(self, seq: Sequence) -> None:
        """Ensure capacity for one more token during decode; allocates a new
        page when the sequence crosses a page boundary."""
        ps = self.config.page_size
        if seq.num_tokens > len(seq.block_table) * ps:
            self._alloc_tenant = seq.tenant
            seq.block_table.append(self._pop_free_page())

    def reserve_slots(self, seq: Sequence, n: int) -> None:
        """Ensure KV-slot capacity for a fused decode burst: positions up to
        ``num_tokens + n - 1`` (token ``num_tokens - 1`` is the burst input;
        step j writes KV at position ``num_tokens - 1 + j``). Allocates all
        crossing pages up front; on exhaustion mid-way the partial growth is
        kept (the caller's preempt-and-retry loop continues from it)."""
        ps = self.config.page_size
        needed = -(-(seq.num_tokens + n - 1) // ps)
        self._alloc_tenant = seq.tenant
        while len(seq.block_table) < needed:
            seq.block_table.append(self._pop_free_page())

    def register_full_pages(self, seq: Sequence) -> None:
        """Hash + cache-register any newly-completed pages of ``seq`` and
        queue their BlockStored events. Called after compute has written the
        page contents. Incremental: only blocks completed since the last
        call are hashed (the chain parent rides on the sequence), keeping
        per-sequence total hashing O(tokens) rather than O(tokens²)."""
        from ..kvcache.kvblock.token_processor import hash_block

        ps = self.config.page_size
        n_full = seq.num_computed // ps
        if n_full <= seq.num_registered_pages:
            return
        tokens = seq.all_tokens
        parent = (
            seq.last_chain_hash
            if seq.last_chain_hash is not None
            else self.token_db.init_hash
        )
        for i in range(seq.num_registered_pages, n_full):
            block = tuple(int(t) for t in tokens[i * ps : (i + 1) * ps])
            h = hash_block(parent, block)
            page = seq.block_table[i]
            info = self._pages[page]
            if info.chain_hash is None:
                existing = self._cached.get(h)
                if existing is not None and existing != page:
                    # Another sequence registered this block concurrently;
                    # keep ours unhashed (it frees normally).
                    parent = h
                    continue
                info.chain_hash = h
                info.token_ids = block
                info.parent_hash = parent if i > 0 else None
                info.tenant = seq.tenant
                self._cached[h] = page
                self._record_lifecycle(h, "tpu_hbm", "allocate", tenant=seq.tenant)
                self._emit(
                    BlockStored(
                        block_hashes=[h],
                        parent_block_hash=info.parent_hash,
                        token_ids=list(block),
                        block_size=ps,
                        medium="tpu_hbm",
                    )
                )
            parent = h
        seq.num_registered_pages = n_full
        seq.last_chain_hash = parent

    def free_sequence(self, seq: Sequence) -> None:
        for page in seq.block_table:
            self._decref(page)
        seq.block_table = []
