"""Data-parallel serving fleet: dp>1 pod servers, one indexer.

VERDICT r2 missing #3: `DataParallelRank` existed on the wire and the pod
took DP_RANK, but nothing ran multiple DP serving replicas publishing
rank-tagged events into ONE indexer with a cross-replica warm-prefix
routing assertion. This suite does exactly that, through the real event
write path (msgpack EventBatch → sharded KVEventsPool → block index) and
the real read path (KVCacheIndexer.score_tokens).

Reference parity: events.go:42 (DataParallelRank), the multi-pod regime of
benchmarking/37-capacity.
"""

import threading

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.kvcache import KVCacheIndexer, KVCacheIndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    EventBatch,
    KVEventsPool,
    KVEventsPoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvevents.pool import Message
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

PS = 4
MODEL = "tiny-llama"
N_REPLICAS = 3


class PoolPublisher:
    """Publishes a pod's KV events into the shared indexer pool through the
    real wire encoding (EventBatch.to_payload → Message), tagged with the
    pod's identity and data-parallel rank — what ZMQPublisher does over
    TCP, minus the socket."""

    def __init__(self, pool, pod_identifier, dp_rank):
        self.pool = pool
        self.pod_identifier = pod_identifier
        self.config = type("C", (), {"data_parallel_rank": dp_rank})()
        self.ranks_published = set()
        self._mu = threading.Lock()

    def publish(self, events, ts=None):
        batch = EventBatch(
            ts=ts or 0.0,
            events=list(events),
            data_parallel_rank=self.config.data_parallel_rank,
        )
        with self._mu:
            self.ranks_published.add(self.config.data_parallel_rank)
        self.pool.add_task(
            Message(
                topic=f"kv@{self.pod_identifier}@{MODEL}",
                pod_identifier=self.pod_identifier,
                model_name=MODEL,
                payload=batch.to_payload(),
            )
        )

    def close(self):
        pass


@pytest.fixture
def fleet():
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=PS))
    )
    pool = KVEventsPool(indexer.kv_block_index, KVEventsPoolConfig(concurrency=2))
    pool.start()

    servers = []
    pubs = []
    for rank in range(N_REPLICAS):
        pod_id = f"tpu-pod-{rank}"
        pub = PoolPublisher(pool, pod_id, dp_rank=rank)
        cfg = PodServerConfig(
            model_name=MODEL,
            pod_identifier=pod_id,
            publish_events=False,
            data_parallel_rank=rank,
            engine=EngineConfig(
                model=TINY_LLAMA,
                block_manager=BlockManagerConfig(total_pages=64, page_size=PS),
                scheduler=SchedulerConfig(max_prefill_batch=4),
                max_model_len=64,
                decode_batch_size=4,
                prefill_bucket=8,
                interpret=True,
            ),
        )
        server = PodServer(cfg, publisher=pub)
        server.start()
        servers.append(server)
        pubs.append(pub)
    try:
        yield indexer, pool, servers, pubs
    finally:
        for s in servers:
            s.shutdown()
        pool.shutdown()
        indexer.shutdown()


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _pod_names():
    return [f"tpu-pod-{r}" for r in range(N_REPLICAS)]


class TestDPFleet:
    def test_cross_replica_warm_prefix_routing(self, fleet):
        """A prefix served on replica 1 must route back to replica 1: its
        pod scores highest at the indexer while the other replicas score
        zero — and the routed request is served warm from cache."""
        indexer, pool, servers, _ = fleet
        prefix = _prompt(0, 16)

        servers[1].generate(prefix, SamplingParams(max_new_tokens=2), timeout=120)
        pool.drain(timeout=10.0)

        scores = indexer.score_tokens(prefix, MODEL, _pod_names())
        assert scores.get("tpu-pod-1", 0) > 0, scores
        assert scores.get("tpu-pod-0", 0) == 0, scores
        assert scores.get("tpu-pod-2", 0) == 0, scores

        # Route a shared-prefix request where the index says, serve it
        # there, and confirm the prefix cache actually fires cross-request.
        followup = prefix + _prompt(1, 4)
        best = max(_pod_names(), key=lambda p: scores.get(p, 0))
        seq = servers[int(best[-1])].generate(
            followup, SamplingParams(max_new_tokens=2), timeout=120
        )
        assert seq.num_cached_prompt >= PS  # at least one warm block

    def test_distinct_prefixes_route_to_their_replicas(self, fleet):
        """Three disjoint prefixes served on three replicas: the index
        separates them — each prefix scores only on its own replica."""
        indexer, pool, servers, _ = fleet
        prefixes = [_prompt(10 + r, 16) for r in range(N_REPLICAS)]
        for r, p in enumerate(prefixes):
            servers[r].generate(p, SamplingParams(max_new_tokens=2), timeout=120)
        pool.drain(timeout=10.0)

        for r, p in enumerate(prefixes):
            scores = indexer.score_tokens(p, MODEL, _pod_names())
            best = max(_pod_names(), key=lambda name: scores.get(name, 0))
            assert best == f"tpu-pod-{r}", (r, scores)
            for other in range(N_REPLICAS):
                if other != r:
                    assert scores.get(f"tpu-pod-{other}", 0) == 0, (r, scores)

    def test_every_rank_publishes_its_own_tag(self, fleet):
        """All dp ranks flow: each replica's batches carry its own rank
        (events.py DataParallelRank — reference events.go:42)."""
        _, pool, servers, pubs = fleet
        for r, s in enumerate(servers):
            s.generate(_prompt(20 + r, 12), SamplingParams(max_new_tokens=1), timeout=120)
        pool.drain(timeout=10.0)
        for r, pub in enumerate(pubs):
            assert pub.ranks_published == {r}

    def test_eviction_on_one_replica_updates_routing(self, fleet):
        """BlockRemoved from replica 1 must withdraw its routing advantage
        at the shared indexer (the closed loop the reference's event plane
        exists for)."""
        indexer, pool, servers, _ = fleet
        prefix = _prompt(30, 16)
        servers[1].generate(prefix, SamplingParams(max_new_tokens=2), timeout=120)
        pool.drain(timeout=10.0)
        assert indexer.score_tokens(prefix, MODEL, _pod_names())["tpu-pod-1"] > 0

        # Force the pod's prefix pages out by flooding it with fresh work.
        for i in range(8):
            servers[1].generate(
                _prompt(100 + i, 48), SamplingParams(max_new_tokens=2), timeout=120
            )
        pool.drain(timeout=10.0)
        scores = indexer.score_tokens(prefix, MODEL, _pod_names())
        assert scores.get("tpu-pod-1", 0) == 0, scores
