from .llama import (
    LlamaConfig,
    init_params,
    prefill,
    decode_step,
    decode_steps,
    init_kv_pages,
    LLAMA_3_8B,
    LLAMA_3_70B,
    QWEN2_5_0_5B,
    QWEN3_32B,
    TINY_LLAMA,
)

__all__ = [
    "LlamaConfig",
    "init_params",
    "prefill",
    "decode_step",
    "decode_steps",
    "init_kv_pages",
    "LLAMA_3_8B",
    "LLAMA_3_70B",
    "QWEN2_5_0_5B",
    "QWEN3_32B",
    "TINY_LLAMA",
]
