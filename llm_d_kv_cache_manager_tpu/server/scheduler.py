"""Request scheduler: continuous batching with FCFS admission.

Two scheduling modes share the admission rules (page-budget FCFS, running
cap):

- **Legacy (default, ``chunked_prefill_tokens=None``)**: each engine step is
  either a **prefill step** (admit waiting sequences whose pages fit,
  batched with padding) or a **decode step** (all running sequences, one
  token each). Prefill-priority keeps TTFT low, matching how the
  reference's benchmarked engines schedule (prefill preemption).

- **Chunked prefill (``chunked_prefill_tokens`` set)**: every step is a
  **mixed step** — it packs up to the token budget of prefill-chunk work
  (resuming partially-prefilled sequences first, then admitting new ones
  under the same page-budget/FCFS rules) *and* carries all running decode
  lanes. One long prompt then never stalls running decodes for its whole
  prefill (Sarathi-Serve-style stall-free scheduling): its ingest is split
  into budget-sized chunks and decode lanes advance between chunks.
  Non-final chunks are floored to ``chunk_align`` (the engine sets
  lcm(prefill_bucket, page_size)) so chunk boundaries stay page-aligned —
  the next chunk's paged context is then exactly the pages written by
  chunks 0..N-1 plus any prefix-cache hit, the same warm-prefill shape the
  engine already compiles.

In both modes the page pool's LRU recycling provides the back-pressure and
page-budget admission prevents over-commit.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from ..utils import get_logger
from .block_manager import AllocationError, BlockManager
from .sequence import Sequence, SequenceStatus

log = get_logger("server.scheduler")


@dataclass
class SchedulerConfig:
    max_running: int = 64
    max_prefill_batch: int = 8
    #: cap on tokens in one prefill batch (bounds score-matrix memory)
    max_prefill_tokens: int = 8192
    #: per-step prefill token budget for chunked prefill + mixed
    #: prefill/decode steps. None (default) keeps the legacy either-or
    #: scheduling bit-identical; set (e.g. 256-2048) to bound how long any
    #: single step's prefill work can stall running decode lanes.
    chunked_prefill_tokens: Optional[int] = None
    #: alignment for non-final chunk lengths; the engine overrides this
    #: with lcm(prefill_bucket, page_size) so mid-prefill chunk boundaries
    #: stay page-aligned (paged-context contract) and dispatch widths stay
    #: on the jit shape buckets.
    chunk_align: int = 1


@dataclass
class ScheduleOutput:
    prefill: list[Sequence]
    decode: list[Sequence]
    #: tokens to prefill per ``prefill`` entry this step (chunked mode;
    #: None in legacy mode = each entry prefills its whole fresh suffix)
    chunks: Optional[list[int]] = None


class Scheduler:
    def __init__(self, block_manager: BlockManager, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        self.block_manager = block_manager
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        #: admitted (pages allocated) but only partially prefilled — only
        #: populated in chunked mode; FCFS order preserved.
        self.prefilling: list[Sequence] = []
        #: TENANT_QOS (off by default): when enabled, the waiting queue is
        #: re-ordered by (priority class, weighted-fair served tokens)
        #: before each admission walk. Engine-thread-only state.
        self.qos_enabled: bool = False
        #: prefill tokens served per tenant slice, divided by the tenant's
        #: weight at comparison time — the weighted-fair tiebreak within a
        #: priority class (lowest normalized share admits first, bounding
        #: starvation between same-class tenants).
        self._qos_served: dict[str, float] = {}

    def attach_qos(self) -> None:
        """Enable TENANT_QOS ordering (serving layer calls this once at
        construction, before the engine thread starts)."""
        self.qos_enabled = True

    def _qos_sort_key(self, seq: Sequence) -> tuple[int, float]:
        served = self._qos_served.get(seq.tenant, 0.0)
        return (seq.priority, served / max(seq.qos_weight, 1e-9))

    def qos_reorder_waiting(self) -> None:
        """Stable-sort the waiting queue by (priority class, normalized
        served tokens). Stability keeps FIFO order within a tenant and
        between tenants with equal shares, so the legacy FCFS admission
        walks below run unmodified — their head-of-queue break rule then
        protects the highest-priority request instead of the oldest."""
        if not self.qos_enabled or len(self.waiting) <= 1:
            return
        self.waiting = deque(sorted(self.waiting, key=self._qos_sort_key))

    def _qos_charge(self, seq: Sequence, tokens: int) -> None:
        """Charge admitted prefill tokens to the tenant's fair-share
        meter. Occasionally renormalized (only relative shares matter)
        so the floats never grow without bound."""
        if not self.qos_enabled or tokens <= 0:
            return
        served = self._qos_served
        served[seq.tenant] = served.get(seq.tenant, 0.0) + float(tokens)
        if len(served) > 1:
            floor = min(served.values())
            if floor >= 1e9:
                for k in served:
                    served[k] -= floor

    def add(self, seq: Sequence) -> None:
        seq.status = SequenceStatus.WAITING
        self.waiting.append(seq)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.prefilling or self.running)

    @property
    def has_ready_work(self) -> bool:
        """Work the engine could make progress on THIS step — ``has_work``
        minus waiting sequences whose async KV-pull is still importing
        (stepping for those alone would busy-spin until the wire
        delivers). The head-of-deque check keeps the common no-import
        case O(1)."""
        if self.prefilling or self.running:
            return True
        w = self.waiting
        if not w:
            return False
        if not w[0].importing:
            return True
        return any(not s.importing for s in w)

    def _skip_importing(self, idx: int) -> int:
        """Advance ``idx`` past waiting sequences mid-import, stamping the
        first time each would otherwise have been an admission candidate
        (the hidden/exposed boundary of the pull-overlap decomposition)."""
        while idx < len(self.waiting) and self.waiting[idx].importing:
            seq = self.waiting[idx]
            if seq.import_wanted_time is None:
                seq.import_wanted_time = time.monotonic()
            idx += 1
        return idx

    def shed_expired(self, now: float) -> list[Sequence]:
        """Deadline shedding for requests that have not produced a token
        yet: expired WAITING sequences are dropped before any prefill
        compute is spent on them, and expired MID-PREFILL sequences (their
        chunked ingest cannot beat an already-passed deadline) release
        their pages. Running lanes are not touched here — the engine
        finishes them at the next commit point so partial output is still
        returned. Shed sequences are marked FINISHED with
        ``finish_reason="deadline"``; the caller (engine step) reports
        them as finished so the serving layer resolves their futures.
        Only called when at least one live request carries a deadline, so
        the legacy no-deadline path never pays the scan."""
        shed: list[Sequence] = []
        if any(
            s.deadline is not None and now >= s.deadline for s in self.waiting
        ):
            keep: deque[Sequence] = deque()
            for seq in self.waiting:
                if seq.is_finished():
                    # Defensive: a sequence that already finished (aborted
                    # or shed elsewhere after a preemption re-queued it)
                    # is dropped without re-counting — one shed per
                    # request, the counters stay exact.
                    continue
                if seq.deadline is not None and now >= seq.deadline:
                    shed.append(seq)
                else:
                    keep.append(seq)
            self.waiting = keep
        for seq in list(self.prefilling):
            if seq.is_finished():
                self.prefilling.remove(seq)
                continue
            if seq.deadline is not None and now >= seq.deadline:
                self.prefilling.remove(seq)
                self.block_manager.free_sequence(seq)
                seq.reset_allocation()
                shed.append(seq)
        for seq in shed:
            seq.status = SequenceStatus.FINISHED
            if seq.finish_reason is None:
                seq.finish_reason = "deadline"
            log.warning(
                "shedding deadline-expired request before prefill",
                seq=seq.seq_id,
                request=seq.request_id,
            )
        return shed

    def schedule(self) -> ScheduleOutput:
        """Pick the work for one engine step."""
        if self.config.chunked_prefill_tokens is not None:
            return self._schedule_chunked()
        self.qos_reorder_waiting()
        # Admit waiting sequences first (prefill priority). Sequences
        # whose async KV-pull is still importing are skipped in place
        # (admission continues past them — the wire must never stall
        # later arrivals); with no imports in flight the walk is the
        # legacy head-of-deque FCFS loop exactly.
        prefill: list[Sequence] = []
        budget = self.config.max_prefill_tokens
        idx = 0
        while (
            len(prefill) < self.config.max_prefill_batch
            and len(self.running) + len(prefill) < self.config.max_running
        ):
            idx = self._skip_importing(idx)
            if idx >= len(self.waiting):
                break
            seq = self.waiting[idx]
            if not self.block_manager.can_allocate(seq):
                break  # FCFS: wait for pages rather than starving this seq
            try:
                self.block_manager.allocate(seq)
            except AllocationError:
                break
            # The token budget bounds prefill *compute*, which is only the
            # non-cached suffix — known exactly after allocation resolves
            # the prefix-cache hit. Roll back rather than over-commit.
            suffix = max(len(seq.prompt_tokens) - seq.num_cached_prompt, 1)
            if prefill and suffix > budget:
                self.block_manager.free_sequence(seq)
                seq.reset_allocation()
                break
            del self.waiting[idx]
            budget -= suffix
            self._qos_charge(seq, suffix)
            prefill.append(seq)

        if prefill:
            return ScheduleOutput(prefill=prefill, decode=[])
        return ScheduleOutput(prefill=[], decode=list(self.running))

    def _take_chunk(self, remaining: int, budget: int, align: int) -> int:
        """Chunk size for a sequence with ``remaining`` fresh prompt tokens
        under ``budget``: the whole remainder when it fits (final chunk),
        else the largest align-multiple that fits (0 = budget exhausted for
        a non-final chunk — the caller stops packing)."""
        if remaining <= budget:
            return remaining
        return (budget // align) * align

    def _schedule_chunked(self) -> ScheduleOutput:
        """Token-budget mixed step: prefill chunks up to the budget plus
        every running decode lane."""
        self.qos_reorder_waiting()
        align = max(1, self.config.chunk_align)
        # A budget below one alignment unit could never form a non-final
        # chunk; the align clamp is applied LAST (also overriding
        # max_prefill_tokens) so long prompts always make forward progress
        # — one align-sized chunk is a single prefill-bucket dispatch, the
        # minimum width the engine compiles anyway.
        budget = max(
            min(self.config.chunked_prefill_tokens, self.config.max_prefill_tokens),
            align,
        )
        prefill: list[Sequence] = []
        chunks: list[int] = []

        # Resume partially-prefilled sequences first (their pages are
        # already held — finishing them releases decode capacity soonest).
        for seq in self.prefilling:
            if budget <= 0 or len(prefill) >= self.config.max_prefill_batch:
                break
            take = self._take_chunk(seq.prompt_remaining, budget, align)
            if take == 0:
                break
            prefill.append(seq)
            chunks.append(take)
            self._qos_charge(seq, take)
            budget -= take

        # Then admit new sequences under the page-budget/FCFS rules
        # (mid-import sequences skipped in place, as in the legacy loop).
        idx = 0
        while (
            budget > 0
            and len(prefill) < self.config.max_prefill_batch
            and len(self.running) + len(self.prefilling) < self.config.max_running
        ):
            idx = self._skip_importing(idx)
            if idx >= len(self.waiting):
                break
            seq = self.waiting[idx]
            if not self.block_manager.can_allocate(seq):
                break  # FCFS: wait for pages rather than starving this seq
            try:
                self.block_manager.allocate(seq)
            except AllocationError:
                break
            take = self._take_chunk(seq.prompt_remaining, budget, align)
            if take == 0:
                # Not even one aligned chunk fits the leftover budget: roll
                # back rather than hold pages for a sequence doing nothing
                # this step.
                self.block_manager.free_sequence(seq)
                seq.reset_allocation()
                break
            del self.waiting[idx]
            self.prefilling.append(seq)
            prefill.append(seq)
            chunks.append(take)
            self._qos_charge(seq, take)
            budget -= take

        return ScheduleOutput(
            prefill=prefill, decode=list(self.running), chunks=chunks
        )

    def on_prefill_done(self, seqs: list[Sequence]) -> None:
        for seq in seqs:
            if seq in self.prefilling:
                self.prefilling.remove(seq)
            seq.status = SequenceStatus.RUNNING
            self.running.append(seq)

    def on_preempted(self, seq: Sequence) -> None:
        """Remove a preempted sequence from whichever active list holds it
        (running lane, or mid-prefill in chunked mode)."""
        if seq in self.running:
            self.running.remove(seq)
        elif seq in self.prefilling:
            self.prefilling.remove(seq)

    def on_finished(self, seq: Sequence) -> None:
        seq.status = SequenceStatus.FINISHED
        self.running.remove(seq)
        self.block_manager.free_sequence(seq)
