"""Dependency-free span recorder with W3C ``traceparent`` propagation.

Design constraints (matching the PR 1-4 convention of zero-cost-when-off):

- **No hard deps.** Only stdlib. When ``opentelemetry-sdk`` happens to be
  installed AND ``OBS_OTLP_ENDPOINT`` is set, finished spans are mirrored
  to an OTLP exporter; otherwise that path is a no-op.
- **Off = free.** A disabled ``Tracer`` hands out one shared ``NOOP_SPAN``
  singleton: no allocation, no clock reads, no lock. Callers never branch
  on enablement — they branch (at most) on ``span.context is None`` when
  deciding whether to emit a ``traceparent``.
- **Bounded memory.** Finished spans land in a ring buffer
  (``max_spans``, default 2048); old traces fall off the back. Served by
  ``GET /debug/traces`` on the scoring API and the pod server.

Propagation follows the W3C Trace Context format::

    traceparent: 00-<32 hex trace-id>-<16 hex parent-span-id>-<2 hex flags>

The scoring service mints or adopts a trace id, the serving layer forwards
it through ``Sequence``, and the transfer protocol carries it to the
exporting peer so that pod's spans join the same trace.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from ..utils import RateLimitedWarn, get_logger

log = get_logger("obs.tracing")
#: exporter faults repeat per span once a collector misbehaves; keep them
#: visible without the log scaling with span volume.
_warn = RateLimitedWarn(log)

_HEX = set("0123456789abcdef")


@dataclass(frozen=True)
class SpanContext:
    """The propagated identity of a span: what children parent onto."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars


def _is_hex(s: str, n: int) -> bool:
    return len(s) == n and set(s) <= _HEX


def gen_trace_id() -> str:
    return os.urandom(16).hex()


def gen_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C ``traceparent`` header; None for absent/malformed input
    (a bad header must never fail a request — tracing is best-effort)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if not _is_hex(version, 2) or version == "ff":
        return None
    if not _is_hex(trace_id, 32) or trace_id == "0" * 32:
        return None
    if not _is_hex(span_id, 16) or span_id == "0" * 16:
        return None
    if not _is_hex(flags, 2):
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id)


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


class Span:
    """One live span. End it explicitly or use it as a context manager;
    attributes set after ``end()`` are ignored."""

    __slots__ = (
        "name",
        "context",
        "parent_span_id",
        "attrs",
        "start_wall",
        "start_mono",
        "end_mono",
        "_tracer",
        "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        context: SpanContext,
        parent_span_id: Optional[str],
        attrs: Optional[dict] = None,
    ):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_span_id = parent_span_id
        self.attrs = dict(attrs) if attrs else {}
        # Wall clock on purpose: start_unix_s is a cross-host display/export
        # timestamp; durations below use the monotonic pair.
        self.start_wall = time.time()  # kvlint: disable=monotonic-time
        self.start_mono = time.monotonic()
        self.end_mono: Optional[float] = None
        self._ended = False

    def set_attr(self, key: str, value) -> None:
        if not self._ended:
            self.attrs[key] = value

    def end(self, end_mono: Optional[float] = None) -> None:
        if self._ended:
            return
        self._ended = True
        self.end_mono = time.monotonic() if end_mono is None else end_mono
        self._tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        if exc is not None:
            self.attrs.setdefault("error", repr(exc))
        self.end()


class _NoopSpan:
    """Shared do-nothing span for disabled tracers. ``context`` is None —
    the one thing callers may branch on (to skip header emission)."""

    __slots__ = ()
    context = None
    parent_span_id = None
    name = ""
    attrs: dict = {}

    def set_attr(self, key: str, value) -> None:
        pass

    def end(self, end_mono=None) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *_a) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Per-process span recorder with a bounded finished-span ring.

    ``service`` tags every span dict (which process recorded it) so merged
    multi-process trace views stay attributable.
    """

    def __init__(
        self,
        enabled: bool = False,
        max_spans: int = 2048,
        service: str = "",
        otlp_endpoint: Optional[str] = None,
    ):
        self.enabled = bool(enabled)
        self.service = service
        self._mu = threading.Lock()
        self._spans: deque = deque(maxlen=max(int(max_spans), 16))  # guarded_by: _mu
        self.spans_recorded = 0  # guarded_by: _mu
        self.spans_dropped = 0  # guarded_by: _mu
        self._otlp = None
        if self.enabled:
            endpoint = otlp_endpoint or os.environ.get("OBS_OTLP_ENDPOINT")
            if endpoint:
                self._otlp = _make_otlp_exporter(endpoint)

    # -- recording -----------------------------------------------------------
    def start_span(self, name: str, parent=None, attrs: Optional[dict] = None):
        """Start a span. ``parent`` is a ``SpanContext``, a ``Span``, or
        None (mint a fresh trace). Disabled tracers return ``NOOP_SPAN``."""
        if not self.enabled:
            return NOOP_SPAN
        pctx = getattr(parent, "context", parent)  # Span -> its context
        if isinstance(pctx, SpanContext):
            ctx = SpanContext(trace_id=pctx.trace_id, span_id=gen_span_id())
            parent_id = pctx.span_id
        else:
            ctx = SpanContext(trace_id=gen_trace_id(), span_id=gen_span_id())
            parent_id = None
        return Span(self, name, ctx, parent_id, attrs)

    def record_span(
        self,
        name: str,
        parent,
        start_mono: float,
        end_mono: float,
        attrs: Optional[dict] = None,
    ) -> None:
        """Record an already-elapsed interval as a finished span — the path
        for timestamp-derived spans (queue/prefill/decode) reconstructed at
        request completion from the timestamps the engine already keeps."""
        if not self.enabled:
            return
        span = self.start_span(name, parent=parent, attrs=attrs)
        # Back-date: the span object was just created but the interval it
        # describes happened earlier.
        span.start_mono = start_mono
        # Back-dating a display timestamp: wall clock minus monotonic delta.
        span.start_wall = time.time() - (time.monotonic() - start_mono)  # kvlint: disable=monotonic-time
        span.end(end_mono=end_mono)

    def _finish(self, span: Span) -> None:
        rec = {
            "name": span.name,
            "service": self.service,
            "trace_id": span.context.trace_id,
            "span_id": span.context.span_id,
            "parent_span_id": span.parent_span_id,
            "start_unix_s": round(span.start_wall, 6),
            "duration_s": round(max(span.end_mono - span.start_mono, 0.0), 6),
            "attrs": span.attrs,
        }
        with self._mu:
            if len(self._spans) == self._spans.maxlen:
                self.spans_dropped += 1
            self._spans.append(rec)
            self.spans_recorded += 1
        if self._otlp is not None:
            try:
                self._otlp(rec)
            except Exception:
                # Broad by necessity (the OTLP SDK's fault surface is not
                # enumerable); a broken exporter must not tax serving, but
                # disabling the mirror silently left operators staring at
                # an empty collector — say so, once.
                log.warning(
                    "OTLP span mirror failed; disabling for this process",
                    exc_info=True,
                )
                self._otlp = None

    # -- reading -------------------------------------------------------------
    def traces(
        self,
        trace_id: Optional[str] = None,
        request_id: Optional[str] = None,
        span_name: Optional[str] = None,
        limit: int = 50,
    ) -> list[dict]:
        """Finished spans grouped by trace (oldest trace first). A
        ``request_id`` filter keeps traces where ANY span carries that
        ``request_id`` attribute; a ``span_name`` filter keeps traces
        containing a span of that name (the whole trace is returned, so
        the match stays readable in context — grepping the disagg
        two-hop traces by ``span=disagg.handoff`` beats hunting ids)."""
        if limit <= 0:
            return []
        with self._mu:
            spans = list(self._spans)
        by_trace: dict[str, list[dict]] = {}
        for rec in spans:
            by_trace.setdefault(rec["trace_id"], []).append(rec)
        out = []
        for tid, recs in by_trace.items():
            if trace_id is not None and tid != trace_id:
                continue
            if request_id is not None and not any(
                r["attrs"].get("request_id") == request_id for r in recs
            ):
                continue
            if span_name is not None and not any(
                r["name"] == span_name for r in recs
            ):
                continue
            out.append({"trace_id": tid, "spans": recs})
        return out[-limit:]

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "spans_buffered": len(self._spans),
                "spans_recorded": self.spans_recorded,
                "spans_dropped": self.spans_dropped,
            }


def debug_traces_payload(tracer: Tracer, query) -> tuple[int, dict]:
    """The shared ``GET /debug/traces`` contract for the scoring API and
    the pod server: ``(http_status, payload)`` from a query mapping with
    optional ``trace_id`` / ``request_id`` / ``span`` / ``limit`` keys.
    Framework-agnostic so both aiohttp handlers stay one line."""
    try:
        limit = int(query.get("limit", "50"))
    except ValueError:
        return 400, {"error": "invalid limit (want a positive int)"}
    return 200, {
        "enabled": tracer.enabled,
        "traces": tracer.traces(
            trace_id=query.get("trace_id"),
            request_id=query.get("request_id"),
            span_name=query.get("span"),
            limit=limit,
        ),
    }


def _make_otlp_exporter(endpoint: str):
    """Optional OTLP mirror: returns a ``span_dict -> None`` callable when
    the opentelemetry SDK is importable, else None (pure no-op path — the
    container does not bake the SDK in).

    Trace identity is preserved by parenting each mirrored span on a
    ``NonRecordingSpan`` carrying the record's trace id (and its recorded
    parent span id), so spans from the scorer, pod, and transfer peer land
    in ONE collector trace. The SDK generates the mirrored span's own id,
    so internal ids additionally ride as attributes for exact matching."""
    try:
        from opentelemetry import trace as otel_trace
        from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
    except ImportError:
        return None
    provider = TracerProvider(resource=Resource.create({}))
    provider.add_span_processor(
        BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
    )
    otel_tracer = provider.get_tracer("llm_d_kv_cache_manager_tpu")

    def export(rec: dict) -> None:
        start_ns = int(rec["start_unix_s"] * 1e9)
        parent_ctx = otel_trace.SpanContext(
            trace_id=int(rec["trace_id"], 16),
            span_id=int(rec["parent_span_id"] or rec["span_id"], 16),
            is_remote=True,
            trace_flags=otel_trace.TraceFlags(otel_trace.TraceFlags.SAMPLED),
        )
        context = otel_trace.set_span_in_context(
            otel_trace.NonRecordingSpan(parent_ctx)
        )
        span = otel_tracer.start_span(
            rec["name"], context=context, start_time=start_ns
        )
        for k, v in {
            **rec["attrs"],
            "internal.span_id": rec["span_id"],
            "internal.parent_span_id": rec["parent_span_id"] or "",
            "service": rec["service"],
        }.items():
            try:
                span.set_attribute(k, v)
            except Exception:
                # Attribute values come from user-supplied request fields
                # and the SDK's fault surface is not enumerable; any escape
                # here would hit _finish's handler and disable the WHOLE
                # mirror. Drop THAT attribute, not the span — visibly, and
                # rate-limited per attribute key.
                _warn.warning(
                    f"otlp-attr:{k}",
                    "dropping unserializable span attribute",
                    attr=k,
                    value_type=type(v).__name__,
                )
        span.end(end_time=start_ns + int(rec["duration_s"] * 1e9))

    return export
