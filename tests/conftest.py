"""Test bootstrap.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding code is
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py runs on the one real chip).

The container's ``sitecustomize`` imports jax and registers the axon
TPU-tunnel PJRT plugin before conftest runs, with ``JAX_PLATFORMS=axon``
baked into jax's config — so env vars set here are too late, and letting
backend init reach the tunnel can hang every test run if the tunnel is
wedged. ``jax.config.update`` before the first backend initialization pins
the platform to CPU in-process and the tunnel is never touched.
"""

import os
import sys

# Must precede the first jax backend initialization (not merely jax import).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_d_kv_cache_manager_tpu.tokenization import Tokenizer  # noqa: E402


class CharTokenizer(Tokenizer):
    """Shared offline test tokenizer: token id = ord(char), byte offsets."""

    def encode(self, prompt, model_name):
        return [ord(c) for c in prompt], [(i, i + 1) for i in range(len(prompt))]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "network: needs a real HF tokenizer (network or populated HF cache); "
        "skips cleanly offline",
    )
