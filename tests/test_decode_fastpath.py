"""Decode fast path (ISSUE 7): async KV-pull overlap + device-resident
decode loop.

The acceptance pins of the subsystem:

- ``DECODE_FUSED_SAMPLING`` off (default) = bit-identical legacy decode;
  on = greedy outputs identical to the unfused engine at every burst
  width, including k=1 (the device-resident step-per-token loop) and
  composed with ``decode_pipeline``.
- ``ASYNC_PULL`` off = the legacy blocking pull flow untouched; on = a
  pull-routed request imports its warm prefix on a worker thread while
  queued ``importing``, the scheduler admits it only once the blocks
  land, and EVERY failure mode (dead peer, timeout, expired deadline,
  abort) degrades to cold prefill or a clean abort — never a stuck
  request, never a stalled batchmate, never a leaked page.
- Aborting a sequence stuck mid-import cancels the in-flight fetch and
  returns free pages to baseline (the PR 4 abort-accounting contract
  extended to the ``importing`` state).
"""

import time

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    Engine,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

PS = 4
MODEL = "tiny-llama"


def _engine_cfg(total_pages=64, **kw):
    kw.setdefault("scheduler", SchedulerConfig(max_prefill_batch=4))
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=PS),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
        **kw,
    )


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _pod_config(pod_id, transfer_endpoint=None, total_pages=64, **kw):
    return PodServerConfig(
        model_name=MODEL,
        pod_identifier=pod_id,
        publish_events=False,
        transfer_endpoint=transfer_endpoint,
        engine=_engine_cfg(total_pages=total_pages),
        **kw,
    )


def _wait_until(cond, timeout=30.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class TestFusedSampling:
    """Device-resident decode loop: greedy parity at every knob setting."""

    PROMPTS = [(0, 10), (1, 17), (2, 5)]

    def _run(self, **kw):
        eng = Engine(_engine_cfg(**kw))
        seqs = [
            eng.add_request(_prompt(s, n), SamplingParams(max_new_tokens=8))
            for s, n in self.PROMPTS
        ]
        eng.run_until_complete()
        assert all(s.error is None for s in seqs)
        return [s.generated_tokens for s in seqs]

    def test_greedy_parity_all_modes(self):
        base = self._run()
        for kw in (
            dict(decode_fused_sampling=True),
            dict(decode_fused_sampling=True, decode_steps_per_iter=2),
            dict(
                decode_fused_sampling=True,
                decode_steps_per_iter=4,
                decode_pipeline=True,
            ),
        ):
            assert self._run(**kw) == base, kw

    def test_fused_k1_enables_pipeline(self):
        eng = Engine(_engine_cfg(decode_fused_sampling=True))
        assert eng._pipeline  # device-resident loop live at k=1
        legacy = Engine(_engine_cfg())
        assert not legacy._pipeline

    def test_parity_under_pool_pressure_with_preemption(self):
        # A pool too small for every lane forces preemption mid-burst;
        # the fused path must recover to the same greedy outputs.
        base = []
        for fused in (False, True):
            eng = Engine(
                _engine_cfg(total_pages=14, decode_fused_sampling=fused)
            )
            seqs = [
                eng.add_request(_prompt(s, 9), SamplingParams(max_new_tokens=10))
                for s in (3, 4)
            ]
            eng.run_until_complete()
            assert all(s.error is None for s in seqs)
            base.append([s.generated_tokens for s in seqs])
        assert base[0] == base[1]

    def test_warm_cache_hit_parity(self):
        # Second request shares a prefix: the fused engine must serve the
        # hit identically (register_full_pages lags one burst on commit).
        prefix = _prompt(5, 12)
        outs = []
        for fused in (False, True):
            eng = Engine(_engine_cfg(decode_fused_sampling=fused))
            a = eng.add_request(prefix + _prompt(6, 4), SamplingParams(max_new_tokens=6))
            eng.run_until_complete()
            b = eng.add_request(prefix + _prompt(7, 4), SamplingParams(max_new_tokens=6))
            eng.run_until_complete()
            assert b.num_cached_prompt >= PS
            outs.append((a.generated_tokens, b.generated_tokens))
        assert outs[0] == outs[1]

    def test_sample_phase_recorded(self):
        eng = Engine(_engine_cfg())
        eng.obs_step_timing = True
        eng.add_request(_prompt(8, 10), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        assert eng.step_stats["sample_s"] > 0.0
        # With timing off the key exists but never accrues (legacy path).
        eng2 = Engine(_engine_cfg())
        eng2.add_request(_prompt(8, 10), SamplingParams(max_new_tokens=4))
        eng2.run_until_complete()
        assert eng2.step_stats["sample_s"] == 0.0


class TestSchedulerImportingState:
    """Waiting sequences mid-import are skipped in place, never block
    admission of later arrivals, and stamp the overlap boundary."""

    def test_importing_seq_skipped_and_later_seq_admitted(self):
        eng = Engine(_engine_cfg())
        a = eng.add_request(_prompt(10, 8), SamplingParams(max_new_tokens=2))
        a.importing = True
        b = eng.add_request(_prompt(11, 8), SamplingParams(max_new_tokens=2))
        out = eng.scheduler.schedule()
        assert out.prefill == [b]
        assert a.import_wanted_time is not None  # overlap boundary stamped
        assert a in eng.scheduler.waiting
        # Import lands: the sequence becomes admittable in FCFS position.
        a.importing = False
        out2 = eng.scheduler.schedule()
        assert a in out2.prefill

    def test_importing_seq_skipped_in_chunked_mode(self):
        eng = Engine(
            _engine_cfg(scheduler=SchedulerConfig(
                max_prefill_batch=4, chunked_prefill_tokens=8
            ))
        )
        a = eng.add_request(_prompt(12, 8), SamplingParams(max_new_tokens=2))
        a.importing = True
        b = eng.add_request(_prompt(13, 8), SamplingParams(max_new_tokens=2))
        out = eng.scheduler.schedule()
        assert out.prefill == [b]
        assert a in eng.scheduler.waiting

    def test_has_ready_work_gates_import_only_queues(self):
        eng = Engine(_engine_cfg())
        assert not eng.has_ready_work
        a = eng.add_request(_prompt(14, 8), SamplingParams(max_new_tokens=2))
        assert eng.has_ready_work
        a.importing = True
        assert eng.has_work and not eng.has_ready_work
        eng.add_request(_prompt(15, 8), SamplingParams(max_new_tokens=2))
        assert eng.has_ready_work


class TestAsyncPull:
    def test_async_pull_parity_and_warm_hit(self):
        from conftest import free_tcp_port

        endpoint = f"tcp://127.0.0.1:{free_tcp_port()}"
        warm = PodServer(_pod_config("ap-warm", transfer_endpoint=endpoint))
        cold = PodServer(_pod_config("ap-cold", async_pull=True))
        ref = PodServer(_pod_config("ap-ref"))
        warm.start(), cold.start(), ref.start()
        try:
            prefix = _prompt(20, 16)
            prompt = prefix + _prompt(21, 4)
            warm.generate(prefix, SamplingParams(max_new_tokens=2), timeout=120)

            fut = cold.submit(
                prompt, SamplingParams(max_new_tokens=4), pull_source=endpoint
            )
            s = fut.result(timeout=120)
            s_ref = ref.generate(prompt, SamplingParams(max_new_tokens=4), timeout=120)
            assert s.generated_tokens == s_ref.generated_tokens
            # Admission waited for the import: the warm prefix MUST hit.
            assert s.num_cached_prompt == len(prefix)
            assert cold.async_pulls == 1 and cold.transfer_pulls == 1
            assert not cold._pull_jobs
        finally:
            warm.shutdown(), cold.shutdown(), ref.shutdown()

    def test_dead_peer_falls_back_to_cold_with_parity(self):
        from conftest import free_tcp_port

        cold = PodServer(
            _pod_config("ap-cold2", async_pull=True, transfer_timeout_s=0.5)
        )
        ref = PodServer(_pod_config("ap-ref2"))
        cold.start(), ref.start()
        try:
            prompt = _prompt(22, 12)
            fut = cold.submit(
                prompt,
                SamplingParams(max_new_tokens=3),
                pull_source=f"tcp://127.0.0.1:{free_tcp_port()}",
            )
            s = fut.result(timeout=120)
            s_ref = ref.generate(prompt, SamplingParams(max_new_tokens=3), timeout=120)
            assert s.generated_tokens == s_ref.generated_tokens
            assert s.num_cached_prompt == 0  # cold prefill, not a failure
            assert cold.async_pull_fallbacks == 1
            assert cold.transfer_pull_failures == 1
        finally:
            cold.shutdown(), ref.shutdown()

    def test_stalled_import_never_blocks_other_requests(self):
        from conftest import free_tcp_port

        cold = PodServer(
            _pod_config("ap-cold3", async_pull=True, transfer_timeout_s=10.0)
        )
        cold.start()
        try:
            stalled = cold.submit(
                _prompt(23, 12),
                SamplingParams(max_new_tokens=2),
                pull_source=f"tcp://127.0.0.1:{free_tcp_port()}",
            )
            assert _wait_until(lambda: bool(cold._pull_jobs), timeout=10)
            # A later arrival is admitted straight past the importing head.
            other = cold.submit(_prompt(24, 8), SamplingParams(max_new_tokens=4))
            s = other.result(timeout=60)
            assert len(s.generated_tokens) == 4
            assert not stalled.done()  # the import is still on the wire
            s_stalled = stalled.result(timeout=60)  # then falls back cold
            assert len(s_stalled.generated_tokens) == 2
        finally:
            cold.shutdown()

    def test_abort_mid_import_cancels_fetch_and_frees_pages(self):
        from conftest import free_tcp_port

        cold = PodServer(
            _pod_config("ap-cold4", async_pull=True, transfer_timeout_s=2.0)
        )
        cold.start()
        try:
            free0 = cold.engine.block_manager.num_free
            fut = cold.submit(
                _prompt(25, 12),
                SamplingParams(max_new_tokens=4),
                pull_source=f"tcp://127.0.0.1:{free_tcp_port()}",
            )
            assert _wait_until(lambda: bool(cold._pull_jobs), timeout=10)
            assert cold.abort(fut.request_id).result(timeout=30)
            s = fut.result(timeout=30)
            assert s.finish_reason == "abort"
            # The in-flight fetch is canceled, installs nothing, and the
            # pool returns to baseline (regression: importing-state abort
            # accounting).
            assert _wait_until(lambda: cold.async_pull_canceled == 1, timeout=30)
            assert cold.engine.block_manager.num_free == free0
            assert not cold._pull_jobs
        finally:
            cold.shutdown()

    def test_deadline_clamps_import_and_sheds(self):
        from conftest import free_tcp_port

        cold = PodServer(
            _pod_config("ap-cold5", async_pull=True, transfer_timeout_s=30.0)
        )
        cold.start()
        try:
            t0 = time.monotonic()
            fut = cold.submit(
                _prompt(26, 12),
                SamplingParams(max_new_tokens=4),
                deadline_s=0.3,
                pull_source=f"tcp://127.0.0.1:{free_tcp_port()}",
            )
            s = fut.result(timeout=30)
            # The fetch was clamped to the remaining deadline budget (not
            # the 30 s transfer timeout) and the expired sequence shed.
            assert s.finish_reason == "deadline"
            assert time.monotonic() - t0 < 10.0
        finally:
            cold.shutdown()

    def test_knob_off_ignores_pull_source(self):
        from conftest import free_tcp_port

        plain = PodServer(_pod_config("ap-plain"))
        plain.start()
        try:
            fut = plain.submit(
                _prompt(27, 10),
                SamplingParams(max_new_tokens=3),
                pull_source=f"tcp://127.0.0.1:{free_tcp_port()}",
            )
            s = fut.result(timeout=120)
            assert len(s.generated_tokens) == 3
            assert plain.async_pulls == 0 and plain.async_pull_fallbacks == 0
            assert plain._pull_pool is None  # nothing was ever spawned
        finally:
            plain.shutdown()

    def test_stats_block_gated_on_knob(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        async def fetch_stats(server):
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.get("/stats")
                return await resp.json()
            finally:
                await client.close()

        on = PodServer(_pod_config("ap-stats-on", async_pull=True))
        off = PodServer(_pod_config("ap-stats-off"))
        on.start(), off.start()
        try:
            stats_on = asyncio.run(fetch_stats(on))
            stats_off = asyncio.run(fetch_stats(off))
            assert set(stats_on["transfer"]["async_pull"]) == {
                "workers", "importing", "pulls", "fallbacks", "canceled"
            }
            assert "async_pull" not in stats_off["transfer"]
        finally:
            on.shutdown(), off.shutdown()


class TestPullOverlapObservability:
    def test_overlap_recorded_on_async_pull(self):
        pytest.importorskip("prometheus_client")
        from conftest import free_tcp_port

        endpoint = f"tcp://127.0.0.1:{free_tcp_port()}"
        warm = PodServer(_pod_config("ov-warm", transfer_endpoint=endpoint))
        cold = PodServer(
            _pod_config(
                "ov-cold", async_pull=True, obs_metrics=True, obs_tracing=True
            )
        )
        warm.start(), cold.start()
        try:
            prefix = _prompt(30, 16)
            warm.generate(prefix, SamplingParams(max_new_tokens=2), timeout=120)
            fut = cold.submit(
                prefix + _prompt(31, 4),
                SamplingParams(max_new_tokens=3),
                pull_source=endpoint,
            )
            fut.result(timeout=120)
            text = cold.metrics.exposition().decode()
            assert 'kvcache_transfer_pull_overlap_seconds_count{kind="hidden"} 1.0' in text
            assert 'kvcache_transfer_pull_overlap_seconds_count{kind="exposed"} 1.0' in text
            # The pull span carries async + overlap attrs.
            spans = [
                sp
                for tr in cold.tracer.traces()
                for sp in tr["spans"]
                if sp["name"] == "pod.pull_prefix"
            ]
            assert spans and spans[0]["attrs"]["async"] is True
            assert "overlap" in spans[0]["attrs"]
        finally:
            warm.shutdown(), cold.shutdown()
