"""Request scheduler: continuous batching with FCFS admission.

Each engine step is either a **prefill step** (admit waiting sequences whose
pages fit, batched with padding) or a **decode step** (all running
sequences, one token each). Prefill-priority keeps TTFT low, matching how
the reference's benchmarked engines schedule (prefill preemption);
page-budget admission prevents over-commit, and the page pool's LRU
recycling provides the back-pressure.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Optional

from ..utils import get_logger
from .block_manager import AllocationError, BlockManager
from .sequence import Sequence, SequenceStatus

log = get_logger("server.scheduler")


@dataclass
class SchedulerConfig:
    max_running: int = 64
    max_prefill_batch: int = 8
    #: cap on tokens in one prefill batch (bounds score-matrix memory)
    max_prefill_tokens: int = 8192


@dataclass
class ScheduleOutput:
    prefill: list[Sequence]
    decode: list[Sequence]


class Scheduler:
    def __init__(self, block_manager: BlockManager, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        self.block_manager = block_manager
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []

    def add(self, seq: Sequence) -> None:
        seq.status = SequenceStatus.WAITING
        self.waiting.append(seq)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def schedule(self) -> ScheduleOutput:
        """Pick the work for one engine step."""
        # Admit waiting sequences first (prefill priority).
        prefill: list[Sequence] = []
        budget = self.config.max_prefill_tokens
        while (
            self.waiting
            and len(prefill) < self.config.max_prefill_batch
            and len(self.running) + len(prefill) < self.config.max_running
        ):
            seq = self.waiting[0]
            if not self.block_manager.can_allocate(seq):
                break  # FCFS: wait for pages rather than starving this seq
            try:
                self.block_manager.allocate(seq)
            except AllocationError:
                break
            # The token budget bounds prefill *compute*, which is only the
            # non-cached suffix — known exactly after allocation resolves
            # the prefix-cache hit. Roll back rather than over-commit.
            suffix = max(len(seq.prompt_tokens) - seq.num_cached_prompt, 1)
            if prefill and suffix > budget:
                self.block_manager.free_sequence(seq)
                seq.reset_allocation()
                break
            self.waiting.popleft()
            budget -= suffix
            prefill.append(seq)

        if prefill:
            return ScheduleOutput(prefill=prefill, decode=[])
        return ScheduleOutput(prefill=[], decode=list(self.running))

    def on_prefill_done(self, seqs: list[Sequence]) -> None:
        for seq in seqs:
            seq.status = SequenceStatus.RUNNING
            self.running.append(seq)

    def on_finished(self, seq: Sequence) -> None:
        seq.status = SequenceStatus.FINISHED
        self.running.remove(seq)
        self.block_manager.free_sequence(seq)
