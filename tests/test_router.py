"""BlendedRouter / PrefixAffinityTracker: the fleet-routing blend.

Pins the round-4 scheduling contract (results/routing_capacity.md): index
score dominates whenever real KV events exist; routed-affinity memory
breaks cold ties (load-aware first placement, then sticky); load breaks
the rest. The tracker is also bench.py's `estimated` comparator, so its
LRU/TTL semantics are product code, not bench-only logic.
"""


from llm_d_kv_cache_manager_tpu.kvcache import (
    BlendedRouter,
    KVCacheIndexer,
    KVCacheIndexerConfig,
    PrefixAffinityTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.keys import PodEntry

BS = 4
MODEL = "m"


def _tracker(n_pods=3, capacity=64, ttl=None):
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import ChunkedTokenDatabase

    return PrefixAffinityTracker(
        n_pods,
        capacity,
        ttl_s=ttl,
        token_processor=ChunkedTokenDatabase(TokenProcessorConfig(block_size=BS)),
    )


class TestPrefixAffinityTracker:
    def test_sticky_after_record(self):
        t = _tracker()
        toks = list(range(16))
        keys = t.keys(toks)
        assert all(t.score(keys, p) == 0 for p in range(3))
        t.record(keys, 1)
        assert t.score(keys, 1) == len(keys) == 4
        assert t.score(keys, 0) == 0

    def test_consecutive_prefix_semantics(self):
        t = _tracker()
        keys = t.keys(list(range(16)))
        # Record only the SECOND block: no consecutive prefix from block 0.
        t.record(keys[1:2], 2)
        assert t.score(keys, 2) == 0

    def test_capacity_lru_evicts_oldest(self):
        t = _tracker(capacity=4)
        a = t.keys(list(range(16)))  # 4 blocks — fills capacity
        b = t.keys(list(range(100, 116)))
        t.record(a, 0)
        t.record(b, 0)  # evicts a's blocks
        assert t.score(b, 0) == 4
        assert t.score(a, 0) == 0

    def test_ttl_expires_affinity(self):
        t = _tracker(ttl=5.0)
        keys = t.keys(list(range(16)))
        t.record(keys, 0, now=10.0)
        assert t.score(keys, 0, now=12.0) == 4
        assert t.score(keys, 0, now=16.1) == 0


class TestBlendedRouter:
    def _setup(self, loads):
        ix = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=BS)
            )
        )
        pods = ["a", "b", "c"]
        tracker = _tracker()
        router = BlendedRouter(
            score_fn=lambda toks, p: ix.score_tokens(toks, MODEL, p),
            affinity=tracker,
            loads_fn=lambda p: [loads[x] for x in p],
        )
        return ix, pods, router

    def test_index_score_dominates(self):
        loads = {"a": 0, "b": 9, "c": 0}
        ix, pods, router = self._setup(loads)
        toks = list(range(16))
        keys = ix.token_processor.tokens_to_kv_block_keys(toks, MODEL)
        ix.kv_block_index.add(keys, [PodEntry("b", "tpu_hbm")])
        # b has the warm prefix: chosen despite the worst load.
        assert router.route(toks, pods).pod == "b"
        ix.shutdown()

    def test_cold_index_uses_load_then_sticks(self):
        loads = {"a": 3, "b": 1, "c": 2}
        ix, pods, router = self._setup(loads)
        toks = list(range(16))
        first = router.route(toks, pods)
        assert first.pod == "b"  # cold everywhere -> least load
        # Same prefix again with b now heavily loaded: affinity keeps it
        # co-located instead of scattering the group.
        loads["b"] = 99
        again = router.route(toks, pods)
        assert again.pod == "b"
        assert again.affinity_score > 0
        # A DIFFERENT prefix goes by load, not to b.
        other = router.route(list(range(200, 216)), pods)
        assert other.pod == "c"
        ix.shutdown()

    def test_decision_reports_decision_time_scores(self):
        loads = {"a": 0, "b": 0, "c": 0}
        ix, pods, router = self._setup(loads)
        toks = list(range(16))
        first = router.route(toks, pods)
        # First-ever placement: everything was cold AT DECISION TIME.
        assert first.index_score == 0 and first.affinity_score == 0
        again = router.route(toks, pods)
        assert again.pod == first.pod
        assert again.affinity_score == 4  # now sticky
        ix.shutdown()
