"""KV-cache Indexer: the read-path orchestrator.

Parity with reference ``pkg/kvcache/indexer.go``: wires the tokenization
pool (with prefix store), the token→block-key processor, the block index,
and the scorer; ``get_pod_scores`` is the hot RPC
(``indexer.go:117-151``):

    prompt → tokenize (prefix-store fast path) → chunk+hash → index lookup
           → longest-prefix score → {pod: score}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..tokenization import TokenizationPool, TokenizationPoolConfig
from ..tokenization.prefixstore import Indexer as PrefixStoreIndexer
from ..tokenization.tokenizer import Tokenizer
from ..utils import get_logger
from .kvblock import (
    ChunkedTokenDatabase,
    Index,
    IndexConfig,
    TokenProcessorConfig,
    create_index,
)
from .kvblock.keys import Key
from .scorer import KVBlockScorer, KVBlockScorerConfig, ScoringStrategy, new_scorer

log = get_logger("kvcache.indexer")


@dataclass
class KVCacheIndexerConfig:
    """Composed config, one member per component
    (reference ``indexer.go:35-52``)."""

    token_processor: TokenProcessorConfig = field(default_factory=TokenProcessorConfig)
    index: IndexConfig = field(default_factory=IndexConfig)
    scorer: KVBlockScorerConfig = field(default_factory=KVBlockScorerConfig)
    tokenization_pool: TokenizationPoolConfig = field(default_factory=TokenizationPoolConfig)


class KVCacheIndexer:
    """Orchestrates scoring requests for KV-cache-aware routing."""

    def __init__(
        self,
        config: Optional[KVCacheIndexerConfig] = None,
        *,
        index: Optional[Index] = None,
        tokenizer: Optional[Tokenizer] = None,
        prefix_store: Optional[PrefixStoreIndexer] = None,
        fleet_health=None,
    ):
        """``fleet_health`` (a ``kvevents.FleetHealth``, optional): when
        attached, every score map is filtered through its routability view
        so a pod past ``pod_ttl_s``, one that published a ``PodDrained``
        goodbye, or one advertising ``draining`` in its heartbeats is
        never returned to the router — even in the window between expiry
        and the dead-pod sweep landing."""
        self.config = config or KVCacheIndexerConfig()
        self.fleet_health = fleet_health
        self.token_processor = ChunkedTokenDatabase(self.config.token_processor)
        self.kv_block_index: Index = (
            index if index is not None else create_index(self.config.index)
        )
        self.scorer: KVBlockScorer = new_scorer(self.config.scorer)
        self.tokenization_pool = TokenizationPool(
            self.config.tokenization_pool, store=prefix_store, tokenizer=tokenizer
        )
        # Fused native paths: lookup+score in one C++ call when the backend
        # offers it and the strategy matches (NativeMemoryIndex).
        fused_ok = self.scorer.strategy == ScoringStrategy.LONGEST_PREFIX
        self._fused_score = (
            getattr(self.kv_block_index, "score_longest_prefix", None)
            if fused_ok
            else None
        )
        self._fused_hash_score = (
            getattr(self.kv_block_index, "score_hashes", None) if fused_ok else None
        )

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        """Start background workers (reference ``Indexer.Run``)."""
        self.tokenization_pool.run()

    def shutdown(self) -> None:
        self.tokenization_pool.shutdown()

    # -- the hot RPC --------------------------------------------------------
    def get_pod_scores(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        placement: Optional[str] = None,
    ) -> dict[str, int]:
        """Score candidate pods by longest consecutive cached-prefix match
        for ``prompt``. Empty/None ``pod_identifiers`` scores all known pods.
        ``placement`` ("prefill"/"decode"; None = legacy, role-blind)
        excludes pods whose heartbeat-advertised role cannot serve that
        tier — a prefill-only pod never wins decode placement and vice
        versa (disaggregated serving)."""
        tokens = self.tokenization_pool.tokenize(prompt, model_name)
        log.debug("tokenized prompt", n_tokens=len(tokens), model=model_name)

        block_keys = self.token_processor.tokens_to_kv_block_keys(tokens, model_name)
        log.debug("computed block keys", n_keys=len(block_keys))
        if not block_keys:
            return {}

        pod_filter = set(pod_identifiers) if pod_identifiers else set()
        scores = self._lookup_and_score(block_keys, pod_filter, placement)
        log.debug("scored pods", scores=scores)
        return scores

    def score_tokens(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        placement: Optional[str] = None,
    ) -> dict[str, int]:
        """Scoring entry for callers that already hold token ids (the in-tree
        JAX server's router path — skips the tokenizer pool hop)."""
        pod_filter = set(pod_identifiers) if pod_identifiers else set()
        if self._fused_hash_score is not None:
            # Zero-object hot path: C++ hash chain → C++ fused lookup+score;
            # no Key instances are built at all.
            hashes = self.token_processor.prefix_hashes(tokens)
            if not hashes:
                return {}
            return self._filter_expired(
                self._fused_hash_score(model_name, hashes, pod_filter), placement
            )
        block_keys = self.token_processor.tokens_to_kv_block_keys(tokens, model_name)
        if not block_keys:
            return {}
        return self._lookup_and_score(block_keys, pod_filter, placement)

    def signal_views(
        self, pods: Optional[Sequence[str]] = None
    ) -> dict[str, dict]:
        """Heartbeat-derived per-pod signal state (age / draining /
        expired / role) for predicted-TTFT routing — the scorer-embedded
        predictor merges these with the caller-supplied serving
        telemetry (queue depth, prefill rate). ``pods`` scopes the
        locked walk to the named pods (per-request callers). ``{}``
        without an attached ``FleetHealth``: every signal then reads as
        fresh, which is exactly the in-process single-binary case."""
        if self.fleet_health is None:
            return {}
        return self.fleet_health.signal_views(pods)

    def _filter_expired(
        self, scores: dict[str, int], placement: Optional[str] = None
    ) -> dict[str, int]:
        """Routability guard: an expired, drained, or draining pod must
        never win routing, even when its swept-in-the-index state lags its
        expiry (sweeper cadence) or its entries have not been evicted yet
        (drain still in progress). ``placement`` adds the role gate."""
        if self.fleet_health is None or not scores:
            return scores
        return self.fleet_health.filter_scores(scores, placement)

    def _lookup_and_score(
        self,
        block_keys: list[Key],
        pod_filter: set[str],
        placement: Optional[str] = None,
    ) -> dict[str, int]:
        if self._fused_score is not None:
            scores = self._fused_score(block_keys, pod_filter)
            if scores is not None:
                return self._filter_expired(scores, placement)
        key_to_pods = self.kv_block_index.lookup(block_keys, pod_filter)
        return self._filter_expired(
            self.scorer.score(block_keys, key_to_pods), placement
        )
