"""LongestPrefixScorer unit tests (reference ``kvblock_scorer_test.go:35-60``)."""


from llm_d_kv_cache_manager_tpu.kvcache import (
    KVBlockScorerConfig,
    LongestPrefixScorer,
    ScoringStrategy,
    new_scorer,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import Key


def _keys(n):
    return [Key("m", i) for i in range(n)]


class TestLongestPrefixScorer:
    def test_consecutive_vs_gap(self):
        keys = _keys(3)
        # podA hits all 3 consecutively; podB hits only blocks 1,2 (not 0).
        hits = {
            keys[0]: ["podA"],
            keys[1]: ["podA", "podB"],
            keys[2]: ["podA", "podB"],
        }
        scores = LongestPrefixScorer().score(keys, hits)
        assert scores == {"podA": 3}
        assert scores.get("podB", 0) == 0

    def test_streak_breaks_mid_chain(self):
        keys = _keys(4)
        hits = {
            keys[0]: ["podA", "podB"],
            keys[1]: ["podA", "podB"],
            keys[2]: ["podA"],
            keys[3]: ["podA"],
        }
        scores = LongestPrefixScorer().score(keys, hits)
        assert scores == {"podA": 4, "podB": 2}

    def test_empty_keys(self):
        assert LongestPrefixScorer().score([], {}) == {}

    def test_no_hits(self):
        assert LongestPrefixScorer().score(_keys(3), {}) == {}

    def test_missing_middle_key_breaks_all(self):
        keys = _keys(3)
        hits = {keys[0]: ["podA"], keys[2]: ["podA"]}
        scores = LongestPrefixScorer().score(keys, hits)
        assert scores == {"podA": 1}

    def test_factory(self):
        s = new_scorer(KVBlockScorerConfig())
        assert s.strategy == ScoringStrategy.LONGEST_PREFIX
