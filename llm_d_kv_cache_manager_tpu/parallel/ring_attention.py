"""Ring attention: sequence-parallel causal attention for long-context prefill.

The reference never runs a model, so sequence scaling has no analogue there
(SURVEY §5); in this framework long context is first-class and the engine's
single-chip ceiling is ``max_model_len``. Ring attention removes it: the
sequence is sharded over a mesh axis (``sp``), every device computes flash
attention for its query shard while K/V shards rotate around the ring via
``jax.lax.ppermute`` — ICI-neighbor traffic only, no all-gather, and peak
memory O(seq/n · block) per chip.

The math is the blockwise online-softmax merge (same accumulator discipline
as ``ops.attention._flash_over_keys``): each ring step contributes a partial
(max, sum, acc) that is merged exactly, so the result is bit-consistent with
single-device flash attention up to float-associativity.

Layout notes (TPU-first):
- Q/K/V stay ``[b, s/n, heads, d]`` per shard; each ring step runs a
  BLOCKED flash scan over the held payload, so score tiles stay
  ``[s/n, key_block]`` regardless of payload length.
- The rotation count is static (mesh size), so the whole ring unrolls inside
  one jit: XLA overlaps each step's ppermute with the previous step's
  compute (double-buffered collective-permute).
- Causality is enforced with absolute positions: shard *i* holds positions
  ``i·s/n … (i+1)·s/n − 1``; a whole ring step whose K shard lies entirely
  in the query shard's future contributes nothing and its FLOPs are skipped
  by masking (the lax.scan stays shape-static as XLA requires).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_body(carry, _, *, axis_name, qf, q_pos, scale, n_shards):
    """One ring step: attend my query shard to the K/V payload currently
    held — as a BLOCKED flash scan (``ops.attention._flash_over_keys``
    seeded with the carried accumulators), so the score tile stays
    [s_q, key_block] however long the rotating payload is (the payload
    carries context slices in the sp-prefill path; an unblocked
    [s_q, s_k] tile would grow linearly with context and OOM exactly in
    the long-context regime this path exists for) — then pass the
    payload to the next device on the ring."""
    from ..ops.attention import FLASH_KEY_BLOCK, _flash_over_keys

    k_cur, v_cur, kpos_cur, kvalid_cur, m, denom, acc = carry

    m_new, l_new, acc_new = _flash_over_keys(
        qf,
        jnp.moveaxis(k_cur, 1, 2),  # [b, s_k, n_kv, d] -> [b, n_kv, s_k, d]
        jnp.moveaxis(v_cur, 1, 2),
        kvalid_cur,
        kpos_cur,
        q_pos,
        scale,
        FLASH_KEY_BLOCK,
        return_accumulators=True,
        init_state=(m, denom, acc),
    )

    # Rotate K/V/pos/validity to the next device; neighbor-only ICI traffic.
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
    v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
    kpos_nxt = jax.lax.ppermute(kpos_cur, axis_name, perm)
    kvalid_nxt = jax.lax.ppermute(kvalid_cur, axis_name, perm)
    return (k_nxt, v_nxt, kpos_nxt, kvalid_nxt, m_new, l_new, acc_new), None


def ring_attention_shard(
    q: jnp.ndarray,  # [b, s_shard, n_heads, d]
    k: jnp.ndarray,  # [b, s_k_shard, n_kv_heads, d]
    v: jnp.ndarray,  # [b, s_k_shard, n_kv_heads, d]
    *,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    q_pos: Optional[jnp.ndarray] = None,  # [b, s_shard] absolute positions
    k_pos: Optional[jnp.ndarray] = None,  # [b, s_k_shard] key positions
    k_valid: Optional[jnp.ndarray] = None,  # [b, s_k_shard] key padding mask
    init_state: Optional[tuple] = None,  # (m, l, acc) seed for the flash state
) -> jnp.ndarray:
    """Per-shard ring attention body. Must run inside ``shard_map`` (or pmap)
    over ``axis_name``; q (and k/v) are this device's sequence shard.

    Defaults reproduce plain causal self-attention over the global
    sequence (positions derived from the shard index). The engine's
    sp-prefill passes a LONGER rotating key payload than the query shard
    ([context slice ++ chunk slice], so ``k_pos``/``k_valid`` are
    decoupled from ``q_pos``): context keys ride at position -1 (visible
    to every chunk query), chunk keys at absolute positions, padding
    masked — the ring merge is exact, so the result equals a
    single-device online softmax over [context ++ chunk].
    """
    b, s, n_q, d = q.shape
    s_k = k.shape[1]
    n_kv = k.shape[2]
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5
    n_shards = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)

    if q_pos is None:
        q_pos = (my * s + jnp.arange(s))[None, :].astype(jnp.int32)
        q_pos = jnp.broadcast_to(q_pos, (b, s))
    if k_pos is None:
        if s_k != s:
            raise ValueError("k_pos required when k length differs from q")
        k_pos = q_pos  # at step 0 each device holds its own K shard
    if k_valid is None:
        k_valid = jnp.ones((b, s_k), bool)

    qf = q.astype(jnp.float32).reshape(b, s, n_kv, group, d)
    if init_state is None:
        m0 = jnp.full((b, n_kv, group, s), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, n_kv, group, s), jnp.float32)
        acc0 = jnp.zeros((b, n_kv, group, s, d), jnp.float32)
    else:
        m0, l0, acc0 = init_state

    body = partial(
        _ring_body,
        axis_name=axis_name,
        qf=qf,
        q_pos=q_pos,
        scale=scale,
        n_shards=n_shards,
    )
    (_, _, _, _, m, denom, acc), _ = jax.lax.scan(
        body, (k, v, k_pos, k_valid, m0, l0, acc0), None, length=n_shards
    )

    out = acc / jnp.where(denom > 0, denom, 1.0)[..., None]
    # A query with no visible keys cannot happen here (it always sees
    # itself), so no NaN guard is needed beyond the denom>0 clamp.
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, n_q, d).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [b, seq, n_heads, d] — seq divisible by mesh axis size
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel causal attention over ``mesh[axis_name]``.

    Shards the sequence dimension, runs the ring under ``shard_map``, and
    returns the output with the same (sequence-sharded) layout. Jit-able and
    composable with tp sharding on the head dimension of the surrounding
    projections.
    """
    from .mesh import shard_map_compat

    n = mesh.shape[axis_name]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by mesh axis "
            f"{axis_name!r} of size {n}"
        )
    spec = P(None, axis_name, None, None)
    fn = shard_map_compat(
        partial(ring_attention_shard, axis_name=axis_name, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
