"""RMSNorm.

Plain jnp: XLA fuses the reduction + scale into neighboring ops on TPU; a
hand-written Pallas kernel buys nothing here (HBM-bound elementwise work
fuses into the surrounding matmuls).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    offset: float = 0.0,
) -> jnp.ndarray:
    """Root-mean-square layer norm (Llama-style, no mean subtraction).

    Statistics are computed in float32 regardless of input dtype (matches
    reference implementations' numerics), output cast back to input dtype.
    ``offset`` implements Gemma's ``(1 + w)`` scaling convention (the HF
    checkpoint stores ``w``; the model applies ``1 + w``).
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    scale = weight.astype(jnp.float32) + offset
    return (normed * scale).astype(dtype)
