"""Fetch side of the KV-transfer channel: DEALER with bounded timeouts.

A pull is strictly an optimization — every failure mode (dead peer, slow
link, truncated chain, garbage payload) must degrade to "recompute the
prefix cold", never wedge or crash the puller. So:

- every ``fetch`` polls with a hard deadline and raises ``TransferError``
  on expiry;
- after a timeout the socket is torn down and rebuilt, so a late straggler
  reply can never be mis-matched to the next request;
- successful fetches report ``(wire_bytes, seconds)`` to ``on_sample`` —
  the measured-link feed of the router's transfer-vs-recompute cost model.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ...utils import get_logger
from .protocol import BlockPayload, decode_response, encode_request

log = get_logger("kvcache.transfer.client")


class TransferError(RuntimeError):
    """A fetch failed (timeout, service error, undecodable reply)."""


@dataclass
class TransferClientConfig:
    endpoint: str = "tcp://localhost:5558"
    timeout_s: float = 10.0


class KVTransferClient:
    def __init__(
        self,
        config: TransferClientConfig,
        on_sample: Optional[Callable[[int, float], None]] = None,
    ):
        self.config = config
        self.on_sample = on_sample
        self._mu = threading.Lock()
        self._sock = None
        self._closed = False

    def _socket(self):
        import zmq

        if self._sock is None:
            ctx = zmq.Context.instance()
            self._sock = ctx.socket(zmq.DEALER)
            self._sock.connect(self.config.endpoint)
        return self._sock

    def _reset_socket(self) -> None:
        if self._sock is not None:
            self._sock.close(linger=0)
            self._sock = None

    def fetch(
        self,
        model_name: str,
        block_hashes: Sequence[int],
        max_blocks: Optional[int] = None,
    ) -> tuple[list[BlockPayload], bool]:
        """Fetch the longest resident prefix of ``block_hashes`` from the
        peer. Returns ``(blocks, complete)``; raises ``TransferError`` on
        timeout/service failure (callers fall back to cold prefill)."""
        import zmq

        if not block_hashes:
            return [], True
        with self._mu:
            if self._closed:
                raise TransferError("client closed")
            sock = self._socket()
            t0 = time.perf_counter()
            try:
                sock.send(encode_request(model_name, block_hashes, max_blocks))
                if not sock.poll(int(self.config.timeout_s * 1000), zmq.POLLIN):
                    self._reset_socket()  # a late reply must not leak forward
                    raise TransferError(
                        f"fetch timed out after {self.config.timeout_s}s "
                        f"({self.config.endpoint})"
                    )
                frames = sock.recv_multipart()
            except zmq.ZMQError as e:
                self._reset_socket()
                raise TransferError(f"fetch failed: {e}") from e
            dt = time.perf_counter() - t0
        decoded = decode_response(frames[-1])
        if decoded is None:
            raise TransferError("undecodable transfer response")
        blocks, complete, error = decoded
        if error is not None:
            raise TransferError(f"peer refused fetch: {error}")
        if self.on_sample is not None and blocks:
            self.on_sample(sum(b.wire_bytes for b in blocks), dt)
        return blocks, complete

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            self._reset_socket()
