"""Fleet health: sequence-gap detection, liveness tracking, dead-pod sweep.

The indexer's view of the fleet is event-sourced and therefore only as
truthful as the event stream. Three failure modes rot it:

1. **Dropped events** — the publisher's bounded send retry drops batches on
   overflow; a lost ``BlockRemoved`` leaves phantom locality, a lost
   ``BlockStored`` hides real warmth. Every message carries a per-publisher
   ``seq``; this module tracks last-seen seq per (pod, model) and flags a
   *gap* whenever the stream skips forward — the pod's view is then
   **suspect** until an ``IndexSnapshot`` resync replaces it wholesale.
2. **Crashed pods** — a dead pod never emits its evictions, so its
   ``BlockStored`` entries would live in the index forever. Pods publish
   ``Heartbeat`` events; after ``pod_ttl_s`` of silence the sweeper evicts
   the pod from the index (``Index.evict_pod``) and the scorer filter stops
   returning it even before the sweep lands.
3. **Silent publisher drops** — a dropped batch with no later traffic never
   produces a detectable seq gap. Heartbeats carry the publisher's monotone
   ``dropped_batches`` count, so loss is detected even across idle periods.
4. **Draining/drained pods** (PR 4) — a pod mid-drain advertises
   ``draining`` in its heartbeats (routing should stop sending it new
   prefixes immediately) and publishes a ``PodDrained`` goodbye when the
   drain completes; the goodbye evicts the pod's entries at once instead of
   waiting out ``pod_ttl_s`` — a rolling restart must not serve stale
   locality for a TTL, nor does it need to.

All tracking is observation-only until configured: ``pod_ttl_s=0`` (the
default) disables expiry/sweeping entirely, and a pool without an attached
``FleetHealth`` behaves bit-identically to previous rounds.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ...utils import get_logger
from ..kvblock import Index
from ..metrics import collector

log = get_logger("kvcache.kvevents.health")


@dataclass
class FleetHealthConfig:
    #: seconds of silence after which a pod is expired and swept from the
    #: index. 0 (default) disables liveness expiry — observation only.
    pod_ttl_s: float = 0.0
    #: sweeper cadence; clamped to pod_ttl_s/4 when a TTL is set so expiry
    #: is detected well within one TTL.
    sweep_interval_s: float = 1.0


@dataclass
class _PodState:
    #: wall-clock time of the last message seen from this pod (any event)
    last_seen: float = 0.0
    #: last-seen publisher seq per model topic
    last_seq: dict[str, int] = field(default_factory=dict)
    #: gap (or reported drop) observed and not yet repaired by a resync
    suspect: bool = False
    #: swept from the index by the TTL sweeper; clears on any new message
    swept: bool = False
    #: last publisher-reported dropped_batches count (from Heartbeat)
    reported_drops: int = 0
    #: pod advertised draining via heartbeat — routable no longer, but its
    #: entries stay until the PodDrained goodbye (or TTL) evicts them
    draining: bool = False
    #: pod published its PodDrained goodbye; treated as expired immediately.
    #: Clears on any new message (the pod restarted under the same identity).
    drained: bool = False
    #: serving role advertised via heartbeat ("prefill"/"decode"/
    #: "kvstore"); None = mixed/unknown — eligible for every placement
    #: (observation-only default). Set AND cleared by heartbeats, the
    #: authoritative carrier. ``kvstore`` pods are excluded from EVERY
    #: serving placement: they hold demoted blocks, they never serve.
    role: Optional[str] = None
    #: remote-tier headroom the pod last advertised (pages its remote
    #: store will still accept); None = never advertised (REMOTE_TIER off)
    headroom: Optional[int] = None
    #: blocks revoked from this pod by BadBlock events (KV_INTEGRITY) —
    #: a climbing count is the bad-block-storm signal the runbook keys on
    bad_blocks: int = 0


class FleetHealth:
    """Per-pod liveness + stream-integrity tracker shared by the ingestion
    pool (writer), the sweeper thread, and the scorer read path (filter)."""

    def __init__(
        self,
        config: Optional[FleetHealthConfig] = None,
        *,
        clock=time.monotonic,
    ):
        self.config = config or FleetHealthConfig()
        self._clock = clock
        self._mu = threading.Lock()
        self._pods: dict[str, _PodState] = {}  # guarded_by: _mu
        # Monotone counters (mirrored into the metrics collector).
        self.gaps_detected = 0  # guarded_by: _mu
        self.resyncs_applied = 0  # guarded_by: _mu
        self.pods_swept = 0  # guarded_by: _mu
        self.heartbeats_seen = 0  # guarded_by: _mu
        self.publisher_drops_reported = 0  # guarded_by: _mu
        self.pods_drained = 0  # guarded_by: _mu
        self.prefills_completed = 0  # guarded_by: _mu
        #: total blocks revoked by BadBlock events (KV_INTEGRITY)
        self.bad_blocks_reported = 0  # guarded_by: _mu
        #: fleet-controller membership changes (observe_pod_added/_removed)
        self.pods_added = 0  # guarded_by: _mu
        self.pods_removed = 0  # guarded_by: _mu
        #: sticky "a kvstore role has ever been advertised" latch: lets the
        #: role-blind (placement=None) filter keep its zero-lookup fast
        #: path on fleets with no remote tier — the overwhelmingly common
        #: case — while kvstore fleets pay the role cut they need.
        self._any_kvstore = False  # guarded_by: _mu
        self._sweep_thread: Optional[threading.Thread] = None
        self._sweep_stop = threading.Event()
        #: optional observer ``(pod) -> None`` fired after a dead pod is
        #: swept from the index (the OBS_LIFECYCLE ledger ends the pod's
        #: tracked residencies through it). Called OUTSIDE the lock; a
        #: raising observer must not break the sweep.
        self.on_pod_swept = None

    # -- ingestion-side observations (called from pool workers) -------------
    def observe_message(self, pod: str, model: str, seq: int) -> bool:
        """Record a message arrival; returns True when a seq gap was
        detected (caller marks the pod's view suspect → resync repairs)."""
        now = self._clock()
        gap = False
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = now
            st.swept = False  # pod is alive again — revive it
            if st.drained:
                # Traffic after a PodDrained goodbye = the pod restarted
                # under the same identity: fully resurrect it (a sticky
                # draining flag would otherwise unroute the new pod until
                # its first non-draining heartbeat — forever, when
                # heartbeats are disabled).
                st.drained = False
                st.draining = False
            last = st.last_seq.get(model)
            if last is not None and seq > last + 1:
                gap = True
                st.suspect = True
                self.gaps_detected += 1
            elif last is not None and seq < last and seq > 0:
                # Regression: a publisher restart whose seq-0 message was
                # itself lost (the loss case this module exists for), or
                # out-of-order redelivery. Flag ONE gap and REBASE to the
                # new stream — keeping the old high-water mark would flag
                # every subsequent message of a restarted stream as a
                # fresh gap until it passed the old count (a WARN storm
                # that re-marks the pod suspect after every resync).
                # Rebasing costs at most one extra catch-up gap if the
                # regression was a genuine straggler; both paths end in
                # the same repair (suspect → resync).
                gap = True
                st.suspect = True
                self.gaps_detected += 1
            st.last_seq[model] = seq
        if gap:
            collector.bump("fleet_gaps")
            collector.fleet_gaps.inc()
            log.warning(
                "event seq gap detected; pod view suspect until resync",
                pod=pod, model=model, seq=seq,
            )
        return gap

    def observe_heartbeat(
        self,
        pod: str,
        dropped_batches: int,
        draining: bool = False,
        role: Optional[str] = None,
        headroom: Optional[int] = None,
    ) -> None:
        """A heartbeat proves liveness and reports the publisher's drop
        count; an increase means batches were lost even if no later seq
        ever reveals the gap. ``draining`` advertises a mid-drain pod —
        the scorer stops returning it immediately (set AND cleared here:
        heartbeats are the authoritative carrier of drain intent).
        ``role`` advertises the pod's serving tier for the placement
        filter; None (mixed/legacy heartbeats) clears it. ``headroom``
        advertises remote-store acceptance capacity (demotion-target
        selection + observability); None leaves the last value — a legacy
        heartbeat from a pod that flipped the knob off mid-run is
        indistinguishable from one that predates it, and zeroing on
        absence would erase real advertisements under mixed fleets."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = self._clock()
            st.swept = False
            st.draining = draining
            st.role = role if role in ("prefill", "decode", "kvstore") else None
            if st.role == "kvstore":
                self._any_kvstore = True
            if headroom is not None:
                st.headroom = max(int(headroom), 0)
            self.heartbeats_seen += 1
            if dropped_batches < st.reported_drops:
                # Publisher restart: its drop counter restarted too. Rebase
                # the baseline or the new publisher's first drops (up to
                # the old total) would be silently masked. Drops that
                # happened before this first post-restart heartbeat still
                # surface as seq gaps via observe_message.
                st.reported_drops = dropped_batches
            new_drops = dropped_batches - st.reported_drops
            if new_drops:
                st.reported_drops = dropped_batches
                st.suspect = True
                self.publisher_drops_reported += new_drops
        if new_drops:
            collector.bump("fleet_publisher_drops", new_drops)
            collector.fleet_publisher_drops.inc(new_drops)
            log.warning(
                "publisher reported dropped batches; pod view suspect",
                pod=pod, new_drops=new_drops, total=dropped_batches,
            )

    def observe_resync(self, pod: str) -> None:
        """An ``IndexSnapshot`` replaced the pod's view — clear suspicion."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = self._clock()
            st.suspect = False
            st.swept = False
            self.resyncs_applied += 1
        collector.bump("fleet_resyncs")
        collector.fleet_resyncs.inc()

    def observe_drained(self, pod: str) -> None:
        """A ``PodDrained`` goodbye: the pod finished draining and its
        entries have been evicted — treat it as expired immediately (no
        ``pod_ttl_s`` wait) until it is heard from again."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = self._clock()
            st.drained = True
            st.draining = False  # the drain completed; drained supersedes
            st.suspect = False  # its view is now empty, nothing to repair
            self.pods_drained += 1
        collector.bump("fleet_pods_drained")
        collector.fleet_pods_drained.inc()
        log.warning("pod drained; evicted from routing immediately", pod=pod)

    # -- fleet-controller membership (kvcache/controller) -------------------
    def observe_pod_added(self, pod: str) -> None:
        """A fleet-controller scale-up provisioned this pod: register it
        live immediately, so routing can count on it before its first
        heartbeat lands (a cold TTL wait on a pod the controller just
        revived warm would waste exactly the revival)."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = self._clock()
            st.swept = False
            st.drained = False
            st.draining = False
            self.pods_added += 1

    def observe_pod_removed(self, pod: str) -> None:
        """A fleet-controller scale-down is retiring this pod: unroute it
        NOW, before its drain even starts — the live migrations moving its
        sequences must not race fresh placements onto the victim. The
        ``PodDrained`` goodbye (or the TTL) finishes the eviction."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = self._clock()
            st.draining = True
            self.pods_removed += 1

    def observe_bad_block(self, pod: str, count: int = 1) -> None:
        """A ``BadBlock`` revocation named ``pod`` as the holder of
        ``count`` corrupt copies (KV_INTEGRITY). Pure observation — the
        ingestion pool already evicted the index entries; this keeps the
        per-pod tally the bad-block-storm runbook reads. Deliberately does
        NOT touch liveness: the event proves the DETECTOR is alive, not
        the holder."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.bad_blocks += count
            self.bad_blocks_reported += count

    def observe_prefill_complete(self, pod: str) -> None:
        """A ``PrefillComplete`` event: a prefill-role pod finished a
        request's ingest and its chain is exportable — handoff supply for
        disaggregated serving (counted; the chain's own BlockStored events
        carry the locality)."""
        with self._mu:
            st = self._pods.setdefault(pod, _PodState())
            st.last_seen = self._clock()
            st.swept = False
            self.prefills_completed += 1

    # -- read-side queries ---------------------------------------------------
    def is_expired(self, pod: str) -> bool:
        """True when the pod passed its TTL (or was swept, or said its
        ``PodDrained`` goodbye) and has not been heard from since. Unknown
        pods are NOT expired: entries may predate this monitor's
        attachment, and expiring them would break the observation-only
        default."""
        ttl = self.config.pod_ttl_s
        with self._mu:
            st = self._pods.get(pod)
            if st is None:
                return False
            if st.swept or st.drained:
                return True
            if ttl <= 0:
                return False
            return (self._clock() - st.last_seen) > ttl

    def is_suspect(self, pod: str) -> bool:
        with self._mu:
            st = self._pods.get(pod)
            return bool(st and st.suspect)

    def is_draining(self, pod: str) -> bool:
        with self._mu:
            st = self._pods.get(pod)
            return bool(st and (st.draining or st.drained))

    def is_routable(self, pod: str) -> bool:
        """Should routing consider this pod at all? Excludes expired pods
        (TTL/swept/drained) and pods advertising a drain in progress —
        sending a new prefix to a pod that will evict it in seconds just
        burns the transfer and the client's retry. One lock acquisition
        (not is_expired + is_draining): this runs per pod on the scoring
        hot path, contended with the ingestion workers."""
        ttl = self.config.pod_ttl_s
        with self._mu:
            st = self._pods.get(pod)
            if st is None:
                return True  # unknown pods stay routable (observation-only)
            if st.swept or st.drained or st.draining:
                return False
            if ttl <= 0:
                return True
            return (self._clock() - st.last_seen) <= ttl

    def signal_views(
        self, pods: Optional[Sequence[str]] = None
    ) -> dict[str, dict]:
        """Predictor-facing snapshot in ONE locked cut: per-pod signal
        age (the staleness gate's input — signals older than 2x the
        heartbeat cadence decay to conservative defaults), draining/
        expired state, and advertised role — the HTTP-deployment hook
        for assembling ``predictor.PodSignals`` (queue depth and the
        prefill-rate EMA ride the serving plane's own telemetry; this
        carries the heartbeat-derived half). ``pods`` scopes the locked
        walk to the named pods (the per-request path names a handful;
        an O(fleet) cut per scoring request would scale lock-hold time
        with fleet size); None walks everything (selection cadence).
        Like ``pod_views``, a point-in-time read."""
        ttl = self.config.pod_ttl_s
        now = self._clock()
        with self._mu:
            items = (
                [(p, self._pods[p]) for p in pods if p in self._pods]
                if pods is not None
                else list(self._pods.items())
            )
            return {
                pod: {
                    "age_s": (
                        max(now - st.last_seen, 0.0)
                        if st.last_seen > 0
                        else None
                    ),
                    "draining": st.draining or st.drained,
                    "expired": bool(
                        st.swept
                        or st.drained
                        or (ttl > 0 and (now - st.last_seen) > ttl)
                    ),
                    "role": st.role,
                }
                for pod, st in items
            }

    def role_of(self, pod: str) -> Optional[str]:
        """The pod's heartbeat-advertised role ("prefill"/"decode"/
        "kvstore"), or None for mixed/unknown pods."""
        with self._mu:
            st = self._pods.get(pod)
            return st.role if st is not None else None

    def headroom_of(self, pod: str) -> Optional[int]:
        """Remote-store headroom the pod last advertised (pages), or None
        when it never has (REMOTE_TIER off / pre-knob fleet)."""
        with self._mu:
            st = self._pods.get(pod)
            return st.headroom if st is not None else None

    def remote_targets(self) -> dict[str, int]:
        """Demotion-target view: every routable-alive pod that has
        advertised remote headroom, with the last advertised value —
        kvstore pods first-class, but serving peers with headroom count
        too. Like ``pod_views``, this is the HTTP-deployment hook (a
        control plane assembling ``REMOTE_PEERS`` for the fleet from
        heartbeat state); the in-process pusher ranks its static peer
        list by push-ack headroom instead. One locked cut
        (scrape/selection cadence, not per event)."""
        ttl = self.config.pod_ttl_s
        now = self._clock()
        with self._mu:
            return {
                pod: st.headroom
                for pod, st in self._pods.items()
                if st.headroom is not None
                and not (st.swept or st.drained or st.draining)
                and not (ttl > 0 and (now - st.last_seen) > ttl)
            }

    def filter_scores(
        self, scores: dict[str, int], placement: Optional[str] = None
    ) -> dict[str, int]:
        """Drop expired and draining pods from a score map — the guarantee
        that routing never targets a pod past its TTL (even before the
        sweeper lands) nor one that advertised a drain in progress.
        ``placement`` ("prefill"/"decode"; None = legacy, role-blind)
        additionally excludes pods whose advertised role cannot serve that
        tier — a prefill-only pod must never win decode placement. A
        ``kvstore`` pod (remote-tier holder) serves NOTHING and is
        excluded from every SERVING placement, including the role-blind
        legacy one — its warmth is reachable only as a pull source.
        ``placement="pull_source"`` is that read path: no role exclusion
        at all (any pod may export its chains over the transfer fabric),
        only the liveness gate — a remote-arm query for the holders'
        warmth must not be blanked by the very filter that keeps them out
        of serving."""
        if not scores:
            return scores
        if placement == "pull_source":
            wrong: set = set()
            roles: dict[str, Optional[str]] = {}
        elif placement is None:
            # One locked cut for the latch AND (when needed) the roles —
            # this runs per scoring request, and a second acquisition
            # would double the lock churn is_routable already pays.
            with self._mu:
                roles = (
                    {
                        p: (st.role if (st := self._pods.get(p)) else None)
                        for p in scores
                    }
                    if self._any_kvstore
                    else {}
                )
            wrong = {"kvstore"}
        else:
            with self._mu:
                roles = {
                    p: (st.role if (st := self._pods.get(p)) else None)
                    for p in scores
                }
            wrong = {
                "kvstore",
                "prefill" if placement == "decode" else "decode",
            }
        out = {
            p: s
            for p, s in scores.items()
            if roles.get(p) not in wrong and self.is_routable(p)
        }
        return out if len(out) != len(scores) else scores

    def pod_views(self) -> dict[str, dict]:
        """Planner-facing snapshot: per-pod role/draining/expired state in
        one locked cut. This (with ``role_of``) is the HTTP-deployment
        hook for assembling ``router.PodView``s from heartbeat state at a
        scorer-embedded planner; the in-process coordinator builds its
        views from live ``PodServer`` attributes instead
        (``disagg.views_from_pods``)."""
        ttl = self.config.pod_ttl_s
        now = self._clock()
        with self._mu:
            return {
                pod: {
                    "role": st.role,
                    "draining": st.draining or st.drained,
                    "expired": bool(
                        st.swept
                        or st.drained
                        or (ttl > 0 and (now - st.last_seen) > ttl)
                    ),
                }
                for pod, st in self._pods.items()
            }

    def scrape_views(self, pods: Sequence[str]) -> dict[str, dict]:
        """Federator-facing liveness cut (``OBS_FED``): per registered
        scrape target, is the pod worth polling at all? ONE locked walk
        over the named pods — a fleet scrape must not pay N ``is_expired``
        acquisitions against the ingestion workers. Unlike ``pod_views``
        this covers pods the monitor has never heard from (``known:
        False``, not expired — the observation-only default: a target the
        operator registered but no event has named yet still gets
        scraped). An ``expired`` pod is skipped by the scrape outright,
        so a dead pod costs one skip, not one timeout per surface."""
        ttl = self.config.pod_ttl_s
        now = self._clock()
        with self._mu:
            out = {}
            for pod in pods:
                st = self._pods.get(pod)
                if st is None:
                    out[pod] = {
                        "known": False,
                        "expired": False,
                        "suspect": False,
                        "draining": False,
                        "age_s": None,
                    }
                    continue
                out[pod] = {
                    "known": True,
                    "expired": bool(
                        st.swept
                        or st.drained
                        or (ttl > 0 and (now - st.last_seen) > ttl)
                    ),
                    "suspect": st.suspect,
                    "draining": st.draining or st.drained,
                    "age_s": (
                        round(max(now - st.last_seen, 0.0), 3)
                        if st.last_seen > 0
                        else None
                    ),
                }
            return out

    def snapshot(self) -> dict:
        """Counters + per-pod state for ``/stats``."""
        with self._mu:
            pods = {
                pod: {
                    "suspect": st.suspect,
                    "swept": st.swept,
                    "draining": st.draining,
                    "drained": st.drained,
                    "age_s": round(self._clock() - st.last_seen, 3),
                    # Role/headroom keys only for advertising pods: a
                    # knob-less fleet's snapshot payload stays bit-identical
                    # legacy.
                    **({"role": st.role} if st.role is not None else {}),
                    **(
                        {"headroom": st.headroom}
                        if st.headroom is not None
                        else {}
                    ),
                    # Key only for pods with revoked blocks: knob-less
                    # fleets keep bit-identical snapshot payloads.
                    **(
                        {"bad_blocks": st.bad_blocks}
                        if st.bad_blocks
                        else {}
                    ),
                }
                for pod, st in self._pods.items()
            }
            # Counters read under the same lock as the per-pod state so one
            # scrape is a consistent cut (found by kvlint lock-discipline:
            # the unguarded reads could pair a new counter with old state).
            return {
                "pod_ttl_s": self.config.pod_ttl_s,
                "gaps_detected": self.gaps_detected,
                "resyncs_applied": self.resyncs_applied,
                "pods_swept": self.pods_swept,
                "heartbeats_seen": self.heartbeats_seen,
                "publisher_drops_reported": self.publisher_drops_reported,
                "pods_drained": self.pods_drained,
                # Key appears only once disagg traffic exists: the no-knobs
                # /stats payload keeps its legacy field set.
                **(
                    {"prefills_completed": self.prefills_completed}
                    if self.prefills_completed
                    else {}
                ),
                # Same rule: key appears only once a BadBlock landed.
                **(
                    {"bad_blocks_reported": self.bad_blocks_reported}
                    if self.bad_blocks_reported
                    else {}
                ),
                # Same rule: keys appear only once a fleet controller has
                # actually resized the fleet.
                **(
                    {"pods_added": self.pods_added} if self.pods_added else {}
                ),
                **(
                    {"pods_removed": self.pods_removed}
                    if self.pods_removed
                    else {}
                ),
                "pods": pods,
            }

    # -- dead-pod sweeper ----------------------------------------------------
    def sweep(self, index: Index) -> list[str]:
        """Evict every TTL-expired pod from the index (one shot). Returns
        the pods swept. Safe to call concurrently with ingestion: a revived
        pod's later events re-add its entries, same eventual-consistency
        contract as normal eviction."""
        ttl = self.config.pod_ttl_s
        if ttl <= 0:
            return []
        now = self._clock()
        with self._mu:
            stale = [
                pod
                for pod, st in self._pods.items()
                if not st.swept and (now - st.last_seen) > ttl
            ]
            for pod in stale:
                self._pods[pod].swept = True
        swept = []
        for pod in stale:
            try:
                index.evict_pod(pod)
            except Exception:
                # Un-mark so the next sweep retries; routing stays safe
                # meanwhile because is_expired() is true via the TTL check
                # regardless of the swept flag.
                log.exception("dead-pod sweep failed", pod=pod)
                with self._mu:
                    st = self._pods.get(pod)
                    if st is not None:
                        st.swept = False
                continue
            swept.append(pod)
            with self._mu:
                self.pods_swept += 1
            collector.bump("fleet_pods_swept")
            collector.fleet_pods_swept.inc()
            cb = self.on_pod_swept
            if cb is not None:
                try:
                    cb(pod)
                except Exception:
                    log.exception("on_pod_swept observer failed", pod=pod)
            log.warning("swept dead pod from index", pod=pod, ttl_s=ttl)
        return swept

    def start_sweeper(self, index: Index) -> None:
        """Background TTL sweeper (idempotent; no-op when pod_ttl_s == 0)."""
        if self.config.pod_ttl_s <= 0:
            return
        if self._sweep_thread is not None and self._sweep_thread.is_alive():
            return
        interval = min(
            self.config.sweep_interval_s, max(self.config.pod_ttl_s / 4, 0.01)
        )
        self._sweep_stop.clear()

        def run():
            while not self._sweep_stop.wait(interval):
                self.sweep(index)

        self._sweep_thread = threading.Thread(
            target=run, name="fleet-health-sweeper", daemon=True
        )
        self._sweep_thread.start()

    def stop_sweeper(self) -> None:
        self._sweep_stop.set()
        if self._sweep_thread is not None:
            self._sweep_thread.join(timeout=5)
            self._sweep_thread = None
