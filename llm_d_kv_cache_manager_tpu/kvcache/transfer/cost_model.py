"""Transfer-vs-recompute cost model for the routing decision.

Same philosophy as the engine's host-tier admission model and the
cost-aware index's budget accounting: decisions come from MEASURED rates
of this deployment, never from assumed constants — a fast-DCN fleet pulls
aggressively, a slow link makes the model fall back to classic routing,
and until both rates have samples the model abstains ("route_warm" =
exactly the legacy router).

Per request the router asks: the warmest pod holds ``warm_blocks`` of this
prompt's prefix but carries ``warm_load`` outstanding requests; the
least-loaded pod is cold. Three options are costed end-to-end:

- ``route_warm``  — queue behind the warm pod, prefill only the suffix;
- ``pull``        — land on the cold pod, DMA the warm prefix over the
  transfer channel, prefill only the suffix;
- ``cold``        — land on the cold pod, recompute the whole prompt.

Queueing is modeled as ``load x est_service_s`` (the same coarse
outstanding-requests proxy ``BlendedRouter`` already ranks by); transfer
time as ``blocks x block_bytes / transfer_rate`` (EMA of client fetch
samples); prefill time as ``tokens / prefill_rate`` (EMA of engine chunk
samples, the engine's own ``_prefill_rate`` feed).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

ROUTE_WARM = "route_warm"
PULL = "pull"
COLD = "cold"


@dataclass
class TransferCostModelConfig:
    #: wire bytes per KV block (k+v pages; ``Engine.kv_block_bytes``)
    block_bytes: int
    block_size: int = 16
    #: modeled queue delay per outstanding request on a pod
    est_service_s: float = 0.05
    #: never pull chains shorter than this (per-fetch overhead floor)
    min_pull_blocks: int = 1
    #: cap on blocks one pull can actually move — set to the transfer
    #: plane's response cap (``TRANSFER_MAX_BLOCKS``) so the modeled pull
    #: matches the mechanism; None = uncapped fetches
    max_pull_blocks: Optional[int] = None


class TransferCostModel:
    def __init__(self, config: TransferCostModelConfig):
        if config.block_bytes < 1:
            raise ValueError("block_bytes must be >= 1")
        self.config = config
        self._mu = threading.Lock()
        self._transfer_rate: Optional[float] = None  # bytes/s  # guarded_by: _mu
        self._prefill_rate: Optional[float] = None  # tokens/s  # guarded_by: _mu

    # -- measured-rate feeds ------------------------------------------------
    @staticmethod
    def _ema(prev: Optional[float], sample: float, alpha: float = 0.3) -> float:
        return sample if prev is None else (1 - alpha) * prev + alpha * sample

    def observe_transfer(self, n_bytes: int, seconds: float) -> None:
        """Feed one measured fetch (``KVTransferClient.on_sample``)."""
        if n_bytes <= 0 or seconds <= 0:
            return
        with self._mu:
            self._transfer_rate = self._ema(self._transfer_rate, n_bytes / seconds)

    def observe_prefill(self, n_tokens: int, seconds: float) -> None:
        if n_tokens <= 0 or seconds <= 0:
            return
        with self._mu:
            self._prefill_rate = self._ema(self._prefill_rate, n_tokens / seconds)

    def seed_rates(
        self,
        transfer_bytes_s: Optional[float] = None,
        prefill_tokens_s: Optional[float] = None,
    ) -> None:
        """Pin rates directly — for callers that already measure them
        elsewhere (the engine's ``_prefill_rate`` EMA, a known link) and
        for deterministic tests/benchmarks. Non-positive values are
        ignored (same guard as ``observe_*``): a zero rate is "nothing
        measured", never a divisor."""
        with self._mu:
            if transfer_bytes_s is not None and transfer_bytes_s > 0:
                self._transfer_rate = transfer_bytes_s
            if prefill_tokens_s is not None and prefill_tokens_s > 0:
                self._prefill_rate = prefill_tokens_s

    @property
    def transfer_rate(self) -> Optional[float]:
        with self._mu:
            return self._transfer_rate

    @property
    def prefill_rate(self) -> Optional[float]:
        with self._mu:
            return self._prefill_rate

    # -- the decision -------------------------------------------------------
    def decide(
        self,
        prompt_len: int,
        warm_blocks: int,
        warm_load: float,
        cold_load: float,
    ) -> str:
        """Pick ``route_warm`` / ``pull`` / ``cold`` for one request.

        Abstains (``route_warm``) until BOTH rates are measured — the
        model must never un-warm routing on guesses, mirroring the host
        tier's bootstrap rule."""
        cfg = self.config
        with self._mu:
            tr, pr = self._transfer_rate, self._prefill_rate
        if tr is None or pr is None or warm_blocks < cfg.min_pull_blocks:
            return ROUTE_WARM
        # A pull can only move what the transfer plane will serve; the
        # warm pod itself still reuses its FULL prefix — the two arms see
        # different reusable lengths under the cap.
        pull_blocks = warm_blocks
        if cfg.max_pull_blocks is not None:
            pull_blocks = min(pull_blocks, cfg.max_pull_blocks)
        # The engine never serves an entire prompt from cache (one fresh
        # position is always computed), so cap the reusable prefix.
        warm_tokens = min(warm_blocks * cfg.block_size, max(prompt_len - 1, 0))
        pull_tokens = min(pull_blocks * cfg.block_size, max(prompt_len - 1, 0))
        q = cfg.est_service_s
        t_warm = warm_load * q + max(prompt_len - warm_tokens, 1) / pr
        t_pull = (
            cold_load * q
            + pull_blocks * cfg.block_bytes / tr
            + max(prompt_len - pull_tokens, 1) / pr
        )
        t_cold = cold_load * q + prompt_len / pr
        # Tie-break toward the least disruptive option: warm routing keeps
        # legacy behavior, pulling beats recomputing the same tokens.
        best, action = t_warm, ROUTE_WARM
        if t_pull < best:
            best, action = t_pull, PULL
        if t_cold < best:
            action = COLD
        return action

    def decide_remote(
        self,
        prompt_len: int,
        remote_blocks: int,
        target_load: float,
        warm_blocks: int = 0,
        warm_load: float = 0.0,
    ) -> str:
        """Remote-tier verdict: should the router pull ``remote_blocks``
        of this prompt's prefix from a remote holder (kvstore pod / peer
        remote store) onto the least-loaded serving pod?

        A remote hit must beat RECOMPUTE but lose to a warm LOCAL hit:
        ``pull`` is returned only when the modeled pull time undercuts
        BOTH serving at the warmest local pod (``warm_blocks`` there)
        and plain cold recompute on the target. The holder is storage,
        not compute, so "queue behind the warmth" is not an arm here.
        Abstains (``route_warm`` = let the legacy ranking stand) until
        both rates are measured, mirroring ``decide``'s bootstrap rule."""
        cfg = self.config
        with self._mu:
            tr, pr = self._transfer_rate, self._prefill_rate
        if tr is None or pr is None or remote_blocks < cfg.min_pull_blocks:
            return ROUTE_WARM
        pull_blocks = remote_blocks
        if cfg.max_pull_blocks is not None:
            pull_blocks = min(pull_blocks, cfg.max_pull_blocks)
        pull_tokens = min(pull_blocks * cfg.block_size, max(prompt_len - 1, 0))
        warm_tokens = min(warm_blocks * cfg.block_size, max(prompt_len - 1, 0))
        q = cfg.est_service_s
        t_pull = (
            target_load * q
            + pull_blocks * cfg.block_bytes / tr
            + max(prompt_len - pull_tokens, 1) / pr
        )
        t_cold = target_load * q + prompt_len / pr
        t_local = (
            warm_load * q + max(prompt_len - warm_tokens, 1) / pr
            if warm_blocks > 0
            else t_cold
        )
        return PULL if t_pull < min(t_local, t_cold) else ROUTE_WARM
