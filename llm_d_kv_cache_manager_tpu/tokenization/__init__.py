from .pool import TokenizationError, TokenizationPool, TokenizationPoolConfig
from .tokenizer import (
    CachedHFTokenizer,
    HFTokenizerConfig,
    Tokenizer,
    char_offsets_to_byte_offsets,
)
from . import prefixstore  # noqa: F401

__all__ = [
    "TokenizationError",
    "TokenizationPool",
    "TokenizationPoolConfig",
    "CachedHFTokenizer",
    "HFTokenizerConfig",
    "Tokenizer",
    "char_offsets_to_byte_offsets",
    "prefixstore",
]
