"""Fleet self-healing chaos suite (ISSUE 3 acceptance).

Invariant under every injected fault — dropped event batches, pod crash,
partition, delayed delivery, dead transfer peers: the index converges back
to engine ground truth within one resync, no routing decision targets an
expired pod, and every degraded path ends in cold prefill with correct
output, never an error.

Fault injection lives in ``tests/chaos.py``; everything here runs through
the real wire encoding (msgpack EventBatch → sharded KVEventsPool → index)
and, for the engine-backed scenarios, real ``PodServer`` instances in
Pallas interpreter mode.
"""

import time

import numpy as np
import pytest

from chaos import ChaosLink, engine_truth, index_view_of_pod, wait_until
from llm_d_kv_cache_manager_tpu.kvcache import (
    BlendedRouter,
    KVCacheIndexer,
    KVCacheIndexerConfig,
    PrefixAffinityTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    CostAwareMemoryIndex,
    CostAwareMemoryIndexConfig,
    InMemoryIndex,
    InMemoryIndexConfig,
    RedisIndexConfig,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import RedisIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    BlockRemoved,
    BlockStored,
    FleetHealth,
    FleetHealthConfig,
    Heartbeat,
    IndexSnapshot,
    KVEventsPool,
    KVEventsPoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.transfer import (
    CircuitBreaker,
    KVTransferClient,
    TransferClientConfig,
    TransferError,
)
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

from fake_redis import FakeRedis

PS = 4
MODEL = "tiny-llama"


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _stored(hashes, medium="tpu_hbm"):
    return [BlockStored(block_hashes=list(hashes), block_size=PS, medium=medium)]


def _pod_config(pod_id, **kw):
    return PodServerConfig(
        model_name=MODEL,
        pod_identifier=pod_id,
        publish_events=False,
        engine=EngineConfig(
            model=TINY_LLAMA,
            block_manager=BlockManagerConfig(total_pages=64, page_size=PS),
            scheduler=SchedulerConfig(max_prefill_batch=4),
            max_model_len=64,
            decode_batch_size=4,
            prefill_bucket=8,
            interpret=True,
        ),
        **kw,
    )


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


@pytest.fixture
def plane():
    """Event plane with health attached: (index, pool, health, clock)."""
    clock = FakeClock()
    health = FleetHealth(FleetHealthConfig(pod_ttl_s=5.0), clock=clock)
    index = InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=8))
    pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=2), health=health)
    pool.start()
    yield index, pool, health, clock
    pool.shutdown()


class TestGapDetectionAndResync:
    """Fault: a dropped event batch. Detection: seq gap. Repair: snapshot
    resync (replace-all-for-pod) — the index converges back to truth."""

    def test_drop_detected_and_resync_heals(self, plane):
        index, pool, health, _ = plane
        link = ChaosLink(pool, "pod-a", MODEL)

        link.publish(_stored([1, 2, 3]))
        link.drop_next(1)
        link.publish([BlockRemoved(block_hashes=[2])])  # lost on the wire
        link.publish(_stored([4]))
        assert pool.drain()

        # The gap is visible, the pod suspect — and the index is WRONG
        # (phantom block 2): exactly the rot resync exists to repair.
        assert health.gaps_detected == 1
        assert health.is_suspect("pod-a")
        assert index_view_of_pod(index, MODEL, link.seen_hashes, "pod-a") == {1, 2, 3, 4}

        # Ground truth after the lost eviction: {1, 3, 4}.
        link.publish([IndexSnapshot(blocks_by_medium={"tpu_hbm": [1, 3, 4]})])
        assert pool.drain()
        assert index_view_of_pod(index, MODEL, link.seen_hashes, "pod-a") == {1, 3, 4}
        assert not health.is_suspect("pod-a")
        assert health.resyncs_applied == 1

    def test_in_order_stream_flags_nothing(self, plane):
        _, pool, health, _ = plane
        link = ChaosLink(pool, "pod-a", MODEL)
        for i in range(10):
            link.publish(_stored([i]))
        assert pool.drain()
        assert health.gaps_detected == 0
        assert not health.is_suspect("pod-a")

    def test_delayed_out_of_order_delivery_detected_and_healed(self, plane):
        index, pool, health, _ = plane
        link = ChaosLink(pool, "pod-b", MODEL)
        link.publish(_stored([10, 11]))
        link.delay_next(1)
        link.publish([BlockRemoved(block_hashes=[11])])  # held → late
        link.publish(_stored([12]))  # seq jumps past the held message
        assert pool.drain()
        assert health.gaps_detected >= 1  # the hole where the held seq was

        link.release_held()  # now arrives with a REGRESSED seq
        assert pool.drain()
        assert health.is_suspect("pod-b")

        link.publish([IndexSnapshot(blocks_by_medium={"tpu_hbm": [10, 12]})])
        assert pool.drain()
        assert index_view_of_pod(index, MODEL, link.seen_hashes, "pod-b") == {10, 12}
        assert not health.is_suspect("pod-b")

    def test_regression_gap_count_is_bounded(self, plane):
        """A regressed seq flags a gap and REBASES the stream: a genuine
        straggler costs at most one extra catch-up gap, after which an
        in-order stream flags nothing further."""
        _, pool, health, _ = plane
        link = ChaosLink(pool, "pod-s", MODEL)
        for i in range(5):
            link.publish(_stored([i]))  # seqs 0..4
        link.delay_next(1)
        link.publish(_stored([5]))  # seq 5 held
        link.publish(_stored([6]))  # seq 6 → gap #1 (hole at 5)
        assert pool.drain()
        assert health.gaps_detected == 1

        link.release_held()  # seq 5 arrives late → regression gap #2
        assert pool.drain()
        assert health.gaps_detected == 2

        link.publish(_stored([7]))  # catch-up vs rebased stream → gap #3
        link.publish(_stored([8]))  # in-order from here: stable
        link.publish(_stored([9]))
        assert pool.drain()
        assert health.gaps_detected == 3  # bounded — no per-message storm

    def test_restart_with_lost_seq0_flags_one_gap_not_a_storm(self, plane):
        """Publisher restart whose seq-0 batch is itself lost: the first
        surviving message flags ONE gap and rebases; the rest of the new
        stream must NOT each count as a regression against the old
        high-water mark."""
        _, pool, health, _ = plane
        old = ChaosLink(pool, "pod-w", MODEL)
        for i in range(50):
            old.publish(_stored([i]))  # old stream: seqs 0..49
        assert pool.drain()
        assert health.gaps_detected == 0

        fresh = ChaosLink(pool, "pod-w", MODEL)  # restart: seq resets
        fresh.drop_next(1)
        fresh.publish(_stored([100]))  # seq 0 LOST in transit
        for i in range(1, 6):
            fresh.publish(_stored([100 + i]))  # seqs 1..5 delivered
        assert pool.drain()
        assert health.gaps_detected == 1  # one rebase, then in-order
        assert health.is_suspect("pod-w")  # repair still triggered

    def test_heartbeat_drop_counter_rebases_on_restart(self, plane):
        """A restarted publisher's dropped_batches counter restarts at 0;
        the baseline must rebase or its first drops are masked forever."""
        _, pool, health, _ = plane
        link = ChaosLink(pool, "pod-h", MODEL)
        link.publish([Heartbeat(dropped_batches=7)])
        assert pool.drain()
        assert health.publisher_drops_reported == 7

        # Restart: counter back to 0 — not new drops, a new baseline.
        link.publish([Heartbeat(dropped_batches=0)])
        assert pool.drain()
        assert health.publisher_drops_reported == 7

        link.publish([Heartbeat(dropped_batches=2)])  # 2 real new drops
        assert pool.drain()
        assert health.publisher_drops_reported == 9

    def test_publisher_restart_resets_without_gap(self, plane):
        _, pool, health, _ = plane
        link = ChaosLink(pool, "pod-r", MODEL)
        for i in range(4):
            link.publish(_stored([i]))  # seqs 0..3
        assert pool.drain()
        before = health.gaps_detected
        # Publisher restart: a fresh stream starts at seq 0 again.
        fresh = ChaosLink(pool, "pod-r", MODEL)
        fresh.publish(_stored([9]))   # seq 0: restart, not loss
        fresh.publish(_stored([10]))  # seq 1: in-order on the new stream
        assert pool.drain()
        assert health.gaps_detected == before

    def test_snapshot_clears_stale_tiers_and_models(self, plane):
        """Replace-all-for-pod means ALL of the pod's entries — every tier,
        every model — are rebuilt from the digest."""
        index, pool, health, _ = plane
        link = ChaosLink(pool, "pod-c", MODEL)
        other = ChaosLink(pool, "pod-d", MODEL)
        link.publish(_stored([1], medium="tpu_hbm"))
        link.publish(_stored([2], medium="host_dram"))
        other.publish(_stored([1, 2]))  # a different pod's entries survive
        assert pool.drain()

        link.publish(
            [IndexSnapshot(blocks_by_medium={"tpu_hbm": [3], "host_dram": []})]
        )
        assert pool.drain()
        assert index_view_of_pod(index, MODEL, {1, 2, 3}, "pod-c") == {3}
        assert index_view_of_pod(index, MODEL, {1, 2, 3}, "pod-d") == {1, 2}


class TestPublisherDropReporting:
    def test_heartbeat_reported_drops_mark_suspect(self, plane):
        _, pool, health, _ = plane
        link = ChaosLink(pool, "pod-a", MODEL)
        link.publish([Heartbeat(dropped_batches=0)])
        assert pool.drain()
        assert health.heartbeats_seen == 1
        assert not health.is_suspect("pod-a")

        # The publisher dropped 2 batches since the last beat — even with
        # no seq gap ever observable (idle stream), loss is detected.
        link.publish([Heartbeat(dropped_batches=2)])
        assert pool.drain()
        assert health.is_suspect("pod-a")
        assert health.publisher_drops_reported == 2

        link.publish([IndexSnapshot(blocks_by_medium={})])
        assert pool.drain()
        assert not health.is_suspect("pod-a")

    def test_publisher_seq_skips_on_drop(self, monkeypatch):
        """The real ZMQPublisher consumes a seq for a dropped batch, so the
        next delivered message exposes the gap (satellite 1)."""
        import zmq

        from conftest import free_tcp_port
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
            ZMQPublisher,
            ZMQPublisherConfig,
        )

        pub = ZMQPublisher(
            ZMQPublisherConfig(endpoint=f"tcp://localhost:{free_tcp_port()}")
        )
        assert pub.publish(_stored([1])) == 0

        def dead(frames):
            raise zmq.ZMQError()

        monkeypatch.setattr(pub._sock, "send_multipart", dead)
        monkeypatch.setattr(time, "sleep", lambda s: None)
        assert pub.publish(_stored([2])) == -1  # dropped, seq 1 consumed
        assert pub.dropped_batches == 1
        monkeypatch.setattr(pub._sock, "send_multipart", lambda frames: None)
        assert pub.publish(_stored([3])) == 2  # the gap at seq 1 is visible
        pub.close()


SWEEP_BACKENDS = {
    "in_memory": lambda: InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=8)),
    "cost_aware": lambda: CostAwareMemoryIndex(
        CostAwareMemoryIndexConfig(max_cost_bytes=10**6)
    ),
    "redis": lambda: RedisIndex(RedisIndexConfig(client=FakeRedis())),
}


class TestDeadPodSweep:
    @pytest.mark.parametrize("backend", list(SWEEP_BACKENDS))
    def test_ttl_sweep_evicts_only_the_dead_pod(self, backend):
        clock = FakeClock()
        health = FleetHealth(FleetHealthConfig(pod_ttl_s=5.0), clock=clock)
        index = SWEEP_BACKENDS[backend]()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1), health=health)
        pool.start()
        try:
            dead = ChaosLink(pool, "pod-dead", MODEL)
            live = ChaosLink(pool, "pod-live", MODEL)
            dead.publish(_stored([1, 2]))
            live.publish(_stored([2, 3]))
            assert pool.drain()

            clock.advance(6.0)  # pod-dead goes silent past TTL...
            live.publish([Heartbeat()])  # ...pod-live keeps beating
            assert pool.drain()

            assert health.sweep(index) == ["pod-dead"]
            assert health.pods_swept == 1
            assert index_view_of_pod(index, MODEL, {1, 2, 3}, "pod-dead") == set()
            assert index_view_of_pod(index, MODEL, {1, 2, 3}, "pod-live") == {2, 3}
            assert health.sweep(index) == []  # idempotent until revival

            # Revival: new events bring the pod back.
            dead.publish(_stored([7]))
            assert pool.drain()
            assert not health.is_expired("pod-dead")
            assert index_view_of_pod(index, MODEL, {7}, "pod-dead") == {7}
        finally:
            pool.shutdown()

    def test_background_sweeper_thread(self):
        health = FleetHealth(
            FleetHealthConfig(pod_ttl_s=0.2, sweep_interval_s=0.05)
        )
        index = InMemoryIndex(InMemoryIndexConfig(size=100, pod_cache_size=4))
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1), health=health)
        pool.start()
        link = ChaosLink(pool, "pod-x", MODEL)
        try:
            link.publish(_stored([1]))
            assert pool.drain()
            health.start_sweeper(index)
            assert wait_until(lambda: health.pods_swept >= 1, timeout=10)
            assert index_view_of_pod(index, MODEL, {1}, "pod-x") == set()
        finally:
            health.stop_sweeper()
            pool.shutdown()

    def test_failed_sweep_retries_next_pass(self):
        """A backend error during evict_pod must not permanently strand the
        dead pod's entries: the pod is un-marked and the next sweep retries
        (routing stays safe meanwhile via the TTL check)."""

        class FlakyIndex(InMemoryIndex):
            def __init__(self):
                super().__init__(InMemoryIndexConfig(size=100, pod_cache_size=4))
                self.fail_next = 1

            def evict_pod(self, pod):
                if self.fail_next:
                    self.fail_next -= 1
                    raise RuntimeError("transient backend error")
                return super().evict_pod(pod)

        clock = FakeClock()
        health = FleetHealth(FleetHealthConfig(pod_ttl_s=5.0), clock=clock)
        index = FlakyIndex()
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import PodEntry
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.keys import Key as K

        index.add([K(MODEL, 1)], [PodEntry("pod-f")])
        health.observe_message("pod-f", MODEL, 0)
        clock.advance(6.0)

        assert health.sweep(index) == []  # first pass: backend error
        assert health.is_expired("pod-f")  # still hidden from routing
        assert health.sweep(index) == ["pod-f"]  # retried and landed
        assert index_view_of_pod(index, MODEL, {1}, "pod-f") == set()

    def test_ttl_zero_never_expires(self):
        clock = FakeClock()
        health = FleetHealth(FleetHealthConfig(pod_ttl_s=0.0), clock=clock)
        health.observe_message("pod-a", MODEL, 0)
        clock.advance(10_000)
        assert not health.is_expired("pod-a")
        index = InMemoryIndex()
        assert health.sweep(index) == []


class TestExpiredPodNeverRouted:
    """The read-path guarantee: between TTL expiry and the sweep landing,
    scores already exclude the dead pod — and the router degrades to a
    cold placement, never an error."""

    def _indexer_with_health(self, clock):
        health = FleetHealth(FleetHealthConfig(pod_ttl_s=5.0), clock=clock)
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            ),
            fleet_health=health,
        )
        return indexer, health

    def test_sole_matching_pod_expired_mid_lookup(self):
        clock = FakeClock()
        indexer, health = self._indexer_with_health(clock)
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import PodEntry

        tokens = list(range(16))
        keys = indexer.token_processor.tokens_to_kv_block_keys(tokens, MODEL)
        indexer.kv_block_index.add(keys, [PodEntry("pod-only")])
        health.observe_message("pod-only", MODEL, 0)

        assert indexer.score_tokens(tokens, MODEL) == {"pod-only": len(keys)}
        clock.advance(6.0)  # TTL passes; the sweeper has NOT run yet
        assert indexer.score_tokens(tokens, MODEL) == {}

    def test_router_degrades_to_cold_not_error(self):
        clock = FakeClock()
        indexer, health = self._indexer_with_health(clock)
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import PodEntry

        tokens = list(range(16))
        keys = indexer.token_processor.tokens_to_kv_block_keys(tokens, MODEL)
        indexer.kv_block_index.add(keys, [PodEntry("pod-warm")])
        health.observe_message("pod-warm", MODEL, 0)

        pods = ["pod-warm", "pod-cold"]
        router = BlendedRouter(
            score_fn=lambda toks, p: indexer.score_tokens(toks, MODEL, p),
            affinity=PrefixAffinityTracker(n_pods=2, capacity_blocks=64),
            loads_fn=lambda p: [0.0] * len(p),
        )
        assert router.route(tokens, pods).pod == "pod-warm"
        clock.advance(6.0)  # pod-warm dies
        decision = router.route(tokens, pods)
        assert decision.pod != "pod-warm" or decision.index_score == 0
        # With zero index signal everywhere, the router must still place
        # the request (affinity seeded pod-warm earlier, but index says
        # nothing) — the point is: a decision, not an exception.
        assert decision.pod in pods


class TestCircuitBreaker:
    def test_state_machine(self):
        clock = FakeClock()
        b = CircuitBreaker(2, backoff_s=1.0, backoff_max_s=4.0, clock=clock)
        assert b.state == "closed" and b.allow()
        b.record_failure()
        assert b.state == "closed" and b.allow()
        b.record_failure()  # threshold: trips
        assert b.state == "open" and b.opens == 1
        assert not b.allow()

        clock.advance(1.1)  # backoff expires → one half-open probe
        assert b.state == "half_open"
        assert b.allow()
        assert not b.allow()  # only one probe in flight
        b.record_failure()  # probe fails → reopen, backoff doubles
        assert not b.allow()
        clock.advance(1.1)
        assert not b.allow()  # 2s backoff now
        clock.advance(1.0)
        assert b.allow()
        b.record_success()  # probe succeeds → closed, backoff reset
        assert b.state == "closed" and b.closes == 1
        assert b.allow()

    def test_backoff_caps(self):
        clock = FakeClock()
        b = CircuitBreaker(1, backoff_s=1.0, backoff_max_s=4.0, clock=clock)
        for _ in range(6):  # repeated failed probes
            b.record_failure()
            clock.advance(100.0)
            assert b.allow()
        assert b.snapshot()["backoff_s"] == 4.0

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(3, clock=FakeClock())
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"  # never saw 3 consecutive

    def test_open_breaker_fails_fetch_instantly(self):
        from conftest import free_tcp_port

        client = KVTransferClient(
            TransferClientConfig(
                endpoint=f"tcp://127.0.0.1:{free_tcp_port()}",
                timeout_s=0.4,
                breaker_failures=1,
            )
        )
        try:
            t0 = time.perf_counter()
            with pytest.raises(TransferError):
                client.fetch(MODEL, [1, 2, 3])
            assert time.perf_counter() - t0 >= 0.35  # ate the real timeout

            t0 = time.perf_counter()
            with pytest.raises(TransferError):
                client.fetch(MODEL, [1, 2, 3])
            # Breaker open: instant rejection, no second timeout burned.
            assert time.perf_counter() - t0 < 0.2
            assert client.breaker_skips == 1
            assert client.breaker.snapshot()["state"] == "open"
        finally:
            client.close()


class TestEngineFleetChaos:
    """Engine-backed scenarios: real PodServers (interpreter mode) with
    ChaosLink transports into one indexer."""

    def _fleet(self, n=2, ttl_s=5.0, clock=None, **pod_kw):
        clock = clock or FakeClock()
        health = FleetHealth(FleetHealthConfig(pod_ttl_s=ttl_s), clock=clock)
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            ),
            fleet_health=health,
        )
        pool = KVEventsPool(
            indexer.kv_block_index, KVEventsPoolConfig(concurrency=2), health=health
        )
        pool.start()
        servers, links = [], []
        for i in range(n):
            pod_id = f"chaos-pod-{i}"
            link = ChaosLink(pool, pod_id, MODEL)
            server = PodServer(_pod_config(pod_id, **pod_kw), publisher=link)
            server.start()
            servers.append(server)
            links.append(link)
        return indexer, pool, health, clock, servers, links

    def _teardown(self, pool, servers):
        for s in servers:
            s.shutdown()
        pool.shutdown()

    def test_pod_crash_swept_and_rerouted_cold(self):
        indexer, pool, health, clock, servers, links = self._fleet(n=2)
        try:
            prefix = _prompt(0, 16)
            baseline = servers[0].generate(
                prefix, SamplingParams(max_new_tokens=3), timeout=120
            )
            assert pool.drain(timeout=10)
            pods = ["chaos-pod-0", "chaos-pod-1"]
            assert indexer.score_tokens(prefix, MODEL, pods)["chaos-pod-0"] > 0

            # CRASH pod 0: no eviction events, no goodbyes — then silence
            # past the TTL while pod 1 stays live.
            servers[0].shutdown()
            clock.advance(6.0)
            links[1].publish([Heartbeat()])
            assert pool.drain(timeout=10)

            # Expiry guard: even before the sweep, scoring excludes it.
            assert indexer.score_tokens(prefix, MODEL, pods) == {}
            assert health.sweep(indexer.kv_block_index) == ["chaos-pod-0"]
            assert (
                index_view_of_pod(
                    indexer.kv_block_index, MODEL, links[0].seen_hashes, "chaos-pod-0"
                )
                == set()
            )

            # Routing degrades to a cold placement on the survivor; the
            # request completes with the SAME greedy output (engines share
            # init seed) — degraded, never wrong, never an error.
            router = BlendedRouter(
                score_fn=lambda toks, p: indexer.score_tokens(toks, MODEL, p),
                affinity=PrefixAffinityTracker(n_pods=2, capacity_blocks=64),
                loads_fn=lambda p: [0.0] * len(p),
            )
            decision = router.route(prefix, pods)
            assert decision.index_score == 0  # nobody advertises warmth
            seq = servers[1].generate(
                prefix, SamplingParams(max_new_tokens=3), timeout=120
            )
            assert seq.output_tokens == baseline.output_tokens
            assert seq.num_cached_prompt == 0  # honest cold prefill
        finally:
            self._teardown(pool, servers)

    def test_partition_heals_via_resync_to_ground_truth(self):
        indexer, pool, health, clock, servers, links = self._fleet(n=1)
        try:
            server, link = servers[0], links[0]
            server.generate(_prompt(1, 16), SamplingParams(max_new_tokens=2), timeout=120)
            assert pool.drain(timeout=10)

            # Partition: everything published during this window is lost —
            # stores AND evictions desync arbitrarily.
            link.partition()
            for i in range(3):
                server.generate(
                    _prompt(10 + i, 24), SamplingParams(max_new_tokens=2), timeout=120
                )
            link.heal()

            # One on-demand resync repairs the whole window: the snapshot
            # message's seq jump flags the gap AND carries the fix.
            assert server.publish_index_snapshot(timeout_s=30)
            assert pool.drain(timeout=10)
            assert health.gaps_detected >= 1
            assert health.resyncs_applied == 1

            truth = engine_truth(server)
            view = index_view_of_pod(
                indexer.kv_block_index, MODEL, link.seen_hashes, "chaos-pod-0"
            )
            assert view == truth
        finally:
            self._teardown(pool, servers)

    def test_periodic_resync_converges_after_drops(self):
        """RESYNC_INTERVAL_S acceptance: with periodic resync on, an
        arbitrary drop fault converges without any operator action within
        one interval."""
        indexer, pool, health, clock, servers, links = self._fleet(
            n=1, resync_interval_s=0.3, heartbeat_interval_s=0.2
        )
        try:
            server, link = servers[0], links[0]
            link.drop_next(2)  # lose the first prefill's event batches
            server.generate(_prompt(2, 16), SamplingParams(max_new_tokens=2), timeout=120)

            def converged():
                pool.drain(timeout=2)
                truth = engine_truth(server)
                view = index_view_of_pod(
                    indexer.kv_block_index, MODEL, link.seen_hashes, "chaos-pod-0"
                )
                return view == truth and truth

            assert wait_until(converged, timeout=30)
            assert server.snapshots_published >= 1
            assert server.heartbeats_published >= 1
            assert health.heartbeats_seen >= 1
        finally:
            self._teardown(pool, servers)

    def test_dead_transfer_peer_breaker_then_cold_prefill(self):
        from conftest import free_tcp_port

        cold = PodServer(_pod_config("breaker-cold"))
        cold.config.transfer_timeout_s = 0.4
        cold.config.transfer_breaker_failures = 1
        ref = PodServer(_pod_config("breaker-ref"))
        cold.start(), ref.start()
        try:
            prompt = _prompt(3, 12)
            peer = f"tcp://127.0.0.1:{free_tcp_port()}"  # nobody home

            t0 = time.perf_counter()
            assert cold.pull_prefix(prompt, peer) == 0  # eats one timeout
            first = time.perf_counter() - t0

            t0 = time.perf_counter()
            assert cold.pull_prefix(prompt, peer) == 0  # breaker: instant
            second = time.perf_counter() - t0
            assert first >= 0.35 and second < 0.2
            assert cold.transfer_pull_failures == 2

            client = cold._transfer_pool.clients()[peer]
            assert client.breaker is not None
            assert client.breaker.snapshot()["state"] == "open"
            assert client.breaker_skips == 1

            # The degraded request still completes, cold and correct.
            s = cold.generate(prompt, SamplingParams(max_new_tokens=4), timeout=120)
            s_ref = ref.generate(prompt, SamplingParams(max_new_tokens=4), timeout=120)
            assert s.output_tokens == s_ref.output_tokens
            assert s.num_cached_prompt == 0
        finally:
            cold.shutdown(), ref.shutdown()

    def test_drained_pod_evicted_immediately_and_never_routed(self):
        """ISSUE 4 acceptance (c): drain → immediate fleet eviction (no
        POD_TTL_S wait) → zero routes to the drained pod — verified against
        engine ground truth (the drained engine still physically holds its
        blocks; the fleet view, not the hardware, is what must forget it).
        TTL is set huge so only the PodDrained goodbye can evict."""
        indexer, pool, health, clock, servers, links = self._fleet(
            n=2, ttl_s=100_000.0
        )
        try:
            prefix = _prompt(20, 16)
            baseline = servers[0].generate(
                prefix, SamplingParams(max_new_tokens=3), timeout=120
            )
            assert pool.drain(timeout=10)
            pods = ["chaos-pod-0", "chaos-pod-1"]
            assert indexer.score_tokens(prefix, MODEL, pods)["chaos-pod-0"] > 0

            # Graceful drain: inflight is empty, so the final snapshot +
            # PodDrained goodbye publish immediately. NO clock advance —
            # eviction must not need the TTL.
            assert servers[0].drain(timeout_s=30) is True
            assert pool.drain(timeout=10)
            assert health.snapshot()["pods_drained"] == 1
            assert (
                index_view_of_pod(
                    indexer.kv_block_index, MODEL, links[0].seen_hashes, "chaos-pod-0"
                )
                == set()
            )
            # Ground truth: the drained engine still holds its cache; the
            # fleet simply must never route to it again.
            assert engine_truth(servers[0])

            assert indexer.score_tokens(prefix, MODEL, pods) == {}
            router = BlendedRouter(
                score_fn=lambda toks, p: indexer.score_tokens(toks, MODEL, p),
                affinity=PrefixAffinityTracker(n_pods=2, capacity_blocks=64),
                loads_fn=lambda p: [0.0] * len(p),
            )
            decision = router.route(prefix, pods)
            assert decision.index_score == 0  # the drained pod's warmth is gone

            # The drained pod itself refuses new work; the survivor serves
            # the request cold with identical greedy output.
            from llm_d_kv_cache_manager_tpu.server.serve import DrainingError

            with pytest.raises(DrainingError):
                servers[0].submit(prefix)
            seq = servers[1].generate(
                prefix, SamplingParams(max_new_tokens=3), timeout=120
            )
            assert seq.output_tokens == baseline.output_tokens
            assert seq.num_cached_prompt == 0
        finally:
            self._teardown(pool, servers)

    def test_draining_heartbeat_unroutes_before_goodbye(self):
        """A pod advertising ``draining`` via heartbeat stops being scored
        immediately — its entries are still indexed (the drain is not done),
        but routing must not hand it new prefixes it is about to evict."""
        indexer, pool, health, clock, servers, links = self._fleet(
            n=2, ttl_s=100_000.0
        )
        try:
            prefix = _prompt(21, 16)
            servers[0].generate(prefix, SamplingParams(max_new_tokens=2), timeout=120)
            assert pool.drain(timeout=10)
            pods = ["chaos-pod-0", "chaos-pod-1"]
            assert indexer.score_tokens(prefix, MODEL, pods)["chaos-pod-0"] > 0

            links[0].publish([Heartbeat(draining=True)])
            assert pool.drain(timeout=10)
            assert indexer.score_tokens(prefix, MODEL, pods) == {}
            # The index itself still holds the entries — only routing hides
            # them while the drain runs.
            assert index_view_of_pod(
                indexer.kv_block_index, MODEL, links[0].seen_hashes, "chaos-pod-0"
            )

            # Drain cancelled (e.g. the restart was aborted): a plain
            # heartbeat restores routability.
            links[0].publish([Heartbeat(draining=False)])
            assert pool.drain(timeout=10)
            assert indexer.score_tokens(prefix, MODEL, pods)["chaos-pod-0"] > 0
        finally:
            self._teardown(pool, servers)

    def test_drained_pod_restart_revives_routing(self):
        """Same pod identity coming back after a PodDrained goodbye must be
        routable again as soon as it publishes — a rolling restart reuses
        pod names."""
        indexer, pool, health, clock, servers, links = self._fleet(
            n=1, ttl_s=100_000.0
        )
        try:
            prefix = _prompt(22, 16)
            servers[0].generate(prefix, SamplingParams(max_new_tokens=2), timeout=120)
            assert pool.drain(timeout=10)
            assert servers[0].drain(timeout_s=30) is True
            assert pool.drain(timeout=10)
            assert indexer.score_tokens(prefix, MODEL, ["chaos-pod-0"]) == {}

            # "Restart": a fresh publisher stream under the same identity
            # re-announces warmth via a resync snapshot.
            digest = servers[0].engine.block_manager.block_digest()
            links[0].publish([IndexSnapshot(blocks_by_medium=digest)])
            assert pool.drain(timeout=10)
            assert (
                indexer.score_tokens(prefix, MODEL, ["chaos-pod-0"])["chaos-pod-0"]
                > 0
            )
        finally:
            self._teardown(pool, servers)


class TestAsyncPullChaos:
    """ISSUE 7 satellite: a delayed/partitioned transfer peer under
    ASYNC_PULL=1 must never stall decode for unrelated sequences, and the
    importing sequence must fall back to cold prefill with identical
    greedy output."""

    def test_partitioned_peer_stalls_nothing_and_falls_back_cold(self):
        from conftest import free_tcp_port

        cold = PodServer(
            _pod_config("apc-cold", async_pull=True, transfer_timeout_s=20.0)
        )
        ref = PodServer(_pod_config("apc-ref"))
        cold.start(), ref.start()
        try:
            # An unrelated request is mid-decode when the pull-routed
            # request arrives pointing at a partitioned peer (nobody
            # home: the fetch hangs until the 20 s poll deadline —
            # generous so a first-run jit compile of the decode shapes
            # can never outlast it and flake the not-done assert).
            running = cold.submit(
                _prompt(40, 8), SamplingParams(max_new_tokens=12)
            )
            prompt = _prompt(41, 12)
            peer = f"tcp://127.0.0.1:{free_tcp_port()}"
            stalled = cold.submit(
                prompt, SamplingParams(max_new_tokens=4), pull_source=peer
            )
            # The running lane finishes all 12 tokens while the import is
            # still on the wire — decode ITL never saw the partition.
            s_run = running.result(timeout=120)
            assert len(s_run.generated_tokens) == 12
            assert not stalled.done()
            assert cold._pull_jobs  # the fetch really is still in flight

            s = stalled.result(timeout=120)  # poll deadline -> cold prefill
            s_ref = ref.generate(
                prompt, SamplingParams(max_new_tokens=4), timeout=120
            )
            assert s.generated_tokens == s_ref.generated_tokens
            assert s.num_cached_prompt == 0
            assert cold.async_pull_fallbacks == 1
        finally:
            cold.shutdown(), ref.shutdown()
