"""Serving-engine tests: continuous batching, prefix caching, event emission.

The load-bearing invariants:
- engine greedy output == direct model-level generation (no scheduler bugs);
- a second request sharing a prefix hits the page cache, skips compute, and
  still produces identical tokens;
- BlockStored/BlockRemoved events drive the routing indexer to score this
  pod exactly as the reference read-path expects (hash parity end-to-end).
"""

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.server import (
    BlockManager,
    BlockManagerConfig,
    Engine,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
    Sequence,
)
from llm_d_kv_cache_manager_tpu.server.block_manager import AllocationError

PS = 4
MODEL = "tiny-llama"


def _engine(total_pages=64, decode_batch=4, host_pages=0, on_events=None,
            model=TINY_LLAMA, **kw):
    cfg = EngineConfig(
        model=model,
        block_manager=BlockManagerConfig(
            total_pages=total_pages, page_size=PS, host_pages=host_pages
        ),
        scheduler=SchedulerConfig(max_prefill_batch=4),
        max_model_len=64,
        decode_batch_size=decode_batch,
        prefill_bucket=8,
        interpret=True,
        **kw,
    )
    return Engine(cfg, on_events=on_events)


def _prompt(seed, n):
    return list(np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))


class TestEngineBasics:
    def test_single_request_generates(self):
        eng = _engine()
        seq = eng.add_request(_prompt(0, 10), SamplingParams(max_new_tokens=5))
        done = eng.run_until_complete()
        assert [s.seq_id for s in done] == [seq.seq_id]
        assert len(seq.output_tokens) == 5
        assert seq.ttft is not None and seq.ttft >= 0

    def test_batch_requests_all_finish(self):
        eng = _engine()
        seqs = [
            eng.add_request(_prompt(i, 6 + i), SamplingParams(max_new_tokens=4))
            for i in range(4)
        ]
        done = eng.run_until_complete()
        assert len(done) == 4
        for s in seqs:
            assert len(s.output_tokens) == 4

    def test_greedy_determinism_across_batching(self):
        # One request alone vs the same request sharing the engine with
        # others must produce identical greedy tokens.
        eng1 = _engine()
        alone = eng1.add_request(_prompt(7, 9), SamplingParams(max_new_tokens=6))
        eng1.run_until_complete()

        eng2 = _engine()
        mixed = eng2.add_request(_prompt(7, 9), SamplingParams(max_new_tokens=6))
        eng2.add_request(_prompt(8, 5), SamplingParams(max_new_tokens=3))
        eng2.add_request(_prompt(9, 13), SamplingParams(max_new_tokens=4))
        eng2.run_until_complete()
        assert alone.output_tokens == mixed.output_tokens

    def test_stop_token(self):
        eng = _engine()
        probe = eng.add_request(_prompt(1, 8), SamplingParams(max_new_tokens=1))
        eng.run_until_complete()
        stop = probe.output_tokens[0]

        eng2 = _engine()
        seq = eng2.add_request(
            _prompt(1, 8), SamplingParams(max_new_tokens=32, stop_token_ids=(stop,))
        )
        eng2.run_until_complete()
        assert seq.output_tokens[-1] == stop
        assert len(seq.output_tokens) == 1

    def test_rejects_bad_requests(self):
        eng = _engine()
        with pytest.raises(ValueError):
            eng.add_request([], SamplingParams())
        with pytest.raises(ValueError):
            eng.add_request(_prompt(0, 64), SamplingParams())


class TestPrefixCaching:
    def test_shared_prefix_hits_cache_and_matches(self):
        eng = _engine()
        shared = _prompt(42, 16)  # 4 full pages
        a = eng.add_request(shared + _prompt(1, 4), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()

        b = eng.add_request(shared + _prompt(2, 4), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        assert b.num_cached_prompt == 16  # full shared prefix served from cache

        # Identical request C must produce identical output to B's sibling run
        # in a fresh engine with no cache.
        eng_fresh = _engine()
        c = eng_fresh.add_request(shared + _prompt(2, 4), SamplingParams(max_new_tokens=4))
        eng_fresh.run_until_complete()
        assert c.num_cached_prompt == 0
        assert b.output_tokens == c.output_tokens

    def test_identical_prompt_not_fully_cached(self):
        eng = _engine()
        p = _prompt(5, 8)  # exactly 2 pages
        eng.add_request(p, SamplingParams(max_new_tokens=2))
        eng.run_until_complete()
        again = eng.add_request(p, SamplingParams(max_new_tokens=2))
        eng.run_until_complete()
        # allocator must leave >=1 fresh token to produce first-token logits
        assert again.num_cached_prompt < len(p)
        assert len(again.output_tokens) == 2

    def test_pages_shared_not_copied(self):
        eng = _engine(total_pages=16)
        shared = _prompt(11, 16)
        eng.add_request(shared + [1], SamplingParams(max_new_tokens=1))
        eng.run_until_complete()
        free_before = eng.block_manager.num_free
        eng.add_request(shared + [2], SamplingParams(max_new_tokens=1))
        eng.run_until_complete()
        # second request allocated only ~1-2 fresh pages, not 5
        assert eng.block_manager.num_free >= free_before - 2


class TestEventEmission:
    def test_events_drive_indexer_to_score_pod(self):
        from llm_d_kv_cache_manager_tpu.kvcache import KVCacheIndexer, KVCacheIndexerConfig
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents import KVEventsPool, Message
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import EventBatch

        # Indexer configured with the engine's block size & seed.
        ix = KVCacheIndexer(
            KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=PS))
        )
        pool = KVEventsPool(ix.kv_block_index)
        pool.start()

        collected = []
        eng_cfg = EngineConfig(
            model=TINY_LLAMA,
            block_manager=BlockManagerConfig(total_pages=64, page_size=PS),
            max_model_len=64,
            decode_batch_size=2,
            prefill_bucket=8,
            interpret=True,
        )
        eng = Engine(eng_cfg, on_events=lambda evs: collected.append(list(evs)))

        prompt = _prompt(33, 13)  # 3 full pages + partial
        seq = eng.add_request(prompt, SamplingParams(max_new_tokens=7))
        eng.run_until_complete()

        # Feed the engine's events through the ingestion pool, as ZMQ would.
        import time as _time

        for evs in collected:
            msg = Message(
                topic=f"kv@tpu-pod-0@{MODEL}",
                pod_identifier="tpu-pod-0",
                model_name=MODEL,
                payload=EventBatch(ts=_time.time(), events=evs).to_payload(),
            )
            pool.add_task(msg)
        assert pool.drain()
        pool.shutdown()

        # The indexer must now route this exact prompt to our pod with a
        # score equal to the number of KV-complete pages. The final sampled
        # token is never fed back through decode, so its K/V is unwritten:
        # complete tokens = num_tokens - 1.
        all_tokens = seq.all_tokens
        scores = ix.score_tokens(all_tokens, MODEL)
        assert scores.get("tpu-pod-0", 0) == (len(all_tokens) - 1) // PS

    def test_eviction_emits_block_removed(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import BlockRemoved

        events = []
        eng_cfg = EngineConfig(
            model=TINY_LLAMA,
            block_manager=BlockManagerConfig(total_pages=10, page_size=PS),
            max_model_len=32,
            decode_batch_size=2,
            prefill_bucket=8,
            interpret=True,
        )
        eng = Engine(eng_cfg, on_events=lambda evs: events.extend(evs))
        # Fill the small pool with successive distinct prompts; finished
        # sequences leave cached pages that must be recycled (with events).
        for i in range(6):
            eng.add_request(_prompt(100 + i, 12), SamplingParams(max_new_tokens=2))
            eng.run_until_complete()
        assert any(isinstance(e, BlockRemoved) for e in events)


class TestPreemption:
    def test_decode_oom_preempts_and_all_finish(self):
        # Pool sized so concurrent decode growth must exhaust it: two
        # sequences with long generations in a small pool.
        eng = _engine(total_pages=9, decode_batch=2)
        a = eng.add_request(_prompt(50, 10), SamplingParams(max_new_tokens=12))
        b = eng.add_request(_prompt(51, 10), SamplingParams(max_new_tokens=12))
        done = eng.run_until_complete()
        assert len(done) == 2
        assert len(a.generated_tokens) == 12
        assert len(b.generated_tokens) == 12

    def test_preempted_output_reporting_stable(self):
        eng = _engine(total_pages=9, decode_batch=2)
        original_prompt = _prompt(52, 10)
        a = eng.add_request(list(original_prompt), SamplingParams(max_new_tokens=10))
        eng.add_request(_prompt(53, 10), SamplingParams(max_new_tokens=10))
        eng.run_until_complete()
        # generated_tokens excludes the original prompt even if the sequence
        # was preempted (prompt folding must not leak into reported output).
        assert len(a.generated_tokens) == 10
        assert a.all_tokens[: a.user_prompt_len] == [int(t) for t in original_prompt]

    def test_oversized_prompt_rejected_upfront(self):
        eng = _engine(total_pages=4)
        with pytest.raises(ValueError, match="pages"):
            eng.add_request(_prompt(60, 16), SamplingParams(max_new_tokens=1))

    def test_pool_too_small_for_growth_aborts_with_error(self):
        # One sequence, pool that cannot hold its growth: the request must
        # abort with an error instead of wedging the engine.
        eng = _engine(total_pages=4, decode_batch=1)
        seq = eng.add_request(_prompt(61, 9), SamplingParams(max_new_tokens=30))
        done = eng.run_until_complete(max_steps=500)
        assert len(done) == 1
        assert seq.error is not None
        assert not eng.has_work


class TestBlockManagerUnit:
    def test_pool_exhaustion_raises(self):
        bm = BlockManager(BlockManagerConfig(total_pages=4, page_size=PS))
        s1 = Sequence(prompt_tokens=list(range(12)))  # needs 3 pages
        bm.allocate(s1)
        s2 = Sequence(prompt_tokens=list(range(8)))
        with pytest.raises(AllocationError):
            bm.allocate(s2)
        # failed allocation must not leak partial reservations
        assert bm.num_free == 0
        bm.free_sequence(s1)
        assert bm.num_free == 3

    def test_refcounted_sharing(self):
        bm = BlockManager(BlockManagerConfig(total_pages=16, page_size=PS))
        s1 = Sequence(prompt_tokens=list(range(9)))
        bm.allocate(s1)
        s1.num_computed = 9
        bm.register_full_pages(s1)
        assert bm.num_cached_pages == 2

        s2 = Sequence(prompt_tokens=list(range(9)))
        cached = bm.allocate(s2)
        assert cached == 8
        assert s2.block_table[:2] == s1.block_table[:2]
        # freeing one sequence keeps shared pages alive for the other
        bm.free_sequence(s1)
        s2.num_computed = 9
        bm.register_full_pages(s2)
        bm.free_sequence(s2)
        # all pages now evictable; a big new allocation recycles them
        s3 = Sequence(prompt_tokens=list(range(14 * PS)))
        bm.allocate(s3)

    def test_failed_restore_keeps_host_block(self):
        # Regression: a prefix hit on the host tier while every HBM page is
        # pinned must leave the host-cached block intact (and emit no
        # events), so a later retry can still restore it.
        captured = []
        bm = BlockManager(
            BlockManagerConfig(total_pages=3, page_size=PS, host_pages=4),
            on_events=captured.extend,
        )
        host_store = {}
        bm.attach_host_pool(
            copy_out=lambda page, slot: host_store.__setitem__(slot, page),
            copy_in=lambda slot, page: None,
        )
        # Fill + register A's 2 pages, free it, then pin both pages with B —
        # recycling A's pages spills them into the host tier.
        a = Sequence(prompt_tokens=list(range(2 * PS)))
        bm.allocate(a)
        a.num_computed = 2 * PS
        bm.register_full_pages(a)
        bm.free_sequence(a)
        b = Sequence(prompt_tokens=list(range(100, 100 + 2 * PS)))
        bm.allocate(b)
        assert bm.num_host_cached_pages == 2 and bm.num_free == 0

        captured.clear()
        c = Sequence(prompt_tokens=list(range(2 * PS)))  # same prefix as A
        with pytest.raises(AllocationError):
            bm.allocate(c)
        assert bm.num_host_cached_pages == 2  # host copy survived
        assert captured == []  # no phantom BlockRemoved/BlockStored
        # Once B releases its pages the restore succeeds.
        bm.free_sequence(b)
        c2 = Sequence(prompt_tokens=list(range(2 * PS)))
        assert bm.allocate(c2) == PS  # first block restored from host tier


class TestBlockManagerHostTierEdges:
    """Bookkeeping edges of the host-DRAM tier, driven through fake movers:
    spills into a FULL host tier, and the bring-back path racing host-LRU
    eviction (block_manager.py::_try_restore's claim-before-alloc rule)."""

    @staticmethod
    def _bm(total_pages=3, host_pages=1):
        captured = []
        bm = BlockManager(
            BlockManagerConfig(
                total_pages=total_pages, page_size=PS, host_pages=host_pages
            ),
            on_events=captured.extend,
        )
        copy_outs, copy_ins = [], []
        bm.attach_host_pool(
            copy_out=lambda page, slot: copy_outs.append((page, slot)),
            copy_in=lambda slot, page: copy_ins.append((slot, page)),
        )
        return bm, captured, copy_outs, copy_ins

    @staticmethod
    def _fill_and_free(bm, tokens):
        """Allocate a one-page sequence, register its block, free it —
        leaving the page evictable under its chain hash."""
        seq = Sequence(prompt_tokens=list(tokens))
        bm.allocate(seq)
        seq.num_computed = len(tokens)
        bm.register_full_pages(seq)
        bm.free_sequence(seq)
        bm.flush_events()
        return bm.token_db.prefix_hashes(tokens)[0]

    def test_offload_into_full_host_tier_evicts_host_lru(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import (
            BlockRemoved,
            BlockStored,
        )

        bm, captured, copy_outs, _ = self._bm()
        h_a = self._fill_and_free(bm, range(PS))
        h_b = self._fill_and_free(bm, range(100, 100 + PS))
        # Recycling A's page spills it into the single host slot.
        self._fill_and_free(bm, range(200, 200 + PS))
        assert bm._host_cached == {h_a: 0}

        # Recycling B's page finds the tier FULL: the host LRU (A) must be
        # evicted — with a truthful host_dram BlockRemoved — and B spilled
        # into the freed slot.
        captured.clear()
        self._fill_and_free(bm, range(300, 300 + PS))
        assert bm.num_host_cached_pages == 1 and bm._host_cached == {h_b: 0}
        host_evs = [e for e in captured if e.medium == "host_dram"]
        assert isinstance(host_evs[0], BlockRemoved)
        assert host_evs[0].block_hashes == [h_a]
        assert isinstance(host_evs[1], BlockStored)
        assert host_evs[1].block_hashes == [h_b]
        assert copy_outs == [(1, 0), (2, 0)]  # A's page, then B's reused slot

    def test_bring_back_races_host_lru_eviction(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import (
            BlockStored,
        )

        bm, captured, copy_outs, copy_ins = self._bm()
        a_tokens = list(range(PS))
        h_a = self._fill_and_free(bm, a_tokens)
        h_b = self._fill_and_free(bm, range(100, 100 + PS))
        self._fill_and_free(bm, range(200, 200 + PS))  # spills A to slot 0
        assert bm._host_cached == {h_a: 0}
        assert copy_outs == [(1, 0)]

        # Bring A back while the pool is exhausted: the restore's
        # _pop_free_page recycles B's page, whose spill wants a host slot —
        # and the only slot is the one A is being restored FROM. The claim
        # taken before allocation must make that spill skip (B's KV is
        # dropped, truthfully), never corrupt the in-flight restore.
        captured.clear()
        seq = Sequence(prompt_tokens=a_tokens + list(range(400, 400 + PS)))
        assert bm.allocate(seq) == PS  # A restored from the host tier
        bm.flush_events()
        assert copy_ins == [(0, 2)]  # restored into B's recycled page
        # B was never spilled into the mid-restore slot...
        assert (2, 0) not in copy_outs
        assert not any(
            isinstance(e, BlockStored)
            and e.medium == "host_dram"
            and e.block_hashes == [h_b]
            for e in captured
        )
        # ...and after the restore freed the slot, the page recycled for
        # the sequence's second block (C's) spilled into it normally.
        assert bm._host_cached and 0 in bm._host_cached.values()
        assert h_b not in bm._host_cached
        # A is resident again under its hash, referenced by the sequence.
        assert bm._cached[h_a] == seq.block_table[0]


class TestFusedDecode:
    """decode_steps_per_iter > 1: device-resident multi-token decode."""

    def test_fused_greedy_matches_per_step(self):
        prompts = [_prompt(i, 9 + i) for i in range(3)]
        outs = []
        for k in (1, 4):
            eng = _engine(decode_steps_per_iter=k)
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=7))
                for p in prompts
            ]
            eng.run_until_complete()
            outs.append([s.output_tokens for s in seqs])
        assert outs[0] == outs[1]

    def test_fused_respects_max_new_tokens(self):
        # max_new not a multiple of the burst: surplus tokens discarded.
        eng = _engine(decode_steps_per_iter=4)
        seq = eng.add_request(_prompt(1, 10), SamplingParams(max_new_tokens=6))
        eng.run_until_complete()
        assert len(seq.output_tokens) == 6

    def test_fused_stop_token_truncates(self):
        eng = _engine(decode_steps_per_iter=4)
        probe = eng.add_request(_prompt(2, 8), SamplingParams(max_new_tokens=3))
        eng.run_until_complete()
        stop = probe.output_tokens[1]
        eng2 = _engine(decode_steps_per_iter=4)
        seq = eng2.add_request(
            _prompt(2, 8), SamplingParams(max_new_tokens=8, stop_token_ids=(stop,))
        )
        eng2.run_until_complete()
        assert seq.output_tokens[-1] == stop
        assert len(seq.output_tokens) == 2

    def test_fused_prefix_cache_still_consistent(self):
        # Same-prefix request after fused decode must produce identical
        # tokens (cached pages registered only for committed tokens).
        p = _prompt(3, 16)
        eng = _engine(decode_steps_per_iter=4)
        a = eng.add_request(p, SamplingParams(max_new_tokens=6))
        eng.run_until_complete()
        b = eng.add_request(p, SamplingParams(max_new_tokens=6))
        eng.run_until_complete()
        assert b.num_cached_prompt > 0
        assert a.output_tokens == b.output_tokens

    def test_fused_preemption_under_tiny_pool(self):
        # Pool sized to force preemption during reservation; everything
        # still completes with the right token counts.
        eng = _engine(total_pages=14, decode_batch=3, decode_steps_per_iter=4)
        seqs = [
            eng.add_request(_prompt(10 + i, 8), SamplingParams(max_new_tokens=8))
            for i in range(3)
        ]
        eng.run_until_complete()
        for s in seqs:
            assert s.error is None
            assert len(s.output_tokens) == 8


class TestDecodePipeline:
    """decode_pipeline=True: burst N+1 dispatched before burst N commits.

    Invariant under test (engine.py ``_run_decode_fused`` docstring): the
    pipelined token streams are IDENTICAL to the unpipelined fused engine
    across every drain edge — staggered arrivals (lane-set change),
    preemption inside reservation, stop tokens, and max-token truncation
    that is not a multiple of the burst.
    """

    def _outputs(self, drive, **kw):
        outs = []
        for pipelined in (False, True):
            eng = _engine(
                decode_steps_per_iter=4, decode_pipeline=pipelined, **kw
            )
            outs.append(drive(eng))
        return outs

    def test_pipelined_greedy_matches_unpipelined(self):
        prompts = [_prompt(20 + i, 9 + i) for i in range(3)]

        def drive(eng):
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=13))
                for p in prompts
            ]
            eng.run_until_complete()
            return [s.generated_tokens for s in seqs]

        base, piped = self._outputs(drive)
        assert base == piped
        # 13 % 4 != 0: the final partial burst (and any surplus pipelined
        # burst) must be truncated identically.
        assert all(len(toks) == 13 for toks in base)

    def test_staggered_arrival_lane_change_drains(self):
        # A second request arriving mid-generation forces a prefill (and
        # thus a pipeline drain + lane-set change) between decode bursts.
        def drive(eng):
            a = eng.add_request(_prompt(30, 8), SamplingParams(max_new_tokens=12))
            for _ in range(3):
                eng.step()
            b = eng.add_request(_prompt(31, 10), SamplingParams(max_new_tokens=12))
            eng.run_until_complete()
            return [a.generated_tokens, b.generated_tokens]

        base, piped = self._outputs(drive)
        assert base == piped
        assert all(len(toks) == 12 for toks in base)

    def test_pipelined_preemption_tiny_pool(self):
        # Pool sized to force preemption during burst reservation — the
        # in-flight burst's lane may be knocked out, and the 2x pipelined
        # headroom must degrade to the unpipelined reservation instead of
        # aborting lanes the unpipelined engine completes.
        from llm_d_kv_cache_manager_tpu.server.block_manager import AllocationError

        def drive(eng):
            bm = eng.block_manager
            orig = bm.reserve_slots
            pressure = [0]

            def spy(seq, n):
                try:
                    return orig(seq, n)
                except AllocationError:
                    pressure[0] += 1
                    raise

            bm.reserve_slots = spy
            seqs = [
                eng.add_request(_prompt(10 + i, 8), SamplingParams(max_new_tokens=8))
                for i in range(3)
            ]
            eng.run_until_complete()
            assert pressure[0] > 0, "pool never under pressure; test too big"
            assert all(s.error is None for s in seqs)
            return [s.generated_tokens for s in seqs]

        base, piped = self._outputs(drive, total_pages=12, decode_batch=3)
        assert base == piped
        assert all(len(toks) == 8 for toks in base)

    def test_pipelined_stop_token_truncates(self):
        probe_eng = _engine(decode_steps_per_iter=4)
        probe = probe_eng.add_request(_prompt(2, 8), SamplingParams(max_new_tokens=3))
        probe_eng.run_until_complete()
        stop = probe.output_tokens[1]

        def drive(eng):
            seq = eng.add_request(
                _prompt(2, 8),
                SamplingParams(max_new_tokens=8, stop_token_ids=(stop,)),
            )
            eng.run_until_complete()
            return seq.generated_tokens

        base, piped = self._outputs(drive)
        assert base == piped
        assert piped[-1] == stop and len(piped) == 2

    def test_pipelined_prefix_cache_still_consistent(self):
        # Pages registered while a burst is in flight must only cover
        # committed tokens; a same-prefix follow-up must reproduce tokens.
        p = _prompt(3, 16)

        def drive(eng):
            a = eng.add_request(p, SamplingParams(max_new_tokens=6))
            eng.run_until_complete()
            b = eng.add_request(p, SamplingParams(max_new_tokens=6))
            eng.run_until_complete()
            assert b.num_cached_prompt > 0
            return [a.generated_tokens, b.generated_tokens]

        base, piped = self._outputs(drive)
        assert base == piped

    def test_inactive_lane_sentinel_preserved_when_chaining(self):
        # White-box: when burst N+1 chains on-device from burst N, only
        # previously-active lanes advance; padded lanes keep the
        # documented 0 = inactive sentinel (no garbage attention, no KV
        # writes into reserved page 0).
        eng = _engine(decode_batch=4, decode_steps_per_iter=2, decode_pipeline=True)
        seqs = [
            eng.add_request(_prompt(40 + i, 8), SamplingParams(max_new_tokens=20))
            for i in range(2)
        ]
        eng.step()  # prefills both (max_prefill_batch=4)
        eng._run_decode_fused(seqs)  # burst 1 in flight
        assert eng._inflight is not None
        eng._run_decode_fused(seqs)  # burst 2 chained from burst 1
        burst = eng._inflight
        np.testing.assert_array_equal(burst["seq_lens"][2:], 0)
        np.testing.assert_array_equal(burst["positions"][2:], 0)
        assert (burst["seq_lens"][:2] > 0).all()
        eng._drain_inflight()

    def test_env_knob_wires_decode_pipeline(self, monkeypatch):
        from llm_d_kv_cache_manager_tpu.server.serve import PodServerConfig

        monkeypatch.setenv("DECODE_PIPELINE", "1")
        monkeypatch.setenv("DECODE_STEPS_PER_ITER", "4")
        cfg = PodServerConfig.from_env()
        assert cfg.engine.decode_pipeline is True
        assert cfg.engine.decode_steps_per_iter == 4
        monkeypatch.setenv("DECODE_PIPELINE", "0")
        assert PodServerConfig.from_env().engine.decode_pipeline is False


class TestTensorParallelServing:
    """EngineConfig.tp > 1: Megatron-sharded params + head-parallel KV over
    a tp mesh (CPU-virtualized devices; conftest forces 8)."""

    def test_tp_greedy_matches_single_chip(self):
        prompts = [_prompt(20 + i, 10 + i) for i in range(3)]
        outs = []
        for tp in (1, 2):
            eng = _engine(tp=tp)
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=6))
                for p in prompts
            ]
            eng.run_until_complete()
            outs.append([s.output_tokens for s in seqs])
        assert outs[0] == outs[1]

    def test_tp_fused_decode_and_prefix_cache(self):
        p = _prompt(30, 16)
        eng = _engine(tp=2, decode_steps_per_iter=4)
        a = eng.add_request(p, SamplingParams(max_new_tokens=6))
        eng.run_until_complete()
        b = eng.add_request(p, SamplingParams(max_new_tokens=6))
        eng.run_until_complete()
        assert b.num_cached_prompt > 0
        assert a.output_tokens == b.output_tokens

    def test_tp_must_divide_heads(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            _engine(tp=3)

    def test_tp_qk_norm_model_serves(self):
        # Regression: qk-norm (Qwen3-style) params must have sharding specs,
        # and TP output must match single-chip.
        import dataclasses

        cfg = dataclasses.replace(TINY_LLAMA, qk_norm=True)
        p = _prompt(35, 10)
        outs = []
        for tp in (1, 2):
            eng = _engine(tp=tp, model=cfg)
            s = eng.add_request(p, SamplingParams(max_new_tokens=5))
            eng.run_until_complete()
            outs.append(s.output_tokens)
        assert outs[0] == outs[1]


class TestHostDramOffloadTier:
    """BlockManagerConfig.host_pages > 0: evicted HBM pages spill to host
    DRAM with medium-tagged events; prefix hits restore them."""

    def test_restored_pages_preserve_kv_exactly(self):
        # Reference: pool big enough that nothing is ever evicted.
        prompts = [_prompt(40 + i, 16) for i in range(3)]
        ref = _engine(total_pages=64)
        ref_outs = []
        for p in prompts + [prompts[0]]:
            s = ref.add_request(p, SamplingParams(max_new_tokens=5))
            ref.run_until_complete()
            ref_outs.append(s.output_tokens)

        # Tiered: pool so small that prompt A's pages are evicted (to host)
        # by B and C; the repeat of A must restore them and match exactly.
        # host_tier_policy="always" pins the MECHANISM (restore exactness)
        # independent of what the cost model thinks of this rig's link.
        eng = _engine(total_pages=12, host_pages=32, host_tier_policy="always")
        outs = []
        for p in prompts + [prompts[0]]:
            s = eng.add_request(p, SamplingParams(max_new_tokens=5))
            eng.run_until_complete()
            outs.append(s.output_tokens)
        assert outs == ref_outs
        assert s.num_cached_prompt > 0  # repeat of A hit the restored pages

    def test_restore_declined_when_recompute_is_cheaper(self):
        # Recompute-vs-restore cost model: with measured rates that make
        # the restore DMA lose (slow tier, fast prefill), a prefix hit on
        # the host tier must be DECLINED — same tokens, zero restores.
        prompts = [_prompt(40 + i, 16) for i in range(3)]

        def run(force_slow_restore):
            # Baseline arm pins "always" so its restores are guaranteed
            # regardless of this rig's measured link; the slow arm runs
            # "auto" with pinned EMAs — the decline under test.
            eng = _engine(
                total_pages=12, host_pages=32,
                host_tier_policy="auto" if force_slow_restore else "always",
            )
            outs = []
            for p in prompts + [prompts[0]]:
                if force_slow_restore:
                    # Pin the EMAs: restoring one page "takes" 1000x the
                    # recompute of its tokens.
                    eng._prefill_rate = 1e9
                    eng._restore_rate = 1e-3
                s = eng.add_request(p, SamplingParams(max_new_tokens=5))
                eng.run_until_complete()
                outs.append(s.output_tokens)
            return eng, s, outs

        ref_eng, ref_last, ref_outs = run(force_slow_restore=False)
        assert ref_last.num_cached_prompt > 0  # baseline DID restore
        eng, last, outs = run(force_slow_restore=True)
        assert outs == ref_outs  # recompute path is exact
        assert last.num_cached_prompt == 0  # ...but nothing was restored

    def test_victim_choice_minimizes_bring_back_cost(self):
        # With the tier on and rates pinned so restores are ~free, the
        # preemption victim should be the sequence whose pages are
        # REGISTERED (restorable) — not the most recent one.
        eng = _engine(total_pages=14, host_pages=32, decode_batch=2)
        a = eng.add_request(_prompt(1, 30), SamplingParams(max_new_tokens=40))
        eng.step()  # prefill A; its prompt pages register
        b = eng.add_request(_prompt(2, 9), SamplingParams(max_new_tokens=40))
        eng.step()  # prefill B (fits in the remaining pages)
        assert a.num_registered_pages > b.num_registered_pages
        eng._prefill_rate = 100.0
        eng._restore_rate = 1e9  # restores ~free -> registered seq is cheap
        victim = eng._pick_victim(b)
        assert victim is a
        # And with no tier data the policy stays recency (most recent
        # other sequence).
        eng._restore_rate = None
        eng._prefill_rate = None
        assert eng._pick_victim(a) is b

    def test_fused_decode_spill_snapshots_before_overwrite(self):
        """Regression for the batched-mover ordering hazard: during FUSED
        decode, burst reservation can preempt a victim and recycle its
        pages; the queued offload must snapshot the victim's KV BEFORE the
        same dispatch overwrites those pages (flush must run after
        reservation, before decode_steps). A later repeat of the victim's
        prompt restores from host and must match a no-eviction engine."""
        prompts = [_prompt(80 + i, 20) for i in range(3)]

        def run(total_pages, host_pages):
            eng = _engine(
                total_pages=total_pages,
                host_pages=host_pages,
                decode_batch=4,
                decode_steps_per_iter=4,
                # mechanism test: spills/restores must actually happen
                host_tier_policy="always",
            )
            outs = []
            # Concurrent requests on a tight pool: fused-burst reservation
            # preempts and spills mid-flight.
            for p in prompts:
                eng.add_request(p, SamplingParams(max_new_tokens=8))
            eng.run_until_complete()
            # Repeat the first prompt: served from restored host pages.
            s = eng.add_request(prompts[0], SamplingParams(max_new_tokens=8))
            eng.run_until_complete()
            outs.append(s.output_tokens)
            return outs, s

        ref_outs, _ = run(total_pages=64, host_pages=0)
        tiered_outs, s = run(total_pages=14, host_pages=64)
        assert tiered_outs == ref_outs

    def test_offload_and_restore_emit_medium_tagged_events(self):
        captured = []
        eng = _engine(total_pages=12, host_pages=32, on_events=captured.extend,
                      host_tier_policy="always")
        a = _prompt(50, 16)
        for p in (a, _prompt(51, 16), _prompt(52, 16), a):
            eng.add_request(p, SamplingParams(max_new_tokens=5))
            eng.run_until_complete()
        media = [(type(e).__name__, e.medium) for e in captured]
        assert ("BlockStored", "host_dram") in media  # offload
        assert ("BlockRemoved", "host_dram") in media  # restore (swap back)
        assert ("BlockStored", "tpu_hbm") in media
        # The restore swapped A's pages back to HBM, so the host tier must
        # have fewer cached pages than were offloaded in total.
        stored_host = sum(
            1 for name, m in media if (name, m) == ("BlockStored", "host_dram")
        )
        assert eng.block_manager.num_host_cached_pages < stored_host

    def test_host_pool_lru_eviction(self):
        # Host tier smaller than the spill volume: oldest host pages get
        # BlockRemoved(host_dram) and the engine keeps working.
        captured = []
        eng = _engine(total_pages=12, host_pages=4, on_events=captured.extend,
                      host_tier_policy="always")
        for i in range(6):
            eng.add_request(_prompt(60 + i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        removed_host = [
            e for e in captured
            if type(e).__name__ == "BlockRemoved" and e.medium == "host_dram"
        ]
        assert removed_host  # LRU host eviction happened
        assert eng.block_manager.num_host_cached_pages <= 4

    def test_flush_dedupes_same_destination_page_last_wins(self, monkeypatch):
        """Two queued restores into the same device page within one flush
        window must land the LAST block's data, AND the batched scatter
        must never see duplicate destination indices (duplicate-index
        scatter order is only nondeterministic on TPU — CPU CI applies
        last-wins regardless, so the data assertion alone could not catch
        a dedupe regression)."""
        from llm_d_kv_cache_manager_tpu.server import engine as engine_mod

        eng = _engine(total_pages=8, host_pages=4)
        L, ps, kv, hd = (
            eng.model_cfg.n_layers,
            eng.page_size,
            eng.model_cfg.n_kv_heads,
            eng.model_cfg.hd,
        )
        # Distinct K and V payloads: a K/V channel swap must not pass.
        ak = np.full((L, ps, kv, hd), 1.0, np.float32)
        av = np.full((L, ps, kv, hd), -1.0, np.float32)
        bk = np.full((L, ps, kv, hd), 2.0, np.float32)
        bv = np.full((L, ps, kv, hd), -2.0, np.float32)
        eng._host_k[0], eng._host_v[0] = ak, av
        eng._host_k[1], eng._host_v[1] = bk, bv

        seen_idx = []
        real_write = engine_mod._write_pages_batch

        def spy(pages, idx, data):
            seen_idx.append(np.asarray(idx))
            return real_write(pages, idx, data)

        monkeypatch.setattr(engine_mod, "_write_pages_batch", spy)
        page = 3
        eng._restore_page(0, page)  # A → p (later rolled back)
        eng._restore_page(1, page)  # B → p (the live restore)
        eng._flush_page_moves()
        np.testing.assert_array_equal(np.asarray(eng.k_pages[:, page]), bk)
        np.testing.assert_array_equal(np.asarray(eng.v_pages[:, page]), bv)
        assert not eng._pending_restores and not eng._restore_by_page
        total = eng.config.block_manager.total_pages
        for idx in seen_idx:  # real (non-pad) destinations are unique
            real = idx[idx < total]
            assert len(real) == len(set(real.tolist())), idx

    def test_flush_restore_from_pending_offload_slot(self):
        """A restore sourced from a host slot whose offload is still
        pending must read the offloading device page, not the stale host
        slot contents — for BOTH the K and V channels."""
        eng = _engine(total_pages=8, host_pages=2)
        L = eng.model_cfg.n_layers
        shape = (L, eng.page_size, eng.model_cfg.n_kv_heads, eng.model_cfg.hd)
        mk = np.full(shape, 7.0, np.float32)
        mv = np.full(shape, -7.0, np.float32)
        eng.k_pages = eng.k_pages.at[:, 5].set(mk)
        eng.v_pages = eng.v_pages.at[:, 5].set(mv)
        eng._offload_page(5, slot=0)  # queued, host slot 0 still stale
        eng._restore_page(0, page=2)  # restore of that very slot
        eng._flush_page_moves()
        np.testing.assert_array_equal(np.asarray(eng.k_pages[:, 2]), mk)
        np.testing.assert_array_equal(np.asarray(eng.v_pages[:, 2]), mv)
        np.testing.assert_array_equal(eng._host_k[0], mk)
        np.testing.assert_array_equal(eng._host_v[0], mv)

    def test_single_host_slot_mid_restore_does_not_crash(self):
        # Regression: with host_pages=1, restoring the only host slot while
        # HBM recycling wants to spill must skip the spill, not KeyError.
        eng = _engine(total_pages=3, host_pages=1)
        a = _prompt(70, 3)
        eng.add_request(a, SamplingParams(max_new_tokens=2))
        eng.run_until_complete()
        eng.add_request(_prompt(71, 6), SamplingParams(max_new_tokens=2))
        eng.run_until_complete()
        s = eng.add_request(a, SamplingParams(max_new_tokens=2))
        eng.run_until_complete()
        assert s.error is None and len(s.output_tokens) == 2


class TestGemmaServing:
    """Gemma family through the full engine: the (1+w)-norm / gated-GELU /
    scaled-embedding variations must survive continuous batching, prefix
    caching, and tensor parallelism unchanged."""

    def test_gemma_greedy_matches_single_chip(self):
        from llm_d_kv_cache_manager_tpu.models import TINY_GEMMA

        prompts = [_prompt(80 + i, 10 + i) for i in range(2)]
        outs = []
        for tp in (1, 2):
            eng = _engine(tp=tp, model=TINY_GEMMA)
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=5))
                for p in prompts
            ]
            eng.run_until_complete()
            outs.append([s.output_tokens for s in seqs])
        assert outs[0] == outs[1]

    def test_gemma_prefix_cache_hit(self):
        from llm_d_kv_cache_manager_tpu.models import TINY_GEMMA

        p = _prompt(90, 16)
        eng = _engine(model=TINY_GEMMA)
        a = eng.add_request(p, SamplingParams(max_new_tokens=5))
        eng.run_until_complete()
        b = eng.add_request(p, SamplingParams(max_new_tokens=5))
        eng.run_until_complete()
        assert b.num_cached_prompt > 0
        assert a.output_tokens == b.output_tokens


class TestMoEServing:
    """Mixtral-style MoE model through the full engine: continuous batching,
    prefix cache, and expert-parallel TP must all preserve greedy output."""

    def test_moe_greedy_matches_single_chip(self):
        from llm_d_kv_cache_manager_tpu.models import TINY_MOE

        prompts = [_prompt(60 + i, 10 + i) for i in range(2)]
        outs = []
        for tp in (1, 2):
            eng = _engine(tp=tp, model=TINY_MOE)
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=5))
                for p in prompts
            ]
            eng.run_until_complete()
            outs.append([s.output_tokens for s in seqs])
        assert outs[0] == outs[1]

    def test_moe_prefix_cache_hit(self):
        from llm_d_kv_cache_manager_tpu.models import TINY_MOE

        p = _prompt(70, 16)
        eng = _engine(model=TINY_MOE)
        a = eng.add_request(p, SamplingParams(max_new_tokens=5))
        eng.run_until_complete()
        b = eng.add_request(p, SamplingParams(max_new_tokens=5))
        eng.run_until_complete()
        assert b.num_cached_prompt > 0
        assert a.output_tokens == b.output_tokens

    def test_qwen3_moe_serves_with_tp(self):
        """qk-norm + MoE + decoupled expert width through the engine: greedy
        output stable across tensor parallelism."""
        from llm_d_kv_cache_manager_tpu.models import TINY_QWEN3_MOE

        prompts = [_prompt(95 + i, 10 + i) for i in range(2)]
        outs = []
        for tp in (1, 2):
            eng = _engine(tp=tp, model=TINY_QWEN3_MOE)
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=5))
                for p in prompts
            ]
            eng.run_until_complete()
            outs.append([s.output_tokens for s in seqs])
        assert outs[0] == outs[1]


class TestSpeculativeDecode:
    """Prompt-lookup speculative decoding: token streams must be IDENTICAL
    to plain greedy decode (spec verify accepts exactly the model's own
    greedy choices), across stop/max-token edges and cache interaction —
    only the number of dispatches may differ."""

    def _pair(self, **kw):
        return (
            _engine(**kw),
            _engine(spec_decode="prompt_lookup", spec_k=4, spec_ngram=2, **kw),
        )

    def test_spec_matches_plain_greedy(self):
        # Mixed workload: a repetitive prompt (lookup hits) and a random
        # one (lookup mostly misses).
        rep = _prompt(50, 6) * 3
        prompts = [rep, _prompt(51, 13)]

        def drive(eng):
            seqs = [
                eng.add_request(p, SamplingParams(max_new_tokens=11))
                for p in prompts
            ]
            eng.run_until_complete()
            assert all(s.error is None for s in seqs)
            return [s.generated_tokens for s in seqs]

        base, spec = (drive(e) for e in self._pair())
        assert base == spec
        assert all(len(t) == 11 for t in spec)

    def test_spec_stop_token_truncates(self):
        probe = _engine()
        p = probe.add_request(_prompt(52, 8), SamplingParams(max_new_tokens=4))
        probe.run_until_complete()
        stop = p.output_tokens[2]

        eng = _engine(spec_decode="prompt_lookup", spec_k=4, spec_ngram=2)
        seq = eng.add_request(
            _prompt(52, 8), SamplingParams(max_new_tokens=16, stop_token_ids=(stop,))
        )
        eng.run_until_complete()
        assert seq.generated_tokens[-1] == stop
        assert len(seq.generated_tokens) == 3

    def test_spec_prefix_cache_consistent(self):
        # Pages registered after spec commits must hold CORRECT hashes:
        # a same-prefix follow-up must cache-hit and reproduce tokens.
        p = _prompt(53, 16)
        eng = _engine(spec_decode="prompt_lookup", spec_k=4, spec_ngram=2)
        a = eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.run_until_complete()
        b = eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.run_until_complete()
        assert b.num_cached_prompt > 0
        assert a.generated_tokens == b.generated_tokens

    def test_spec_accepts_on_repetitive_output(self):
        # A 2-token cycle in the prompt makes greedy output echo it; the
        # lookup must then accept drafts (the mechanism's whole point).
        cyc = _prompt(54, 2) * 8
        eng = _engine(spec_decode="prompt_lookup", spec_k=4, spec_ngram=2)
        eng.add_request(cyc, SamplingParams(max_new_tokens=12))
        eng.run_until_complete()
        assert eng.spec_stats["verify_steps"] > 0
        # Not guaranteed >0 for arbitrary weights, but with a tiny model on
        # a pure cycle greedy almost always repeats; keep a soft floor.
        assert eng.spec_stats["proposed"] >= 0

    @pytest.mark.parametrize("rounds", [1, 3])
    def test_spec_sampled_lane_generates(self, rounds):
        # temperature>0 runs deterministic-draft speculative sampling
        # (inside the device scan when rounds > 1); the request completes
        # with the right count and in-vocab tokens.
        cyc = _prompt(55, 2) * 8
        eng = _engine(
            spec_decode="prompt_lookup", spec_k=4, spec_ngram=2,
            spec_rounds=rounds,
        )
        seq = eng.add_request(
            cyc, SamplingParams(max_new_tokens=9, temperature=0.8, top_k=8)
        )
        eng.run_until_complete()
        assert len(seq.generated_tokens) == 9
        assert all(0 <= t < TINY_LLAMA.vocab_size for t in seq.generated_tokens)

    @pytest.mark.parametrize("rounds", [1, 3])
    def test_spec_topk1_sampling_equals_greedy(self, rounds):
        # top_k=1 collapses every filtered distribution to a point mass, so
        # temperature>0 spec sampling must emit EXACTLY the greedy stream —
        # a deterministic end-to-end check of the acceptance/residual math,
        # including through the multi-round device scan.
        cyc = _prompt(57, 3) * 6
        outs = []
        for sampling in (
            SamplingParams(max_new_tokens=10),
            SamplingParams(max_new_tokens=10, temperature=0.9, top_k=1),
        ):
            eng = _engine(
                spec_decode="prompt_lookup", spec_k=4, spec_ngram=2,
                spec_rounds=rounds,
            )
            seq = eng.add_request(list(cyc), sampling)
            eng.run_until_complete()
            outs.append(seq.generated_tokens)
        assert outs[0] == outs[1]

    def test_spec_mixed_greedy_and_sampled_batch(self):
        eng = _engine(spec_decode="prompt_lookup", spec_k=3, spec_ngram=2)
        g = eng.add_request(_prompt(58, 2) * 6, SamplingParams(max_new_tokens=7))
        s = eng.add_request(
            _prompt(59, 9),
            SamplingParams(max_new_tokens=7, temperature=0.7, top_p=0.9),
        )
        eng.run_until_complete()
        assert len(g.generated_tokens) == 7 and len(s.generated_tokens) == 7
        # The greedy lane must match a spec engine run without the sampled
        # batchmate (per-lane independence).
        eng2 = _engine(spec_decode="prompt_lookup", spec_k=3, spec_ngram=2)
        g2 = eng2.add_request(_prompt(58, 2) * 6, SamplingParams(max_new_tokens=7))
        eng2.run_until_complete()
        assert g.generated_tokens == g2.generated_tokens

    def test_spec_under_pool_pressure(self):
        def drive(eng):
            seqs = [
                eng.add_request(_prompt(56 + i, 8), SamplingParams(max_new_tokens=8))
                for i in range(3)
            ]
            eng.run_until_complete()
            assert all(s.error is None for s in seqs)
            return [s.generated_tokens for s in seqs]

        base, spec = (
            drive(e) for e in self._pair(total_pages=14, decode_batch=3)
        )
        assert base == spec

    def test_spec_rejects_bad_config(self):
        with pytest.raises(ValueError, match="spec_decode"):
            _engine(spec_decode="medusa")
        with pytest.raises(ValueError, match="spec_k"):
            _engine(spec_decode="prompt_lookup", spec_k=0)

    def test_spec_adaptive_gate_stops_hopeless_proposals(self):
        # Force the gate shut by making acceptance impossible: propose from
        # a seq whose output never echoes (random prompt) and verify the
        # engine stops paying verify dispatches once the sample fills.
        eng = _engine(
            spec_decode="prompt_lookup", spec_k=4, spec_ngram=1,
            spec_min_accept=1.1,  # nothing can satisfy this
            spec_min_sample=4,
        )
        # Budget must leave room for a full-k proposal when the first match
        # lands: proposals are clamped to max_new_tokens - generated - 1
        # (drafts past the budget can never be emitted), so a budget that
        # expires right at the first match would starve the gate's sample
        # counter instead of exercising the gate.
        seq = eng.add_request(_prompt(60, 10), SamplingParams(max_new_tokens=40))
        eng.run_until_complete()
        assert len(seq.generated_tokens) == 40
        stats = eng.spec_stats
        # Gate must have ENGAGED, not been vacuously absent: proposals
        # happened, then stopped shortly after the sample threshold — far
        # below the no-gate worst case (~k per token).
        assert stats["proposed"] >= eng.config.spec_min_sample
        assert stats["proposed"] <= eng.config.spec_min_sample + eng.config.spec_k

    def test_fused_rounds_same_tokens_fewer_host_syncs(self):
        # The point of spec_rounds: an echo-heavy workload decodes the
        # same greedy stream with ~rounds× fewer host syncs (bursts).
        prompt = ([7, 3, 9, 5, 2] * 6)[:28]
        streams, bursts = [], []
        for rounds in (1, 4):
            eng = _engine(
                spec_decode="prompt_lookup", spec_k=4, spec_ngram=2,
                spec_rounds=rounds,
            )
            seq = eng.add_request(prompt, SamplingParams(max_new_tokens=20))
            eng.run_until_complete()
            streams.append(seq.generated_tokens)
            bursts.append(eng.spec_stats["bursts"])
            assert len(seq.generated_tokens) == 20
            # Every dispatched round is accounted.
            assert eng.spec_stats["verify_steps"] == rounds * eng.spec_stats["bursts"]
        assert streams[0] == streams[1]
        assert bursts[1] < bursts[0], (bursts, "fused rounds should cut syncs")

    def test_fused_rounds_respect_budget_clamp(self):
        # A lane whose budget expires mid-burst must stop emitting exactly
        # at max_new_tokens even though the device keeps verifying.
        prompt = ([4, 8, 1] * 8)[:20]
        eng = _engine(
            spec_decode="prompt_lookup", spec_k=4, spec_ngram=2,
            spec_rounds=4,
        )
        seq = eng.add_request(prompt, SamplingParams(max_new_tokens=6))
        eng.run_until_complete()
        assert len(seq.generated_tokens) == 6
        assert seq.num_tokens <= eng.config.max_model_len


class TestDecodePathParityFuzz:
    """Randomized cross-path parity: for random prompts/arrival patterns
    and pool sizes, the four decode paths (plain, fused, pipelined, spec)
    must produce IDENTICAL greedy token streams — the edges the targeted
    tests don't enumerate (odd prompt lengths, mixed finish times, pool
    sizes near the preemption boundary) get swept here."""

    CONFIGS = [
        dict(),  # plain
        dict(decode_steps_per_iter=3),  # fused, odd burst
        dict(decode_steps_per_iter=3, decode_pipeline=True),
        dict(spec_decode="prompt_lookup", spec_k=3, spec_ngram=2),
        dict(host_pages=16),  # host-DRAM offload tier in the loop
        dict(sp=2),  # sequence-parallel prefill on the virtual mesh
        # interaction: spec verify dispatches through an sp-sharded prefill
        dict(sp=2, spec_decode="prompt_lookup", spec_k=3, spec_ngram=2),
        # interaction: spec's empty-proposal fallback lands in the
        # PIPELINED fused path (drain-before-spec + chained bursts)
        dict(
            decode_steps_per_iter=3,
            decode_pipeline=True,
            spec_decode="prompt_lookup",
            spec_k=3,
            spec_ngram=2,
        ),
        # FUSED multi-round spec: propose/verify/accept chained on device,
        # one host sync per 3 rounds (llama.spec_decode_steps scan)
        dict(spec_decode="prompt_lookup", spec_k=3, spec_ngram=2,
             spec_rounds=3),
        # interaction: fused spec rounds through an sp-sharded prefill body
        dict(sp=2, spec_decode="prompt_lookup", spec_k=3, spec_ngram=2,
             spec_rounds=2),
        # interaction: fused spec rounds + host-DRAM tier page moves
        dict(host_pages=16, spec_decode="prompt_lookup", spec_k=3,
             spec_ngram=2, spec_rounds=3),
        # interaction: fused spec rounds with the empty-proposal fallback
        # landing in pipelined fused bursts
        dict(decode_steps_per_iter=3, decode_pipeline=True,
             spec_decode="prompt_lookup", spec_k=3, spec_ngram=2,
             spec_rounds=3),
    ]

    @pytest.mark.parametrize("seed", [101, 202, 303, 404, 505])
    def test_paths_agree(self, seed):
        rng = np.random.default_rng(seed)
        n_req = int(rng.integers(2, 5))
        prompts = []
        for _ in range(n_req):
            if rng.random() < 0.5:  # repetition-heavy (exercises spec)
                pat = _prompt(int(rng.integers(0, 1000)), int(rng.integers(2, 5)))
                prompts.append((pat * 6)[: int(rng.integers(8, 20))])
            else:
                prompts.append(_prompt(int(rng.integers(0, 1000)), int(rng.integers(5, 20))))
        max_new = [int(rng.integers(3, 12)) for _ in range(n_req)]
        pages = int(rng.integers(24, 64))
        stagger = int(rng.integers(0, 3))

        streams = []
        for kw in self.CONFIGS:
            eng = _engine(total_pages=pages, decode_batch=3, **kw)
            seqs = []
            for i, (p, m) in enumerate(zip(prompts, max_new)):
                seqs.append(eng.add_request(p, SamplingParams(max_new_tokens=m)))
                if stagger and i < n_req - 1:
                    for _ in range(stagger):
                        eng.step()
            eng.run_until_complete()
            assert all(s.error is None for s in seqs), kw
            streams.append([s.generated_tokens for s in seqs])
        for i, got in enumerate(streams[1:], 1):
            assert got == streams[0], f"config {self.CONFIGS[i]} diverged (seed {seed})"
        assert all(len(t) == m for t, m in zip(streams[0], max_new))
