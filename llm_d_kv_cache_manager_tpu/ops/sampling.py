"""Token sampling: greedy / temperature / top-k / top-p, jit-compiled.

One fused function over the batch — sampling params are per-sequence arrays
so mixed strategies share a single compiled program (no per-request
recompiles, XLA-friendly static shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jnp.ndarray,  # [batch, vocab] f32
    temperature: jnp.ndarray,  # [batch] f32; 0 = greedy
    top_k: jnp.ndarray,  # [batch] int32; 0 = disabled
    top_p: jnp.ndarray,  # [batch] f32; 1 = disabled
    rng_key: jax.Array,
) -> jnp.ndarray:
    """Returns sampled token ids [batch] int32."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Temperature scaling (guard 0 for the greedy lanes).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # Top-k mask: keep the k highest logits per row.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [b, vocab]
    k = jnp.where(top_k > 0, top_k, vocab).astype(jnp.int32)
    kth_val = jnp.take_along_axis(
        sorted_desc, jnp.clip(k - 1, 0, vocab - 1)[:, None], axis=-1
    )
    masked = jnp.where(scaled >= kth_val, scaled, -jnp.inf)

    # Top-p (nucleus) on the surviving distribution.
    sorted_masked = jnp.sort(masked, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cumprobs = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens while cumulative prob (exclusive) < top_p
    cutoff_mask = (cumprobs - probs_sorted) < top_p[:, None]
    threshold = jnp.min(
        jnp.where(cutoff_mask, sorted_masked, jnp.inf), axis=-1, keepdims=True
    )
    masked = jnp.where(masked >= threshold, masked, -jnp.inf)

    sampled = jax.random.categorical(rng_key, masked, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)
