"""Fleet observability: request tracing + latency decomposition.

The tracing layer is dependency-free (stdlib only) and off by default —
``OBS_TRACING=1`` turns it on per process. Spans propagate across the
scorer → pod → transfer-peer hop via W3C ``traceparent`` (HTTP headers on
the scoring/serving APIs, a trailing optional field in the KV-transfer
msgpack envelope), so one request's time is attributable end to end.
"""

from .audit import (  # noqa: F401
    AuditRecord,
    RouteAuditor,
    StalenessTracker,
    debug_audit_payload,
    debug_staleness_payload,
)
from .federation import (  # noqa: F401
    SCRAPE_SURFACES,
    FederatedPod,
    FleetFederator,
    debug_fleet_payload,
)
from .slo import (  # noqa: F401
    SLObjective,
    SLORecorder,
    parse_slo_spec,
    parse_windows,
)
from .tracing import (  # noqa: F401
    NOOP_SPAN,
    Span,
    SpanContext,
    Tracer,
    format_traceparent,
    parse_traceparent,
)
