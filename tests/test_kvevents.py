"""Event plane tests: schema round-trip, legacy tolerance, sharded ordering,
poison pills, and the end-to-end ZMQ offline-demo flow (reference §3.5)."""

import struct
import time

import msgpack

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    DeviceTier,
    InMemoryIndex,
    Key,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
    Heartbeat,
    IndexSnapshot,
    KVEventsPool,
    KVEventsPoolConfig,
    Message,
    ZMQPublisher,
    ZMQPublisherConfig,
    ZMQSubscriber,
    ZMQSubscriberConfig,
    decode_event_batch,
    fnv1a_32,
    parse_topic,
)

MODEL = "meta-llama/Llama-3-8B"


class TestEventSchema:
    def test_round_trip(self):
        batch = EventBatch(
            ts=123.5,
            events=[
                BlockStored(
                    block_hashes=[1, 2, 3],
                    parent_block_hash=7,
                    token_ids=[10, 11],
                    block_size=16,
                    medium="tpu_hbm",
                ),
                BlockRemoved(block_hashes=[2], medium="host_dram"),
                AllBlocksCleared(),
            ],
            data_parallel_rank=1,
        )
        decoded = decode_event_batch(batch.to_payload())
        assert decoded.ts == 123.5
        assert decoded.data_parallel_rank == 1
        bs, br, ac = decoded.events
        assert bs == batch.events[0]
        assert br == batch.events[1]
        assert isinstance(ac, AllBlocksCleared)

    def test_legacy_block_stored_without_medium(self):
        # Legacy arity: [tag, hashes, parent, tokens, block_size, lora_id]
        raw = [1000.0, [["BlockStored", [5, 6], None, [1, 2], 16, None]]]
        decoded = decode_event_batch(msgpack.packb(raw))
        (ev,) = decoded.events
        assert ev.block_hashes == [5, 6]
        assert ev.medium is None

    def test_legacy_block_removed_minimal(self):
        raw = [1000.0, [["BlockRemoved", [5]]]]
        decoded = decode_event_batch(msgpack.packb(raw))
        (ev,) = decoded.events
        assert ev.block_hashes == [5]
        assert ev.medium is None

    def test_unknown_tag_skipped(self):
        raw = [1.0, [["FutureEvent", 1, 2], ["BlockRemoved", [9]]]]
        decoded = decode_event_batch(msgpack.packb(raw))
        assert len(decoded.events) == 1
        assert decoded.events[0].block_hashes == [9]

    def test_poison_pill_returns_none(self):
        assert decode_event_batch(b"\xff\xfe not msgpack") is None
        assert decode_event_batch(msgpack.packb("just a string")) is None
        assert decode_event_batch(msgpack.packb([1.0])) is None
        assert decode_event_batch(msgpack.packb(["not-a-ts", []])) is None
        assert decode_event_batch(msgpack.packb([None, []])) is None

    def test_nested_raw_event_bytes(self):
        # Events may arrive as embedded msgpack blobs (reference RawMessage).
        inner = msgpack.packb(["BlockRemoved", [4], None])
        decoded = decode_event_batch(msgpack.packb([1.0, [inner]]))
        assert decoded.events[0].block_hashes == [4]

    def test_uint64_hashes_survive(self):
        big = 2**64 - 1
        batch = EventBatch(ts=0.0, events=[BlockStored(block_hashes=[big])])
        decoded = decode_event_batch(batch.to_payload())
        assert decoded.events[0].block_hashes == [big]

    def test_heartbeat_round_trip(self):
        batch = EventBatch(ts=1.0, events=[Heartbeat(dropped_batches=7)])
        (ev,) = decode_event_batch(batch.to_payload()).events
        assert ev == Heartbeat(dropped_batches=7)
        # bare legacy form: ["Heartbeat"] with no fields
        (ev,) = decode_event_batch(msgpack.packb([1.0, [["Heartbeat"]]])).events
        assert ev == Heartbeat(dropped_batches=0)

    def test_index_snapshot_round_trip(self):
        snap = IndexSnapshot(
            blocks_by_medium={"tpu_hbm": [1, 2, 2**64 - 1], "host_dram": []}
        )
        batch = EventBatch(ts=1.0, events=[snap])
        (ev,) = decode_event_batch(batch.to_payload()).events
        assert ev == snap

    def test_malformed_snapshot_skipped(self):
        cases = [
            [1.0, [["IndexSnapshot"]]],                       # no digest
            [1.0, [["IndexSnapshot", ["not", "a", "dict"]]]],
            [1.0, [["IndexSnapshot", {"tpu_hbm": "not-a-list"}]]],
            [1.0, [["Heartbeat", "not-an-int"]]],             # tolerated → 0
        ]
        for case in cases[:3]:
            decoded = decode_event_batch(msgpack.packb(case))
            assert decoded is not None and decoded.events == []
        (hb,) = decode_event_batch(msgpack.packb(cases[-1])).events
        assert hb == Heartbeat(dropped_batches=0)


class TestFNV:
    def test_known_vectors(self):
        # Standard FNV-1a 32-bit test vectors.
        assert fnv1a_32(b"") == 0x811C9DC5
        assert fnv1a_32(b"a") == 0xE40C292C
        assert fnv1a_32(b"foobar") == 0xBF9CF968


class TestTopicParsing:
    def test_valid(self):
        assert parse_topic("kv@pod-1@meta-llama/Llama-3-8B") == ("pod-1", "meta-llama/Llama-3-8B")

    def test_model_with_at(self):
        assert parse_topic("kv@pod@org/model@rev") == ("pod", "org/model@rev")

    def test_invalid(self):
        assert parse_topic("kv@podonly") is None
        assert parse_topic("nonsense") is None
        assert parse_topic("kv@@model") is None


def _stored_payload(hashes, medium=None):
    return EventBatch(
        ts=time.time(), events=[BlockStored(block_hashes=hashes, medium=medium)]
    ).to_payload()


def _removed_payload(hashes, medium=None):
    return EventBatch(
        ts=time.time(), events=[BlockRemoved(block_hashes=hashes, medium=medium)]
    ).to_payload()


class TestKVEventsPool:
    def test_add_and_remove_flow(self):
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=2))
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([1, 2, 3])))
            assert pool.drain()
            got = index.lookup([Key(MODEL, h) for h in (1, 2, 3)], set())
            assert all(got[Key(MODEL, h)] == ["pod-1"] for h in (1, 2, 3))

            pool.add_task(Message("t", "pod-1", MODEL, _removed_payload([2])))
            assert pool.drain()
            got = index.lookup([Key(MODEL, 2)], set())
            assert got.get(Key(MODEL, 2), []) == []
        finally:
            pool.shutdown()

    def test_medium_maps_to_tier(self):
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([7], medium="host_dram")))
            assert pool.drain()
            # evicting the hbm-tier entry must not remove the dram-tier entry
            index.evict(Key(MODEL, 7), [PodEntry("pod-1", DeviceTier.TPU_HBM)])
            got = index.lookup([Key(MODEL, 7)], set())
            assert got[Key(MODEL, 7)] == ["pod-1"]
        finally:
            pool.shutdown()

    def test_mediumless_remove_clears_all_tiers(self):
        # A legacy BlockRemoved (no medium) must evict the pod's entry even
        # when the block was stored with an explicit medium.
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([7], medium="host_dram")))
            assert pool.drain()
            pool.add_task(Message("t", "pod-1", MODEL, _removed_payload([7])))  # no medium
            assert pool.drain()
            got = index.lookup([Key(MODEL, 7)], set())
            assert got.get(Key(MODEL, 7), []) == []
        finally:
            pool.shutdown()

    def test_poison_pill_does_not_kill_worker(self):
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, b"\x00garbage"))
            pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([42])))
            assert pool.drain()
            got = index.lookup([Key(MODEL, 42)], set())
            assert got[Key(MODEL, 42)] == ["pod-1"]
        finally:
            pool.shutdown()

    def test_per_pod_ordering_under_concurrency(self):
        """Store/remove pairs for one pod must apply in order even with many
        interleaved pods; final state must reflect the last event per pod."""
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=4))
        pool.start()
        try:
            pods = [f"pod-{i}" for i in range(8)]
            for round_ in range(50):
                for pod in pods:
                    pool.add_task(Message("t", pod, MODEL, _stored_payload([round_])))
                    if round_ % 2 == 0:
                        pool.add_task(Message("t", pod, MODEL, _removed_payload([round_])))
            assert pool.drain(timeout=10)
            # odd rounds stored and never removed; even rounds removed last
            for round_ in range(50):
                got = index.lookup([Key(MODEL, round_)], set())
                pods_found = set(got.get(Key(MODEL, round_), []))
                if round_ % 2 == 0:
                    assert pods_found == set(), f"round {round_}: {pods_found}"
                else:
                    assert pods_found == set(pods), f"round {round_}: {pods_found}"
        finally:
            pool.shutdown()


class TestZMQEndToEnd:
    """The offline-demo acceptance flow (reference §3.5): score empty →
    publish BlockStored → score hits → publish BlockRemoved → score reduced."""

    def test_offline_demo_flow(self):
        from llm_d_kv_cache_manager_tpu.kvcache import KVCacheIndexer, KVCacheIndexerConfig
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
        from conftest import CharTokenizer as CharTok, free_tcp_port

        port = free_tcp_port()
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=4)),
            tokenizer=CharTok(),
        )
        indexer.run()
        pool = KVEventsPool(indexer.kv_block_index, KVEventsPoolConfig(concurrency=2))
        pool.start()
        sub = ZMQSubscriber(pool, ZMQSubscriberConfig(endpoint=f"tcp://*:{port}"))
        sub.start()

        prompt = "abcdefghijklmnop"  # 4 blocks of 4
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            [ord(c) for c in prompt], MODEL
        )
        hashes = [k.chunk_hash for k in keys]

        try:
            pub = ZMQPublisher(
                ZMQPublisherConfig(
                    endpoint=f"tcp://localhost:{port}",
                    pod_identifier="tpu-pod-1",
                    model_name=MODEL,
                )
            )
            # PUB/SUB needs the subscription to propagate; retry-publish until
            # the subscriber sees it (slow-joiner handling).
            assert indexer.get_pod_scores(prompt, MODEL) == {}

            deadline = time.time() + 20
            scores = {}
            while time.time() < deadline and not scores:
                pub.publish([BlockStored(block_hashes=hashes, token_ids=[], block_size=4)])
                time.sleep(0.2)
                scores = indexer.get_pod_scores(prompt, MODEL)
            assert scores == {"tpu-pod-1": 4}

            # Remove the last two blocks → score drops to 2.
            pub.publish([BlockRemoved(block_hashes=hashes[2:])])
            deadline = time.time() + 10
            while time.time() < deadline:
                scores = indexer.get_pod_scores(prompt, MODEL)
                if scores == {"tpu-pod-1": 2}:
                    break
                time.sleep(0.1)
            assert scores == {"tpu-pod-1": 2}
            pub.close()
        finally:
            sub.shutdown()
            pool.shutdown()
            indexer.shutdown()


class TestPublisherHardening:
    """ISSUE 2 satellite: idempotent close and bounded send retry/backoff —
    a transient socket error must never raise into the engine loop."""

    @staticmethod
    def _pub():
        from conftest import free_tcp_port

        return ZMQPublisher(
            ZMQPublisherConfig(endpoint=f"tcp://localhost:{free_tcp_port()}")
        )

    def test_double_close_is_idempotent(self):
        pub = self._pub()
        pub.close()
        pub.close()  # second close must not hit the closed socket

    def test_publish_after_close_drops_without_raising(self):
        pub = self._pub()
        pub.close()
        assert pub.publish([BlockStored(block_hashes=[1], block_size=4)]) == -1
        assert pub.dropped_batches == 1

    def test_send_failure_retries_then_succeeds(self, monkeypatch):
        import zmq

        pub = self._pub()
        calls = []

        def flaky(frames):
            calls.append(frames)
            if len(calls) < 3:
                raise zmq.ZMQError()

        monkeypatch.setattr(pub._sock, "send_multipart", flaky)
        monkeypatch.setattr(time, "sleep", lambda s: None)
        seq = pub.publish([BlockStored(block_hashes=[1], block_size=4)])
        assert seq == 0 and len(calls) == 3
        assert pub.dropped_batches == 0
        pub.close()

    def test_send_failure_bounded_then_drops(self, monkeypatch):
        import zmq

        pub = self._pub()
        calls = []

        def dead(frames):
            calls.append(frames)
            raise zmq.ZMQError()

        monkeypatch.setattr(pub._sock, "send_multipart", dead)
        monkeypatch.setattr(time, "sleep", lambda s: None)
        # Never raises into the caller; attempts are bounded; the batch is
        # dropped and counted. The next publish still works (and keeps its
        # own seq, so subscribers see the gap).
        assert pub.publish([BlockStored(block_hashes=[1], block_size=4)]) == -1
        assert len(calls) == 3 and pub.dropped_batches == 1
        monkeypatch.setattr(pub._sock, "send_multipart", lambda frames: None)
        assert pub.publish([BlockStored(block_hashes=[2], block_size=4)]) == 1
        pub.close()


class TestZMQReconnect:
    """Failure-detection parity (SURVEY §5): the subscriber reconnects with
    backoff after socket errors — here the endpoint is initially occupied by
    another socket (bind fails repeatedly) and the subscriber must recover
    and deliver events once the port frees up."""

    def test_recovers_after_bind_conflict(self, monkeypatch):
        import zmq

        from llm_d_kv_cache_manager_tpu.kvcache.kvevents import zmq_subscriber

        monkeypatch.setattr(zmq_subscriber, "_RECONNECT_BACKOFF_S", 0.1)

        from conftest import free_tcp_port

        port = free_tcp_port()
        ctx = zmq.Context.instance()
        squatter = ctx.socket(zmq.PUB)
        squatter.bind(f"tcp://*:{port}")

        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
            InMemoryIndex,
            InMemoryIndexConfig,
            Key,
        )

        index = InMemoryIndex(InMemoryIndexConfig(size=100, pod_cache_size=4))
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        sub = ZMQSubscriber(pool, ZMQSubscriberConfig(endpoint=f"tcp://*:{port}"))
        sub.start()
        try:
            time.sleep(0.5)  # a few failed bind/backoff cycles
            squatter.close(linger=0)

            pub = ZMQPublisher(
                ZMQPublisherConfig(
                    endpoint=f"tcp://localhost:{port}",
                    pod_identifier="pod-r",
                    model_name=MODEL,
                )
            )
            deadline = time.time() + 20
            found = {}
            while time.time() < deadline and not found:
                pub.publish(
                    [BlockStored(block_hashes=[7], token_ids=[], block_size=4)]
                )
                time.sleep(0.2)
                found = index.lookup([Key(MODEL, 7)], set())
            pub.close()
            assert found.get(Key(MODEL, 7)) == ["pod-r"]
        finally:
            sub.shutdown()
            pool.shutdown()


class TestDecodeFuzz:
    """Decoder robustness: arbitrary bytes and structurally-mutated msgpack
    must never raise — the reference drops poison pills, never crashes
    (pool.go:175-180), and the subscriber feeds the pool raw network input."""

    def test_random_bytes_never_raise(self):
        import random

        rng = random.Random(0)
        for _ in range(500):
            blob = bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 64)))
            decode_event_batch(blob)  # None or EventBatch; never an exception

    def test_mutated_valid_payloads_never_raise(self):
        import random

        rng = random.Random(1)
        base = EventBatch(
            ts=1.0,
            events=[
                BlockStored(block_hashes=[1, 2], token_ids=[3, 4], block_size=4),
                BlockRemoved(block_hashes=[2]),
            ],
        ).to_payload()
        for _ in range(500):
            blob = bytearray(base)
            for _ in range(rng.randrange(1, 4)):
                blob[rng.randrange(len(blob))] = rng.getrandbits(8)
            decode_event_batch(bytes(blob))

    def test_structural_garbage_never_raises(self):
        cases = [
            [1.0, [["BlockStored"]]],                      # missing all fields
            [1.0, [["BlockStored", "not-a-list", 1, 2, 3]]],
            [1.0, [["BlockStored", [None], None, None, "x", None, 5]]],
            [1.0, [["BlockRemoved", {"a": 1}]]],
            [1.0, [[123, [1]]]],                           # non-string tag
            [1.0, [None, 5, "str"]],                       # non-event entries
            ["ts", []],
            [1.0, "not-a-list"],
            [1.0, [["BlockStored", [1], None, [1], 4, None, 42]]],  # int medium
        ]
        for case in cases:
            decode_event_batch(msgpack.packb(case))

    def test_snapshot_and_heartbeat_through_pool(self):
        """Self-healing events flow through the worker pool: a snapshot
        replaces the pod's view; a heartbeat is a harmless no-op without an
        attached FleetHealth (legacy pools stay bit-identical)."""
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([1, 2])))
            snap = EventBatch(
                ts=0.0,
                events=[
                    Heartbeat(),
                    IndexSnapshot(blocks_by_medium={"tpu_hbm": [2, 3]}),
                ],
            ).to_payload()
            pool.add_task(Message("t", "pod-1", MODEL, snap))
            assert pool.drain()
            got = index.lookup([Key(MODEL, h) for h in (1, 2, 3)], set())
            assert got.get(Key(MODEL, 1), []) == []  # replaced away
            assert got[Key(MODEL, 2)] == ["pod-1"]
            assert got[Key(MODEL, 3)] == ["pod-1"]
        finally:
            pool.shutdown()

    def test_fuzz_through_pool_worker(self):
        """Same robustness at the pool level: garbage tasks never kill the
        worker; a valid task after 200 fuzzed ones still lands."""
        import random

        rng = random.Random(2)
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        try:
            for i in range(200):
                blob = bytes(rng.getrandbits(8) for _ in range(rng.randrange(1, 48)))
                pool.add_task(Message("t", f"pod-{i%3}", MODEL, blob))
            pool.add_task(Message("t", "pod-ok", MODEL, _stored_payload([99])))
            assert pool.drain(timeout=30)
            got = index.lookup([Key(MODEL, 99)], set())
            assert got[Key(MODEL, 99)] == ["pod-ok"]
        finally:
            pool.shutdown()


class TestSubscriberFrameHardening:
    """ISSUE 3 satellite: malformed messages — wrong frame count, short seq
    frame, undecodable topic — are counted and dropped; none may kill the
    receive loop."""

    @staticmethod
    def _sub():
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        return ZMQSubscriber(pool, ZMQSubscriberConfig()), pool, index

    def test_wrong_frame_count_dropped(self):
        sub, _, _ = self._sub()
        assert sub._parse_frames([b"kv@p@m"]) is None
        assert sub._parse_frames([b"a", b"b", b"c", b"d"]) is None
        assert sub.malformed_dropped["frames"] == 2

    def test_short_seq_frame_dropped(self):
        sub, _, _ = self._sub()
        # Pre-hardening this decoded with seq=0, silently poisoning gap
        # detection; now it is counted and dropped.
        assert sub._parse_frames([b"kv@p@m", b"\x00\x01", b"{}"]) is None
        assert sub._parse_frames([b"kv@p@m", b"\x00" * 9, b"{}"]) is None
        assert sub.malformed_dropped["seq"] == 2

    def test_undecodable_topic_dropped(self):
        sub, _, _ = self._sub()
        assert sub._parse_frames([b"\xff\xfe\xfd", b"\x00" * 8, b"{}"]) is None
        assert sub.malformed_dropped["topic"] == 1

    def test_unparseable_topic_dropped(self):
        sub, _, _ = self._sub()
        assert sub._parse_frames([b"not-kv-topic", b"\x00" * 8, b"{}"]) is None
        assert sub.malformed_dropped["topic"] == 1

    def test_valid_frames_still_parse(self):
        sub, _, _ = self._sub()
        msg = sub._parse_frames(
            [b"kv@pod-1@" + MODEL.encode(), struct.pack(">Q", 42), b"payload"]
        )
        assert msg is not None
        assert (msg.pod_identifier, msg.model_name, msg.seq) == ("pod-1", MODEL, 42)
        assert sum(sub.malformed_dropped.values()) == 0

    def test_receive_loop_survives_garbage_frames(self):
        """Over a real socket: malformed multipart messages precede a valid
        one; the loop must survive and deliver the valid event."""
        import zmq

        from conftest import free_tcp_port

        port = free_tcp_port()
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        sub = ZMQSubscriber(pool, ZMQSubscriberConfig(endpoint=f"tcp://*:{port}"))
        sub.start()
        try:
            ctx = zmq.Context.instance()
            raw = ctx.socket(zmq.PUB)
            raw.connect(f"tcp://localhost:{port}")
            topic = f"kv@pod-g@{MODEL}".encode()
            deadline = time.time() + 20
            found = {}
            while time.time() < deadline and not found:
                raw.send_multipart([topic, b"\x00" * 8])              # 2 frames
                raw.send_multipart([topic, b"\x01", b"x"])            # short seq
                raw.send_multipart([b"\xff\xfe", b"\x00" * 8, b"x"])  # bad utf-8... 
                # (note: SUB topic filter drops the bad-topic one early)
                raw.send_multipart(
                    [topic, struct.pack(">Q", 1), _stored_payload([5])]
                )
                time.sleep(0.2)
                found = index.lookup([Key(MODEL, 5)], set())
            raw.close(linger=0)
            assert found.get(Key(MODEL, 5)) == ["pod-g"]
            assert sub.malformed_dropped["frames"] >= 1
            assert sub.malformed_dropped["seq"] >= 1
        finally:
            sub.shutdown()
            pool.shutdown()


class TestPoolShutdownHardening:
    """ISSUE 3 satellite: shutdown idempotence and drain ordering."""

    def test_double_shutdown_is_idempotent(self):
        pool = KVEventsPool(InMemoryIndex(), KVEventsPoolConfig(concurrency=2))
        pool.start()
        pool.shutdown()
        pool.shutdown()  # second call must be a no-op

    def test_shutdown_before_start_is_noop(self):
        pool = KVEventsPool(InMemoryIndex(), KVEventsPoolConfig(concurrency=2))
        pool.shutdown()
        pool.start()  # still startable afterwards
        pool.shutdown()

    def test_shutdown_applies_queued_events_before_join(self):
        """Events accepted before shutdown land in the index: the poison
        pill queues BEHIND them, so shutdown drains rather than discards."""
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=2))
        pool.start()
        for i in range(200):
            pool.add_task(Message("t", f"pod-{i % 5}", MODEL, _stored_payload([i])))
        pool.shutdown()
        got = index.lookup([Key(MODEL, i) for i in range(200)], set())
        assert len(got) == 200

    def test_add_task_after_shutdown_rejected_not_parked(self):
        pool = KVEventsPool(InMemoryIndex(), KVEventsPoolConfig(concurrency=1))
        pool.start()
        pool.shutdown()
        pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([1])))
        assert pool.rejected_after_shutdown == 1
        assert pool.drain(timeout=0.5)  # nothing left dangling

    def test_restart_after_shutdown_processes_again(self):
        index = InMemoryIndex()
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1))
        pool.start()
        pool.shutdown()
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _stored_payload([9])))
            assert pool.drain()
            assert index.lookup([Key(MODEL, 9)], set())[Key(MODEL, 9)] == ["pod-1"]
        finally:
            pool.shutdown()
