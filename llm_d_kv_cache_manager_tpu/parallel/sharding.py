"""Sharding rules: Megatron-style TP layout expressed as PartitionSpecs.

GSPMD does the collective insertion; these specs only say where tensors
live. Layout per transformer block (scaling-book recipe):

- ``wq/wk/wv``           column-parallel  → shard output dim on ``tp``
- ``wo``                 row-parallel     → shard input dim on ``tp``
  (XLA emits the reduce-scatter/all-reduce after the contraction)
- ``w_gate/w_up``        column-parallel
- ``w_down``             row-parallel
- norms/biases           replicated (biases of column-parallel layers are
  sharded with their outputs)
- ``embed``/``lm_head``  shard the vocab/output dim
- KV pages               shard ``n_kv_heads`` on ``tp`` (head-parallel
  cache; requires n_kv_heads % tp == 0)

Batch dims shard on ``dp``.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, Params


def _layer_specs(cfg: LlamaConfig, tp: int = 1) -> dict[str, P]:
    specs = {
        "attn_norm": P(),
        "wq": P(None, "tp"),
        "wk": P(None, "tp"),
        "wv": P(None, "tp"),
        "wo": P("tp", None),
        "mlp_norm": P(),
        "w_gate": P(None, "tp"),
        "w_up": P(None, "tp"),
        "w_down": P("tp", None),
    }
    if cfg.n_experts:
        # MoE FFN: expert-parallel when the expert count divides the tp
        # axis (each device holds E/tp whole experts; the combine's
        # contraction over E becomes a psum over ICI), else fall back to
        # Megatron-style sharding of the expert-intermediate dim.
        specs["router"] = P()
        if cfg.n_experts % tp == 0:
            specs["w_gate"] = P("tp", None, None)
            specs["w_up"] = P("tp", None, None)
            specs["w_down"] = P("tp", None, None)
        else:
            specs["w_gate"] = P(None, None, "tp")
            specs["w_up"] = P(None, None, "tp")
            specs["w_down"] = P(None, "tp", None)
    if cfg.qkv_bias:
        specs["bq"] = P("tp")
        specs["bk"] = P("tp")
        specs["bv"] = P("tp")
    if cfg.qk_norm:
        # Per-head-dim scale, identical across heads → replicated.
        specs["q_norm"] = P()
        specs["k_norm"] = P()
    return specs


def param_specs(cfg: LlamaConfig, tp: int = 1) -> dict[str, Any]:
    """PartitionSpec pytree matching ``init_params``' structure."""
    specs: dict[str, Any] = {
        "embed": P("tp", None),  # vocab-sharded; gather rides ICI
        "final_norm": P(),
        "layers": [_layer_specs(cfg, tp) for _ in range(cfg.n_layers)],
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def param_shardings(mesh: Mesh, cfg: LlamaConfig, params: Params | None = None):
    """NamedSharding pytree for ``params``.

    When ``params`` is given and contains int8-quantized weights
    (``models/quant.QuantizedTensor``), each one gets a matching pair of
    shardings: the int8 payload follows the weight's spec; its scale
    (shape ``[..., 1, out]``) follows the same spec with the contraction
    axis (size 1 — unpartitionable) replicated.
    """
    specs = param_specs(cfg, tp=mesh.shape.get("tp", 1))
    if params is None:
        return jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )
    from ..models.quant import QuantizedTensor

    def to_sharding(spec: P, p):
        if isinstance(p, QuantizedTensor):
            entries = list(spec) + [None] * (p.ndim - len(spec))
            scale_entries = list(entries)
            scale_entries[-2] = None
            return QuantizedTensor(
                q=NamedSharding(mesh, P(*entries)),
                scale=NamedSharding(mesh, P(*scale_entries)),
            )
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        to_sharding,
        specs,
        params,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, mesh: Mesh, cfg: LlamaConfig) -> Params:
    """Place a (host or single-device) param pytree onto the mesh."""
    return jax.device_put(params, param_shardings(mesh, cfg, params))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Token batches: shard the leading batch dim on dp, replicate across tp."""
    return NamedSharding(mesh, P("dp"))


def kv_pages_sharding(mesh: Mesh) -> NamedSharding:
    """KV pools [n_layers, pages, page_size, n_kv_heads, hd]: head-parallel."""
    return NamedSharding(mesh, P(None, None, None, "tp"))
