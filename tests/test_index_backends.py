"""Backend-agnostic Index conformance suite.

Port of the reference's pattern (``pkg/kvcache/kvblock/index_test.go:35-63``):
one behavioral suite instantiated for every backend — in-memory, cost-aware,
redis (fake), and the instrumented wrapper — plus per-backend eviction-bound
tests and a concurrency hammer.
"""

import threading

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    CostAwareMemoryIndex,
    CostAwareMemoryIndexConfig,
    DeviceTier,
    InMemoryIndex,
    InMemoryIndexConfig,
    InstrumentedIndex,
    Key,
    PodEntry,
    RedisIndexConfig,
    create_index,
    IndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import RedisIndex

from fake_redis import FakeRedis


def _k(i: int, model="m") -> Key:
    return Key(model, i)


def _e(pod: str, tier: DeviceTier = DeviceTier.TPU_HBM) -> PodEntry:
    return PodEntry(pod, tier)


BACKENDS = {
    "in_memory": lambda: InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=10)),
    "cost_aware": lambda: CostAwareMemoryIndex(CostAwareMemoryIndexConfig(max_cost_bytes=10**6)),
    "redis": lambda: RedisIndex(RedisIndexConfig(client=FakeRedis())),
    "instrumented": lambda: InstrumentedIndex(
        InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=10))
    ),
}

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (  # noqa: E402
    NativeMemoryIndex,
    NativeMemoryIndexConfig,
    native_available,
)

if native_available():
    BACKENDS["native"] = lambda: NativeMemoryIndex(
        NativeMemoryIndexConfig(size=1000, pod_cache_size=10)
    )


@pytest.fixture(params=list(BACKENDS))
def index(request):
    return BACKENDS[request.param]()


class TestIndexConformance:
    def test_basic_add_and_lookup(self, index):
        keys = [_k(1), _k(2), _k(3)]
        index.add(keys, [_e("podA")])
        got = index.lookup(keys, set())
        assert set(got) == set(keys)
        for key in keys:
            assert got[key] == ["podA"]

    def test_duplicate_pod_handling(self, index):
        index.add([_k(1)], [_e("podA")])
        index.add([_k(1)], [_e("podA")])
        got = index.lookup([_k(1)], set())
        assert got[_k(1)] == ["podA"]

    def test_filtered_lookup(self, index):
        index.add([_k(1)], [_e("podA"), _e("podB"), _e("podC")])
        got = index.lookup([_k(1)], {"podB"})
        assert got[_k(1)] == ["podB"]

    def test_filter_no_match(self, index):
        index.add([_k(1)], [_e("podA")])
        got = index.lookup([_k(1)], {"podZ"})
        # no surviving pods for the key → chain considered broken
        assert got.get(_k(1), []) == []

    def test_evict_basic(self, index):
        index.add([_k(1)], [_e("podA"), _e("podB")])
        index.evict(_k(1), [_e("podA")])
        got = index.lookup([_k(1)], set())
        assert got.get(_k(1), []) == ["podB"]
        index.evict(_k(1), [_e("podB")])
        got = index.lookup([_k(1)], set())
        assert got.get(_k(1), []) == []

    def test_evict_missing_key_is_noop(self, index):
        index.evict(_k(99), [_e("podA")])

    def test_multiple_tiers_same_pod(self, index):
        index.add([_k(1)], [_e("podA", DeviceTier.TPU_HBM), _e("podA", DeviceTier.HOST_DRAM)])
        got = index.lookup([_k(1)], set())
        # pod appears once per tier entry; dedup is the scorer's concern
        assert set(got[_k(1)]) == {"podA"}
        # evicting only the HBM tier keeps the DRAM entry
        index.evict(_k(1), [_e("podA", DeviceTier.TPU_HBM)])
        got = index.lookup([_k(1)], set())
        assert got.get(_k(1), []) == ["podA"]

    def test_evict_pod_removes_every_entry(self, index):
        """Dead-pod sweep parity (ISSUE 3): all keys, all tiers, all models
        — and keys whose pod set empties disappear entirely."""
        index.add([_k(1), _k(2)], [_e("podA"), _e("podB")])
        index.add([_k(3)], [_e("podA", DeviceTier.HOST_DRAM)])
        index.add([_k(4, "other-model")], [_e("podA")])
        removed = index.evict_pod("podA")
        assert removed == 4
        got = index.lookup([_k(1), _k(2)], set())
        assert got.get(_k(1), []) == ["podB"]
        assert got.get(_k(2), []) == ["podB"]
        # podA-only keys are gone in both models
        assert index.lookup([_k(3)], set()).get(_k(3), []) == []
        assert index.lookup([_k(4, "other-model")], set()).get(
            _k(4, "other-model"), []
        ) == []

    def test_evict_pod_multi_tier_same_key(self, index):
        index.add(
            [_k(1)],
            [_e("podA", DeviceTier.TPU_HBM), _e("podA", DeviceTier.HOST_DRAM)],
        )
        assert index.evict_pod("podA") == 2
        assert index.lookup([_k(1)], set()).get(_k(1), []) == []

    def test_evict_pod_remote_tier_keyed_to_holder(self, index):
        """Remote-tier death semantics (ISSUE 13): demoted entries are
        keyed to the HOLDER pod (the kvstore/peer storing the bytes), so
        the DEMOTER dying keeps them and the holder dying drops them —
        across every backend (and ShardedIndex, which reruns this suite).
        """
        index.add([_k(1)], [_e("demoter", DeviceTier.TPU_HBM)])
        index.add(
            [_k(1), _k(2)], [_e("kv-holder", DeviceTier.REMOTE)]
        )
        # The demoter's death never touches the holder's remote entries.
        assert index.evict_pod("demoter") == 1
        got = index.lookup([_k(1), _k(2)], set())
        assert got[_k(1)] == ["kv-holder"]
        assert got[_k(2)] == ["kv-holder"]
        # The holder's death drops exactly the entries whose bytes died.
        assert index.evict_pod("kv-holder") == 2
        got = index.lookup([_k(1), _k(2)], set())
        assert got.get(_k(1), []) == [] and got.get(_k(2), []) == []

    def test_evict_remote_tier_entry_by_medium(self, index):
        """A holder's BlockRemoved(remote) (store LRU drop) evicts the
        REMOTE-tier entry without touching its other tiers."""
        index.add(
            [_k(1)],
            [_e("pod", DeviceTier.TPU_HBM), _e("pod", DeviceTier.REMOTE)],
        )
        index.evict(_k(1), [_e("pod", DeviceTier.REMOTE)])
        assert index.lookup([_k(1)], set())[_k(1)] == ["pod"]
        index.evict(_k(1), [_e("pod", DeviceTier.TPU_HBM)])
        assert index.lookup([_k(1)], set()).get(_k(1), []) == []

    def test_evict_pod_unknown_is_noop(self, index):
        index.add([_k(1)], [_e("podA")])
        assert index.evict_pod("never-seen") == 0
        assert index.lookup([_k(1)], set())[_k(1)] == ["podA"]

    def test_evict_pod_then_readd_revives(self, index):
        index.add([_k(1)], [_e("podA")])
        index.evict_pod("podA")
        index.add([_k(1)], [_e("podA")])
        assert index.lookup([_k(1)], set())[_k(1)] == ["podA"]

    def test_concurrent_operations(self, index):
        errors = []
        n_threads, n_ops = 20, 25

        def worker(tid: int):
            try:
                for i in range(n_ops):
                    key = _k(i % 7)
                    pod = f"pod{tid % 3}"
                    op = (tid + i) % 4
                    if op == 0:
                        index.add([key], [_e(pod)])
                    elif op == 1:
                        index.lookup([key], set())
                    elif op == 2:
                        index.evict(key, [_e(pod)])
                    else:  # pod sweeps race normal traffic
                        index.evict_pod(pod)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors


@pytest.mark.skipif(not native_available(), reason="liblruindex.so not built")
class TestNativeSpecifics:
    def test_lru_key_eviction_bound(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=2, pod_cache_size=10))
        idx.add([_k(1), _k(2), _k(3)], [_e("podA")])
        got = idx.lookup([_k(1), _k(2), _k(3)], set())
        assert _k(1) not in got
        assert got[_k(2)] == ["podA"] and got[_k(3)] == ["podA"]
        assert len(idx) == 2

    def test_pod_lru_bound(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=10, pod_cache_size=2))
        idx.add([_k(1)], [_e("podA")])
        idx.add([_k(1)], [_e("podB")])
        idx.add([_k(1)], [_e("podC")])  # podA (least recent) evicted
        got = idx.lookup([_k(1)], set())
        assert set(got[_k(1)]) == {"podB", "podC"}

    def test_lookup_promotes_key_recency(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=2, pod_cache_size=4))
        idx.add([_k(1), _k(2)], [_e("podA")])
        idx.lookup([_k(1)], set())  # key 1 now most recent
        idx.add([_k(3)], [_e("podA")])  # evicts key 2, not key 1
        got = idx.lookup([_k(1), _k(2), _k(3)], set())
        assert _k(1) in got and _k(3) in got and _k(2) not in got

    def test_early_stop_on_emptied_key(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=10, pod_cache_size=4))
        idx.add([_k(1), _k(2), _k(3)], [_e("podA")])
        idx.add([_k(2)], [_e("podB")])
        idx.evict(_k(2), [_e("podA")])
        idx.evict(_k(2), [_e("podB")])  # key 2 now gone (empty → removed)
        got = idx.lookup([_k(1), _k(2), _k(3)], set())
        # missing key does NOT break the chain (in_memory.py semantics)
        assert _k(1) in got and _k(3) in got

    def test_mixed_model_batches(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=10, pod_cache_size=4))
        idx.add([_k(1, "m1")], [_e("podA")])
        idx.add([_k(1, "m2")], [_e("podB")])
        got = idx.lookup([_k(1, "m1"), _k(1, "m2")], set())
        assert got[_k(1, "m1")] == ["podA"]
        assert got[_k(1, "m2")] == ["podB"]

    def test_unknown_model_lookup_empty(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=10, pod_cache_size=4))
        assert idx.lookup([_k(1, "never-seen")], set()) == {}

    def test_fused_score_matches_python_pipeline(self):
        """The C++ fused lookup+score must agree with lookup → scorer on
        randomized hit patterns (the property the fused read path rests on)."""
        import random

        from llm_d_kv_cache_manager_tpu.kvcache.scorer import LongestPrefixScorer

        rng = random.Random(0)
        scorer = LongestPrefixScorer()
        for trial in range(50):
            native = NativeMemoryIndex(NativeMemoryIndexConfig(size=100, pod_cache_size=8))
            mirror = InMemoryIndex(InMemoryIndexConfig(size=100, pod_cache_size=8))
            keys = [_k(i) for i in range(rng.randint(1, 12))]
            for pod in ("podA", "podB", "podC"):
                depth = rng.randint(0, len(keys))
                # occasionally leave holes in the chain
                chain = [
                    k for i, k in enumerate(keys[:depth]) if rng.random() > 0.15
                ]
                if not chain:
                    continue
                for idx in (native, mirror):
                    idx.add(chain, [_e(pod)])
            pod_filter = rng.choice([set(), {"podA"}, {"podA", "podB"}, {"podZ"}])
            fused = native.score_longest_prefix(keys, pod_filter)
            expected = scorer.score(keys, mirror.lookup(keys, pod_filter))
            assert fused == expected, (trial, fused, expected)

    def test_fused_score_multi_tier_dedup(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=10, pod_cache_size=8))
        idx.add([_k(1), _k(2)], [_e("podA", DeviceTier.TPU_HBM)])
        idx.add([_k(1)], [_e("podA", DeviceTier.HOST_DRAM)])
        assert idx.score_longest_prefix([_k(1), _k(2)], set()) == {"podA": 2}

    def test_fused_score_promotes_past_holes(self):
        """The fused walk must LRU-promote every present key even after the
        scoring streak dies at a hole — identical recency behavior to the
        two-step lookup path (regression for an early-break divergence)."""
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=2, pod_cache_size=4))
        idx.add([_k(1), _k(2)], [_e("podA")])  # recency: k2 > k1
        # Chain with a hole at the front, then k1: scoring yields nothing,
        # but k1 must still be promoted over k2.
        assert idx.score_longest_prefix([_k(99), _k(1)], set()) == {}
        idx.add([_k(3)], [_e("podA")])  # evicts the LRU key — must be k2
        got = idx.lookup([_k(1), _k(2), _k(3)], set())
        assert _k(1) in got and _k(3) in got and _k(2) not in got

    def test_fused_score_hits_match_two_step_semantics(self):
        """*_with_hits reports keys-with-surviving-pods (the plain lookup
        metric), including keys past a hole in the streak."""
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=16, pod_cache_size=4))
        keys = [_k(i) for i in range(10)]
        chain = keys[:2] + keys[3:]  # hole at key 2
        idx.add(chain, [_e("podA")])
        scores, hits = idx.score_hashes_with_hits(
            "m", [k.chunk_hash for k in keys], set()
        )
        assert scores == {"podA": 2}  # streak ends at the hole
        assert hits == 9  # but 9 of 10 keys held pods

    def test_unknown_filter_pod_still_promotes(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=2, pod_cache_size=4))
        idx.add([_k(1), _k(2)], [_e("podA")])
        # Filter on an unknown pod: empty result, but k1 is still promoted.
        assert idx.lookup([_k(1)], {"podZ"}) == {}
        idx.add([_k(3)], [_e("podA")])
        got = idx.lookup([_k(1), _k(2), _k(3)], set())
        assert _k(1) in got and _k(2) not in got

    def test_fused_score_mixed_models_falls_back(self):
        idx = NativeMemoryIndex(NativeMemoryIndexConfig(size=10, pod_cache_size=8))
        idx.add([_k(1, "m1")], [_e("podA")])
        assert idx.score_longest_prefix([_k(1, "m1"), _k(1, "m2")], set()) is None


class TestInMemorySpecifics:
    def test_lru_eviction_bound(self):
        idx = InMemoryIndex(InMemoryIndexConfig(size=2, pod_cache_size=10))
        idx.add([_k(1), _k(2), _k(3)], [_e("podA")])
        # size=2 → key 1 evicted
        got = idx.lookup([_k(1), _k(2), _k(3)], set())
        assert _k(1) not in got
        assert got[_k(2)] == ["podA"]
        assert got[_k(3)] == ["podA"]

    def test_pod_cache_bound(self):
        idx = InMemoryIndex(InMemoryIndexConfig(size=10, pod_cache_size=2))
        idx.add([_k(1)], [_e("podA"), _e("podB"), _e("podC")])
        got = idx.lookup([_k(1)], set())
        assert len(got[_k(1)]) == 2  # oldest pod evicted

    def test_missing_key_does_not_stop_scan(self):
        idx = InMemoryIndex()
        idx.add([_k(2)], [_e("podA")])
        got = idx.lookup([_k(1), _k(2)], set())
        # key 1 absent → skipped, scan continues (in_memory.go:132-134)
        assert got == {_k(2): ["podA"]}

    def test_lookup_empty_keys_raises(self):
        idx = InMemoryIndex()
        with pytest.raises(ValueError):
            idx.lookup([], set())

    def test_add_empty_raises(self):
        idx = InMemoryIndex()
        with pytest.raises(ValueError):
            idx.add([], [_e("podA")])
        with pytest.raises(ValueError):
            idx.add([_k(1)], [])


class TestCostAwareSpecifics:
    def test_cost_eviction(self):
        # Budget fits roughly one entry (key overhead ~104 + pod ~70).
        idx = CostAwareMemoryIndex(CostAwareMemoryIndexConfig(max_cost_bytes=250))
        idx.add([_k(1)], [_e("podA")])
        idx.add([_k(2)], [_e("podA")])
        got = idx.lookup([_k(1), _k(2)], set())
        assert _k(1) not in got  # LRU-evicted by cost pressure
        assert got[_k(2)] == ["podA"]

    def test_total_cost_tracks_evictions(self):
        idx = CostAwareMemoryIndex(CostAwareMemoryIndexConfig(max_cost_bytes=10**6))
        idx.add([_k(1), _k(2)], [_e("podA")])
        c2 = idx.total_cost
        idx.evict(_k(1), [_e("podA")])
        assert idx.total_cost < c2
        idx.evict(_k(2), [_e("podA")])
        assert idx.total_cost == 0


class TestRedisSpecifics:
    def test_missing_key_stops_scan(self):
        idx = RedisIndex(RedisIndexConfig(client=FakeRedis()))
        idx.add([_k(2)], [_e("podA")])
        # redis cannot distinguish missing from empty → chain breaks at key 1
        got = idx.lookup([_k(1), _k(2)], set())
        assert got == {}

    def test_empty_lookup_returns_empty(self):
        idx = RedisIndex(RedisIndexConfig(client=FakeRedis()))
        assert idx.lookup([], set()) == {}


class TestFactory:
    def test_default_is_in_memory(self):
        idx = create_index()
        assert isinstance(idx, InMemoryIndex)

    def test_priority_order(self):
        idx = create_index(
            IndexConfig(
                in_memory=InMemoryIndexConfig(),
                cost_aware=CostAwareMemoryIndexConfig(),
            )
        )
        assert isinstance(idx, InMemoryIndex)

    def test_cost_aware_selected(self):
        idx = create_index(IndexConfig(in_memory=None, cost_aware=CostAwareMemoryIndexConfig()))
        assert isinstance(idx, CostAwareMemoryIndex)

    def test_no_backend_raises(self):
        with pytest.raises(ValueError):
            create_index(IndexConfig(in_memory=None))

    def test_metrics_wrapper(self):
        idx = create_index(IndexConfig(enable_metrics=True))
        assert isinstance(idx, InstrumentedIndex)
        idx.add([_k(1)], [_e("podA")])
        got = idx.lookup([_k(1)], set())
        assert got[_k(1)] == ["podA"]
        from llm_d_kv_cache_manager_tpu.kvcache.metrics import collector

        snap = collector.snapshot()
        assert snap["admissions"] >= 1
        assert snap["lookup_requests"] >= 1
        assert snap["lookup_hits"] >= 1
