"""Event-ingestion worker pool: sharded, per-pod ordered.

Parity with reference ``pkg/kvcache/kvevents/pool.go``: incoming messages
are sharded by FNV-1a(pod id) onto per-worker FIFO queues so events for one
pod are always applied in order (``pool.go:125-137``); workers decode the
msgpack batch and apply Add/Evict to the block index. Poison pills are
dropped, not retried (``:174-180``).

TPU retarget: the pod entry tier comes from the event's ``medium`` field
({tpu_hbm, host_dram}) rather than the reference's hardcoded ``"gpu"``
(``pool.go:247``).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Optional

from ...utils import RateLimitedWarn, get_logger
from ..kvblock import DeviceTier, Index, Key, PodEntry, tier_for_medium
from .events import (
    AllBlocksCleared,
    BadBlock,
    BlockRemoved,
    BlockStored,
    Heartbeat,
    IndexSnapshot,
    PodDrained,
    PrefillComplete,
    RequestAudit,
    decode_event_batch,
)

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # avoids a runtime import cycle with health.py
    from .health import FleetHealth

log = get_logger("kvcache.kvevents.pool")
#: index-backend faults repeat at the event rate when a backend degrades;
#: warn with a suppressed-repeat count instead of one line per event.
_warn = RateLimitedWarn(log)

DEFAULT_CONCURRENCY = 4


def fnv1a_32(data: bytes) -> int:
    """FNV-1a 32-bit (matches Go ``hash/fnv.New32a``)."""
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


@dataclass
class Message:
    """One raw event message from the transport
    (reference ``zmq_subscriber.go`` Message)."""

    topic: str
    pod_identifier: str
    model_name: str
    payload: bytes
    seq: int = 0


@dataclass
class KVEventsPoolConfig:
    concurrency: int = DEFAULT_CONCURRENCY
    # Transport config is attached by the subscriber layer (zmq_subscriber).


class KVEventsPool:
    """Sharded ordered worker pool applying KV events to the index.

    ``health`` (optional, a ``FleetHealth``) receives per-message stream
    observations — last-seen seq per (pod, model) for gap detection,
    heartbeats, resync acknowledgements. ``staleness`` (optional, an
    ``obs.StalenessTracker``) records publish→apply lag per (pod, event
    type) plus received/applied seq high-waters; ``audit`` (optional, an
    ``obs.RouteAuditor``) receives ``RequestAudit`` realized-hit reports;
    ``lifecycle`` (optional, an ``obs.lifecycle.BlockLifecycleLedger``)
    receives the per-pod ``BlockStored``/``BlockRemoved`` tier story —
    the scorer-side half of the OBS_LIFECYCLE ledger, derived from the
    stream this pool already decodes (no new wire fields);
    ``on_bad_block`` (optional, ``fn(holder, block_hashes, medium)``)
    fires after a ``BadBlock`` revocation lands on the index — serving
    layers hook replica purges (remote-store copies of the revoked
    block) here. All ``None`` (default) keeps the legacy behavior
    bit-identical.
    """

    def __init__(
        self,
        index: Index,
        config: Optional[KVEventsPoolConfig] = None,
        health: Optional["FleetHealth"] = None,
        *,
        staleness=None,
        audit=None,
        lifecycle=None,
        on_bad_block=None,
    ):
        self.config = config or KVEventsPoolConfig()
        if self.config.concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        self.index = index
        self.health = health
        self.staleness = staleness
        self.audit = audit
        self.lifecycle = lifecycle
        self.on_bad_block = on_bad_block
        self._mu = threading.Lock()
        #: tasks rejected because the pool was already shut down — after the
        #: poison pill a task would sit unprocessed forever, which is worse
        #: than an honest drop (the index self-heals via resync anyway).
        self.rejected_after_shutdown = 0  # guarded_by: _mu
        #: immutable after construction; workers index it lock-free
        self._queues: list["queue.Queue[Optional[Message]]"] = [
            queue.Queue() for _ in range(self.config.concurrency)
        ]
        self._threads: list[threading.Thread] = []  # guarded_by: _mu
        self._running = False  # guarded_by: _mu
        self._started = False  # guarded_by: _mu

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        with self._mu:
            if self._running:
                return
            self._running = True
            self._started = True
            for i in range(self.config.concurrency):
                t = threading.Thread(
                    target=self._worker, args=(i,), name=f"kvevents-worker-{i}", daemon=True
                )
                t.start()
                self._threads.append(t)

    def shutdown(self) -> None:
        """Idempotent. Drain ordering: the poison pill is enqueued BEHIND
        any already-queued events, so every event accepted before shutdown
        is applied to the index before the workers join."""
        with self._mu:
            if not self._running:
                return
            self._running = False
            for q in self._queues:
                q.put(None)
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=5)

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until all queued *and in-flight* events have been applied."""
        import time

        # Deadline math on the monotonic clock: a wall-clock (time.time)
        # deadline steps under NTP slew and can wait forever or not at all.
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(q.unfinished_tasks == 0 for q in self._queues):
                return True
            time.sleep(0.002)
        return False

    # -- ingestion ----------------------------------------------------------
    def add_task(self, msg: Message) -> None:
        """Shard by pod id so per-pod ordering holds. Tasks offered after
        shutdown are rejected (counted), never silently parked behind the
        poison pill — the check and the enqueue share the pool lock, so a
        racing shutdown cannot slip its pill under an admitted task."""
        shard = fnv1a_32(msg.pod_identifier.encode("utf-8")) % self.config.concurrency
        with self._mu:
            if self._started and not self._running:
                self.rejected_after_shutdown += 1
            else:
                self._queues[shard].put(msg)
                if self.staleness is not None:
                    # Received high-water BEFORE the worker applies it: the
                    # delta to the applied high-water is the events-behind
                    # gauge (only admitted tasks count — a rejected task
                    # will never be applied, so counting it would pin the
                    # gauge above zero forever).
                    self.staleness.observe_received(msg.pod_identifier, msg.seq)
                return
        log.warning("event after pool shutdown; dropping", pod=msg.pod_identifier)

    def _worker(self, shard: int) -> None:
        q = self._queues[shard]
        while True:
            msg = q.get()
            if msg is None:
                q.task_done()
                return
            try:
                self._process_event(msg)
            except Exception:
                # Deliberately broad: ANY failure on one message must not
                # kill the worker thread — a dead shard silently stops
                # applying its pods' events forever. Rate-limited so a
                # poison storm stays one WARN per interval, not one per
                # message (reference pool.go:174-180).
                _warn.warning(
                    f"worker-{shard}",
                    "failed to process event message; dropping",
                    exc_info=True,
                    pod=msg.pod_identifier,
                )
            finally:
                q.task_done()

    def _process_event(self, msg: Message) -> None:
        batch = decode_event_batch(msg.payload)
        if batch is None:
            log.debug("failed to unmarshal event batch, dropping message", topic=msg.topic)
            return

        # Stream-integrity observation BEFORE applying: per-pod ordering is
        # guaranteed by sharding, so last-seen seq per (pod, model) is
        # exact; a skip marks the pod's view suspect until a resync.
        if self.health is not None:
            self.health.observe_message(msg.pod_identifier, msg.model_name, msg.seq)

        for ev in batch.events:
            if isinstance(ev, BlockStored):
                keys = [Key(msg.model_name, h) for h in ev.block_hashes]
                entries = [PodEntry(msg.pod_identifier, tier_for_medium(ev.medium))]
                try:
                    self.index.add(keys, entries)
                except Exception:
                    # Backend-specific fault zoo (redis I/O, native index,
                    # lru) — broad by necessity, loud by rate-limited WARN.
                    _warn.warning(
                        "index-add",
                        "failed to add event to index",
                        exc_info=True,
                        pod=msg.pod_identifier,
                    )
                if self.lifecycle is not None:
                    self.lifecycle.observe_stored(
                        msg.pod_identifier, ev.block_hashes, ev.medium
                    )
            elif isinstance(ev, BlockRemoved):
                if ev.medium is None:
                    # No medium (incl. legacy events) = the pod no longer
                    # holds the block at all: clear every tier, else an entry
                    # stored with an explicit medium would never match the
                    # eviction and stale locality would persist forever.
                    entries = [PodEntry(msg.pod_identifier, t) for t in DeviceTier]
                else:
                    entries = [PodEntry(msg.pod_identifier, tier_for_medium(ev.medium))]
                for h in ev.block_hashes:
                    try:
                        self.index.evict(Key(msg.model_name, h), entries)
                    except Exception:
                        _warn.warning(
                            "index-evict",
                            "failed to evict from index",
                            exc_info=True,
                            pod=msg.pod_identifier,
                        )
                if self.lifecycle is not None:
                    self.lifecycle.observe_removed(
                        msg.pod_identifier, ev.block_hashes, ev.medium
                    )
            elif isinstance(ev, BadBlock):
                # Fleet-wide revocation: a pod's digest check caught a
                # corrupt copy. The HOLDER (``ev.pod`` when the detector
                # published under its own identity on a peer's behalf,
                # else the publisher itself) loses its index entry NOW —
                # the scorer must stop routing toward poisoned warmth —
                # and replica purges fan out via ``on_bad_block``.
                holder = ev.pod or msg.pod_identifier
                if ev.medium is None:
                    entries = [PodEntry(holder, t) for t in DeviceTier]
                else:
                    entries = [PodEntry(holder, tier_for_medium(ev.medium))]
                for h in ev.block_hashes:
                    try:
                        self.index.evict(Key(msg.model_name, h), entries)
                    except Exception:
                        _warn.warning(
                            "bad-block-evict",
                            "failed to revoke bad block from index",
                            exc_info=True,
                            pod=holder,
                        )
                if self.audit is not None:
                    # Routes already in flight toward the revoked entry
                    # will miss: attribute those as ``quarantined``.
                    self.audit.observe_bad_block(ev.block_hashes)
                if self.health is not None:
                    self.health.observe_bad_block(
                        holder, len(ev.block_hashes)
                    )
                from ..metrics import collector

                collector.observe_bad_blocks(len(ev.block_hashes))
                if self.on_bad_block is not None:
                    try:
                        self.on_bad_block(holder, ev.block_hashes, ev.medium)
                    except Exception:
                        _warn.warning(
                            "bad-block-purge",
                            "bad-block purge callback failed",
                            exc_info=True,
                            pod=holder,
                        )
            elif isinstance(ev, Heartbeat):
                if self.health is not None:
                    self.health.observe_heartbeat(
                        msg.pod_identifier,
                        ev.dropped_batches,
                        ev.draining,
                        role=ev.role,
                        headroom=ev.headroom,
                    )
            elif isinstance(ev, PrefillComplete):
                # Observation-only: the chain's BlockStored events already
                # carry the locality truth; this just counts handoff supply
                # (and liveness, via observe_message above).
                if self.health is not None:
                    self.health.observe_prefill_complete(msg.pod_identifier)
            elif isinstance(ev, IndexSnapshot):
                self._apply_snapshot(msg, ev)
            elif isinstance(ev, PodDrained):
                # Graceful goodbye: evict the pod NOW — a drained pod's
                # cache is gone and a rolling restart must not serve stale
                # locality for a whole POD_TTL_S. Eviction is unconditional
                # (no health needed): the pod itself declared the state.
                try:
                    self.index.evict_pod(msg.pod_identifier)
                except Exception:
                    _warn.warning(
                        "evict-pod",
                        "drained-pod eviction failed",
                        exc_info=True,
                        pod=msg.pod_identifier,
                    )
                if self.health is not None:
                    self.health.observe_drained(msg.pod_identifier)
                if self.lifecycle is not None:
                    # The ledger must not keep a drained pod's blocks
                    # "resident" forever — end every tracked residency.
                    self.lifecycle.observe_pod_gone(
                        msg.pod_identifier, "drained"
                    )
                log.info(
                    "pod drained; evicted from index", pod=msg.pod_identifier
                )
            elif isinstance(ev, RequestAudit):
                # Observation-only: the pod's realized prefix-cache hit
                # count joins the scorer's prediction in the route auditor
                # (predicted-vs-realized ratio + miss attribution).
                if self.audit is not None:
                    self.audit.record_realized(
                        ev.request_id, msg.pod_identifier, ev.realized_blocks
                    )
            elif isinstance(ev, AllBlocksCleared):
                # No-op, as in the reference (pool.go:300-301): the event
                # carries no hash list, and the index ages entries out.
                continue

        if self.staleness is not None:
            # AFTER the apply loop: the lag measured is publish → index
            # VISIBILITY (what a routing decision at this instant would
            # see), not publish → dequeue.
            self.staleness.observe_batch(
                msg.pod_identifier,
                msg.seq,
                batch.ts,
                [type(ev).__name__ for ev in batch.events],
            )

    def _apply_snapshot(self, msg: Message, ev: IndexSnapshot) -> None:
        """Replace-all-for-pod reconciliation: the digest IS the pod's KV
        cache, so first drop every entry the index holds for the pod, then
        add exactly the digest. Runs on the pod's own shard worker, so it
        is ordered against the pod's normal event stream.

        Contract: a pod identifier serves ONE model (the in-tree PodServer
        invariant — one engine, one topic ``kv@<pod>@<model>``; the digest
        covers that engine's whole cache). ``evict_pod`` sweeps all models,
        so a pod identity shared by publishers of different models would
        have its other models' entries wiped here — give each engine its
        own pod identifier instead."""
        try:
            self.index.evict_pod(msg.pod_identifier)
        except Exception:
            _warn.warning(
                "resync-evict",
                "resync: evict_pod failed",
                exc_info=True,
                pod=msg.pod_identifier,
            )
            return
        if self.lifecycle is not None:
            # Replace-all means replace-all in the ledger too: end every
            # tracked residency, then re-open exactly the digest's.
            self.lifecycle.observe_pod_gone(msg.pod_identifier, "resync")
        for medium, hashes in ev.blocks_by_medium.items():
            if not hashes:
                continue
            keys = [Key(msg.model_name, h) for h in hashes]
            entries = [PodEntry(msg.pod_identifier, tier_for_medium(medium))]
            try:
                self.index.add(keys, entries)
            except Exception:
                _warn.warning(
                    "resync-add",
                    "resync: failed to apply snapshot tier",
                    exc_info=True,
                    pod=msg.pod_identifier,
                    medium=medium,
                )
            if self.lifecycle is not None:
                self.lifecycle.observe_stored(
                    msg.pod_identifier, hashes, medium
                )
        if self.health is not None:
            self.health.observe_resync(msg.pod_identifier)
        log.info(
            "applied index snapshot (replace-all-for-pod)",
            pod=msg.pod_identifier,
            blocks={m: len(h) for m, h in ev.blocks_by_medium.items()},
        )
