"""Pallas paged-attention kernel vs pure-jnp oracle (interpret mode on CPU;
the same kernel compiles for TPU via Mosaic)."""

import numpy as np
import pytest
import jax.numpy as jnp

from llm_d_kv_cache_manager_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
)


def _setup(seed, B, NH, NKV, D, PS, NPAGES, MAXP, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.array(rng.standard_normal((B, NH, D)), dtype)
    k = jnp.array(rng.standard_normal((NPAGES, PS, NKV, D)) * 0.3, dtype)
    v = jnp.array(rng.standard_normal((NPAGES, PS, NKV, D)), dtype)
    # unique pages per sequence (engine invariant: no aliasing between live seqs)
    ids = rng.permutation(NPAGES)[: B * MAXP].reshape(B, MAXP)
    bt = jnp.array(ids, jnp.int32)
    return q, k, v, bt


class TestPagedAttentionKernel:
    @pytest.mark.parametrize(
        "B,NH,NKV,D,PS,MAXP,lens",
        [
            (1, 1, 1, 128, 16, 2, [17]),
            (3, 8, 2, 128, 16, 4, [5, 64, 33]),
            (2, 4, 4, 64, 8, 3, [24, 1]),  # MHA (group=1)
            (4, 8, 1, 128, 16, 2, [32, 31, 16, 9]),  # MQA
        ],
    )
    def test_matches_reference(self, B, NH, NKV, D, PS, MAXP, lens):
        NPAGES = B * MAXP + 2
        q, k, v, bt = _setup(0, B, NH, NKV, D, PS, NPAGES, MAXP)
        sl = jnp.array(lens, jnp.int32)
        ref = paged_attention_reference(q, k, v, bt, sl)
        out = paged_attention(q, k, v, bt, sl, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_5d_pool_layer_index_matches_sliced_pool(self):
        """Passing the full multi-layer pool with `layer=li` must equal
        attention over the sliced per-layer pool — the 5-D operand is the
        form the decode body uses so XLA never materializes a per-layer
        pool copy around the custom call (results/decode_poolsize.md)."""
        rng = np.random.default_rng(7)
        L, B, NH, NKV, D, PS, MAXP = 3, 2, 4, 2, 64, 8, 3
        NPAGES = B * MAXP + 1
        q = jnp.array(rng.standard_normal((B, NH, D)), jnp.float32)
        k5 = jnp.array(rng.standard_normal((L, NPAGES, PS, NKV, D)), jnp.float32)
        v5 = jnp.array(rng.standard_normal((L, NPAGES, PS, NKV, D)), jnp.float32)
        bt = jnp.array(
            rng.permutation(NPAGES)[: B * MAXP].reshape(B, MAXP), jnp.int32
        )
        sl = jnp.array([13, 20], jnp.int32)
        for li in range(L):
            ref = paged_attention_reference(q, k5[li], v5[li], bt, sl)
            out = paged_attention(q, k5, v5, bt, sl, layer=li, interpret=True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
            )

    def test_zero_length_sequence_is_zero_not_nan(self):
        q, k, v, bt = _setup(1, B=2, NH=4, NKV=2, D=64, PS=8, NPAGES=6, MAXP=2)
        sl = jnp.array([0, 16], jnp.int32)
        out = paged_attention(q, k, v, bt, sl, interpret=True)
        assert not bool(jnp.any(jnp.isnan(out)))
        assert float(jnp.max(jnp.abs(out[0]))) == 0.0

    def test_bfloat16_inputs(self):
        q, k, v, bt = _setup(2, B=2, NH=8, NKV=2, D=128, PS=16, NPAGES=6, MAXP=2, dtype=jnp.bfloat16)
        sl = jnp.array([20, 32], jnp.int32)
        ref = paged_attention_reference(q, k, v, bt, sl)
        out = paged_attention(q, k, v, bt, sl, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
        )

    def test_partial_last_page_masked(self):
        # seq_len cuts mid-page; garbage in the tail slots must not leak.
        B, NH, NKV, D, PS, MAXP = 1, 2, 1, 64, 8, 2
        q, k, v, bt = _setup(3, B, NH, NKV, D, PS, B * MAXP + 2, MAXP)
        # Poison the slots beyond seq_len in the last used page.
        sl_val = 11  # page 1, slot 3
        last_page = int(bt[0, 1])
        k = k.at[:, last_page, 3:].set(1e4)
        v = v.at[:, last_page, 3:].set(1e4)
        sl = jnp.array([sl_val], jnp.int32)
        ref = paged_attention_reference(q, k, v, bt, sl)
        out = paged_attention(q, k, v, bt, sl, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
        assert float(jnp.max(jnp.abs(out))) < 100.0


class TestFreshKV:
    """Deferred-write contract: kernel with (history-only pages + fresh K/V
    args) must equal the kernel with the token already written to pages."""

    def test_fresh_kv_matches_written_pages(self):
        import numpy as np
        from llm_d_kv_cache_manager_tpu.ops.paged_attention import (
            paged_attention,
            paged_attention_reference,
        )

        rng = np.random.default_rng(11)
        b, nq, nkv, d, ps, pages, maxp = 3, 8, 4, 32, 4, 32, 6
        q = jnp.asarray(rng.standard_normal((b, nq, d)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((pages, ps, nkv, d)), jnp.float32)
        vp = jnp.asarray(rng.standard_normal((pages, ps, nkv, d)), jnp.float32)
        # Distinct pages per sequence so writes don't collide.
        bt = jnp.asarray(
            rng.permutation(pages - 1)[: b * maxp].reshape(b, maxp) + 1, jnp.int32
        )
        seq_lens = jnp.asarray([1, ps + 2, 2 * ps], jnp.int32)  # incl. current
        fk = jnp.asarray(rng.standard_normal((b, nkv, d)), jnp.float32)
        fv = jnp.asarray(rng.standard_normal((b, nkv, d)), jnp.float32)

        # Write the current token into its page slot, then run both paths.
        kp_w, vp_w = kp, vp
        for i in range(b):
            pos = int(seq_lens[i]) - 1
            page = int(bt[i, pos // ps])
            slot = pos % ps
            kp_w = kp_w.at[page, slot].set(fk[i])
            vp_w = vp_w.at[page, slot].set(fv[i])

        written = paged_attention(q, kp_w, vp_w, bt, seq_lens)
        fresh = paged_attention(q, kp, vp, bt, seq_lens, fk, fv)
        np.testing.assert_allclose(
            np.asarray(fresh), np.asarray(written), atol=2e-5
        )
        # And both agree with the oracle on the written pages.
        ref = paged_attention_reference(q, kp_w, vp_w, bt, seq_lens)
        np.testing.assert_allclose(np.asarray(fresh), np.asarray(ref), atol=2e-5)

    def test_fresh_kv_inactive_lane_zeros(self):
        import numpy as np
        from llm_d_kv_cache_manager_tpu.ops.paged_attention import paged_attention

        rng = np.random.default_rng(12)
        b, nq, nkv, d, ps, pages, maxp = 2, 4, 2, 32, 4, 8, 2
        q = jnp.asarray(rng.standard_normal((b, nq, d)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((pages, ps, nkv, d)), jnp.float32)
        vp = jnp.asarray(rng.standard_normal((pages, ps, nkv, d)), jnp.float32)
        bt = jnp.zeros((b, maxp), jnp.int32)
        seq_lens = jnp.asarray([3, 0], jnp.int32)  # lane 1 inactive
        fk = jnp.asarray(rng.standard_normal((b, nkv, d)), jnp.float32)
        fv = jnp.asarray(rng.standard_normal((b, nkv, d)), jnp.float32)
        out = paged_attention(q, kp, vp, bt, seq_lens, fk, fv)
        assert bool(jnp.all(out[1] == 0.0))
        assert bool(jnp.any(out[0] != 0.0))
