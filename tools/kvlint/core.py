"""kvlint core: file loading, suppression handling, checker registry, runner.

A checker is a module in ``tools/kvlint/checkers`` exposing

- ``RULE``: the rule name (kebab-case, what suppression comments name)
- ``check(unit, ctx) -> list[Finding]``: per-file pass
- optionally ``check_repo(ctx) -> list[Finding]``: one cross-file pass per
  run (e.g. the docs→code direction of metric-pin)

Suppressions: a trailing ``# kvlint: disable=rule`` (or comma-separated
list) drops that rule's findings on its line; the same comment on a line
of its own covers the NEXT line (the noqa-above-the-line habit must not
silently widen scope). File scope requires the explicit
``# kvlint: disable-file=rule`` form. Every suppression in tree code is
expected to carry a human justification alongside it.
"""

from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

REPO_ROOT = Path(__file__).resolve().parents[2]

_SUPPRESS_RE = re.compile(r"#\s*kvlint:\s*disable=([a-z0-9,\-\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*kvlint:\s*disable-file=([a-z0-9,\-\s]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class ModuleUnit:
    """One parsed source file plus its suppression map."""

    path: Path
    rel: str
    source: str
    lines: list[str]
    tree: ast.Module
    #: line number -> set of suppressed rules on that line
    line_suppress: dict[int, set[str]] = field(default_factory=dict)
    #: rules suppressed for the entire file
    file_suppress: set[str] = field(default_factory=set)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppress:
            return True
        return rule in self.line_suppress.get(line, set())

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


@dataclass
class RepoContext:
    """Run-wide state shared by checkers."""

    repo_root: Path
    units: list[ModuleUnit]
    #: read_repo_file cache: one disk read per repo file per run, not per
    #: linted module (the allowlist/manifest/docs are re-consulted by
    #: every file a checker visits)
    _file_cache: dict[str, Optional[str]] = field(default_factory=dict)
    #: scratch space for checkers to memoise parsed artifacts per run
    parsed_cache: dict[str, object] = field(default_factory=dict)

    def read_repo_file(self, rel: str) -> Optional[str]:
        if rel not in self._file_cache:
            try:
                self._file_cache[rel] = (self.repo_root / rel).read_text(
                    encoding="utf-8"
                )
            except OSError:
                self._file_cache[rel] = None
        return self._file_cache[rel]


def _parse_suppressions(lines: list[str]) -> tuple[dict[int, set[str]], set[str]]:
    per_line: dict[int, set[str]] = {}
    whole_file: set[str] = set()
    for i, text in enumerate(lines, start=1):
        mf = _SUPPRESS_FILE_RE.search(text)
        if mf:
            # File scope only via the explicit form — the module-wide
            # exemption must be unmistakable in review.
            whole_file |= {r.strip() for r in mf.group(1).split(",") if r.strip()}
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if text.lstrip().startswith("#"):
            # A standalone suppression comment covers the NEXT line (the
            # flake8 noqa-above-the-line habit) — never the whole file.
            per_line.setdefault(i + 1, set()).update(rules)
        else:
            per_line.setdefault(i, set()).update(rules)
    return per_line, whole_file


def load_unit(path: Path, repo_root: Path = REPO_ROOT) -> ModuleUnit:
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError) as exc:
        # Undecodable/unparsable files are reported, not skipped silently.
        raise RuntimeError(f"kvlint cannot parse {path}: {exc}") from exc
    lines = source.splitlines()
    per_line, whole_file = _parse_suppressions(lines)
    try:
        rel = str(path.resolve().relative_to(repo_root))
    except ValueError:
        rel = str(path)
    return ModuleUnit(
        path=path,
        rel=rel,
        source=source,
        lines=lines,
        tree=tree,
        line_suppress=per_line,
        file_suppress=whole_file,
    )


def iter_py_files(targets: Iterable[str]) -> list[Path]:
    out: list[Path] = []
    for t in targets:
        p = Path(t)
        if p.is_dir():
            found = sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
            if not found:
                # A directory with nothing to lint is almost certainly a
                # typo'd/renamed path — exiting 0 would turn the CI gate
                # into a silent no-op forever.
                print(f"kvlint: no .py files under {t!r}", file=sys.stderr)
                raise SystemExit(2)
            out.extend(found)
        elif p.is_file() and p.suffix == ".py":
            out.append(p)
        else:
            print(
                f"kvlint: {t!r} is not a .py file or a directory",
                file=sys.stderr,
            )
            raise SystemExit(2)
    return out


def all_rules() -> dict[str, object]:
    """Rule name -> checker module, in deterministic order."""
    from tools.kvlint.checkers import (
        kernel_abi,
        knob_default,
        lock_discipline,
        metric_pin,
        monotonic_time,
        wire_append_only,
    )

    mods = [
        knob_default,
        wire_append_only,
        kernel_abi,
        metric_pin,
        lock_discipline,
        monotonic_time,
    ]
    return {m.RULE: m for m in mods}


def lint_paths(
    targets: Iterable[str],
    rules: Optional[Iterable[str]] = None,
    repo_root: Path = REPO_ROOT,
) -> list[Finding]:
    checkers = all_rules()
    if rules is not None:
        unknown = set(rules) - set(checkers)
        if unknown:
            raise SystemExit(f"kvlint: unknown rule(s): {', '.join(sorted(unknown))}")
        checkers = {k: v for k, v in checkers.items() if k in set(rules)}

    units = [load_unit(p, repo_root) for p in iter_py_files(targets)]
    ctx = RepoContext(repo_root=repo_root, units=units)

    findings: list[Finding] = []
    for rule, mod in checkers.items():
        for unit in units:
            for f in mod.check(unit, ctx):
                if not unit.suppressed(rule, f.line):
                    findings.append(f)
        check_repo = getattr(mod, "check_repo", None)
        if check_repo is not None:
            findings.extend(check_repo(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
