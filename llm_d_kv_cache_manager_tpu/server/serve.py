"""TPU pod serving binary: the in-tree analogue of a vLLM pod.

The reference deploys external vLLM pods configured to publish KV events
(``vllm-setup-helm/templates/deployment.yaml:80-81``: ``--kv-events-config
publisher=zmq, topic kv@<pod>@<model>``, ``--prefix-caching-hash-algo
sha256_cbor_64bit``). In this framework the serving engine is in-tree, so
this module is that pod: a continuous-batching ``Engine`` (Pallas paged
attention, prefix-caching block manager) wrapped in

- a background engine loop thread,
- a ZMQ KV-event publisher wired to the block manager's alloc/evict
  transitions (``kv@<pod>@<model>`` topic, msgpack array-struct batches,
  big-endian seq — the exact contract the indexer's subscriber expects),
- an OpenAI-style HTTP surface: ``POST /v1/completions``, ``GET /healthz``,
  ``GET /stats``.

Config comes from env vars mirroring the reference's online service
(``examples/kv_events/online/main.go:162-209``): ``MODEL_NAME``,
``POD_IDENTIFIER``, ``ZMQ_ENDPOINT``, ``BLOCK_SIZE``, ``PYTHONHASHSEED``,
``HTTP_PORT``, plus engine sizing (``TOTAL_PAGES``, ``HOST_PAGES``, ``TP``,
``MAX_MODEL_LEN``, ``DP_RANK``), the KV capacity tiers (``KV_QUANT``,
``KV_QUANT_HBM``, ``HOST_PREFETCH``, ``HOST_TIER_POLICY``) and the
cross-pod KV transfer plane
(``TRANSFER_ENDPOINT`` binds this pod's page export service — unset = off;
``TRANSFER_MAX_BLOCKS``, ``TRANSFER_TIMEOUT_S``; ``ASYNC_PULL`` +
``PULL_WORKERS`` import pulled prefixes in the background instead of
blocking submission), the remote capacity tier (``REMOTE_TIER`` demotes
last-copy evictions to ``REMOTE_PEERS`` / accepts pushes into a
``REMOTE_STORE_PAGES``-sized store; ``POD_ROLE=kvstore`` is a dedicated
holder) and the decode fast path (``DECODE_FUSED_SAMPLING``).

Run: ``python -m llm_d_kv_cache_manager_tpu.server.serve``
"""

from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from dataclasses import dataclass, field, replace
from typing import Optional

from ..kvcache.kvevents import (
    Heartbeat,
    IndexSnapshot,
    PodDrained,
    PrefillComplete,
    RequestAudit,
    ZMQPublisher,
    ZMQPublisherConfig,
)
from ..kvcache.transfer import (
    KVTransferClient,
    KVTransferService,
    MigrationPayload,
    TransferClientConfig,
    TransferClientPool,
    TransferError,
    TransferServiceConfig,
)
from ..models import LlamaConfig
from ..obs import lifecycle as lifecycle_mod
from ..obs.tracing import Tracer, format_traceparent, parse_traceparent
from ..utils import get_logger, log_context
from .engine import Engine, EngineConfig
from .block_manager import BlockManagerConfig
from .sequence import SamplingParams, Sequence

log = get_logger("server.serve")


class AdmissionError(RuntimeError):
    """Request rejected by admission control (the pod is overloaded).
    Carries a ``retry_after_s`` hint derived from the measured serving
    rates — the HTTP surface turns it into ``429`` + ``Retry-After``."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DrainingError(RuntimeError):
    """Request rejected (or terminated) because the pod is draining for a
    rolling restart — clients should retry against another pod (503)."""


def admission_reject_response(web, err: AdmissionError):
    """The one 429 shape for every admission-reject site: the JSON body
    carries the float hint verbatim; the ``Retry-After`` header is the
    hint rounded UP to whole seconds (RFC 9110 allows only integers) and
    floored at 1 — truncation would turn a 0.2 s hint into ``0``, an
    immediate-retry invitation to the exact client being shed.
    ``web`` is the caller's ``aiohttp.web`` module (imported lazily by
    the HTTP surface, so this helper takes it rather than importing)."""
    retry_after = max(int(-(-err.retry_after_s // 1)), 1)
    return web.json_response(
        {"error": str(err), "retry_after_s": err.retry_after_s},
        status=429,
        headers={"Retry-After": str(retry_after)},
    )


class _ServingMetrics:
    """Prometheus serving metrics (the pod-side analogue of the indexer's
    collector): request/token counters, prefix-cache savings, TTFT histogram.
    Inert when prometheus_client is unavailable."""

    def __init__(
        self,
        obs: bool = False,
        lifecycle: bool = False,
        tenant_qos: bool = False,
        integrity: bool = False,
        exemplars: bool = False,
    ):
        """``obs``: build the PR-5 latency-decomposition histograms and
        engine-step telemetry series (``OBS_METRICS``). ``lifecycle``:
        build the ISSUE 15 block-lifecycle families (tier transitions,
        per-tier residency, reuse distance — fed by the ``OBS_LIFECYCLE``
        ledger/estimator). ``tenant_qos``: build the tenant-labeled SLO
        burn gauge (``TENANT_QOS`` + ``OBS_SLO``). ``integrity``: build
        the ISSUE 19 digest-check/quarantine/scrub families (delta-synced
        from the engine's ``BlockIntegrity`` counters). All off (default)
        keeps the exposition surface bit-identical to previous rounds."""
        # Measured serving rates (EMAs over request completions), kept
        # OUTSIDE the prometheus guard: admission control derives its
        # Retry-After hint from them, with or without prometheus_client.
        self.request_rate: Optional[float] = None  # finished requests / s
        self.token_rate: Optional[float] = None  # generated tokens / s
        self._last_finish: Optional[float] = None
        self._obs = bool(obs)
        self._lifecycle = bool(lifecycle)
        self._tenant_qos = bool(tenant_qos)
        self._integrity = bool(integrity)
        # OBS_EXEMPLARS (ISSUE 20): latency histograms attach the
        # observing request's trace_id per bucket, and exposition()
        # switches to the OpenMetrics format (the classic text format
        # drops exemplars) — a tail bucket then resolves directly to
        # /debug/traces?trace=<id>.
        self._exemplars = bool(exemplars)
        try:
            import prometheus_client as prom
        except ImportError:  # pragma: no cover
            self._prom = None
            return
        self._prom = prom
        self.registry = prom.CollectorRegistry()
        self.requests = prom.Counter(
            "tpu_pod_requests_total", "Completed requests", registry=self.registry
        )
        self.generated = prom.Counter(
            "tpu_pod_generated_tokens_total",
            "Generated tokens",
            registry=self.registry,
        )
        self.cached_prompt = prom.Counter(
            "tpu_pod_cached_prompt_tokens_total",
            "Prompt tokens served from the prefix cache",
            registry=self.registry,
        )
        self.ttft = prom.Histogram(
            "tpu_pod_ttft_seconds",
            "Time to first token",
            registry=self.registry,
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30),
        )
        # Speculative decoding (engine.spec_stats mirrored as counters;
        # acceptance rate = accepted/proposed).
        self.spec_proposed = prom.Counter(
            "tpu_pod_spec_proposed_tokens_total",
            "Speculative tokens proposed",
            registry=self.registry,
        )
        self.spec_accepted = prom.Counter(
            "tpu_pod_spec_accepted_tokens_total",
            "Speculative tokens accepted",
            registry=self.registry,
        )
        self.spec_verify = prom.Counter(
            "tpu_pod_spec_verify_steps_total",
            "Speculative verify rounds",
            registry=self.registry,
        )
        self.spec_bursts = prom.Counter(
            "tpu_pod_spec_bursts_total",
            "Speculative host-sync bursts (verify rounds per host sync = "
            "verify_steps/bursts)",
            registry=self.registry,
        )
        self._spec_seen = {
            "proposed": 0, "accepted": 0, "verify_steps": 0, "bursts": 0,
        }
        # Overload protection / request lifecycle (PR 4): admission sheds,
        # deadline expiries, aborts, drain activity.
        self.admission_rejected = prom.Counter(
            "kvcache_admission_rejected_total",
            "Requests rejected by admission control (429)",
            registry=self.registry,
        )
        self.admission_rejected_draining = prom.Counter(
            "kvcache_admission_draining_rejected_total",
            "Requests rejected because the pod was draining (503)",
            registry=self.registry,
        )
        self.deadline_shed = prom.Counter(
            "kvcache_admission_deadline_shed_total",
            "Deadline-expired requests shed before any prefill compute",
            registry=self.registry,
        )
        self.deadline_expired = prom.Counter(
            "kvcache_admission_deadline_expired_total",
            "Running requests finished early at their deadline",
            registry=self.registry,
        )
        self.requests_aborted = prom.Counter(
            "kvcache_admission_aborted_total",
            "Requests aborted mid-flight (client disconnect/timeout)",
            registry=self.registry,
        )
        self.drain_started = prom.Counter(
            "kvcache_drain_started_total",
            "Graceful drains started (SIGTERM / POST /drain)",
            registry=self.registry,
        )
        self.drain_completed = prom.Counter(
            "kvcache_drain_completed_total",
            "Graceful drains completed with every inflight request finished",
            registry=self.registry,
        )
        self.drain_forced = prom.Counter(
            "kvcache_drain_forced_requests_total",
            "Inflight requests aborted because the drain timeout expired",
            registry=self.registry,
        )
        self._lifecycle_seen = {
            "deadline_shed": 0, "deadline_expired": 0, "aborted": 0,
        }
        # Latency decomposition + engine-step telemetry (PR 5): built only
        # under OBS_METRICS so the default exposition stays unchanged.
        if self._obs:
            slo_buckets = (
                0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
            )
            # TTFT/ITL get a denser grid: a full sub-100 ms decade plus
            # 0.15/0.2 splits of the old 0.1–0.25 gap. The default
            # buckets aliased the CPU-smoke serving regime — the r12
            # burst-arm p50 (≈ 0.17 s) and the precise/predicted race it
            # decided both lived inside ONE 2.5x-wide bucket, so the
            # quantile estimate moved more with bucket placement than
            # with routing policy. queue/e2e/pull keep the legacy grid.
            lat_buckets = (
                0.001, 0.0025, 0.005, 0.0075, 0.01, 0.015, 0.02, 0.03,
                0.04, 0.06, 0.08, 0.1, 0.15, 0.2, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0, 60.0,
            )
            req_labels = ["outcome", "finish"]
            self.req_ttft = prom.Histogram(
                "kvcache_request_ttft_seconds",
                "Time to first token, by cache outcome (warm/pull/cold) "
                "and finish reason",
                req_labels, registry=self.registry, buckets=lat_buckets,
            )
            self.req_itl = prom.Histogram(
                "kvcache_request_itl_seconds",
                "Mean inter-token latency per request "
                "((finish - first token) / (generated - 1))",
                req_labels, registry=self.registry, buckets=lat_buckets,
            )
            self.req_queue = prom.Histogram(
                "kvcache_request_queue_seconds",
                "Submit-to-first-prefill-dispatch wait",
                req_labels, registry=self.registry, buckets=slo_buckets,
            )
            self.req_e2e = prom.Histogram(
                "kvcache_request_e2e_seconds",
                "Submit-to-finish wall time",
                req_labels, registry=self.registry, buckets=slo_buckets,
            )
            self.transfer_pull = prom.Histogram(
                "kvcache_transfer_pull_seconds",
                "pull_prefix wall time (fetch + import), by outcome "
                "(ok/empty/failed)",
                ["outcome"], registry=self.registry, buckets=slo_buckets,
            )
            self.pull_overlap = prom.Histogram(
                "kvcache_transfer_pull_overlap_seconds",
                "Async KV-pull (ASYNC_PULL) wall time split by exposure: "
                "hidden = spent before the scheduler first wanted the "
                "sequence (overlapped with other work), exposed = the "
                "remainder (it delayed this sequence's prefill)",
                ["kind"], registry=self.registry, buckets=slo_buckets,
            )
            self.engine_steps = prom.Counter(
                "kvcache_engine_steps_total",
                "Engine iterations",
                registry=self.registry,
            )
            self.engine_phase_s = prom.Counter(
                "kvcache_engine_step_phase_seconds_total",
                "Cumulative engine-step wall seconds by phase (schedule/"
                "prefill/decode/sample/gather/demote/publish; gather, "
                "sample and demote overlap the dispatch phases)",
                ["phase"], registry=self.registry,
            )
            self.engine_occupancy = prom.Gauge(
                "kvcache_engine_batch_occupancy",
                "Running decode lanes / decode_batch_size",
                registry=self.registry,
            )
            self.engine_free_pages = prom.Gauge(
                "kvcache_engine_free_pages",
                "Free KV pages in the HBM pool",
                registry=self.registry,
            )
            self.engine_loop_lag = prom.Gauge(
                "kvcache_engine_loop_lag_seconds",
                "EMA of host-side gap between engine iterations while work "
                "was pending (staging, bookkeeping, GIL pressure)",
                registry=self.registry,
            )
            self._step_seen = dict.fromkeys(
                (
                    "schedule_s", "prefill_s", "decode_s", "sample_s",
                    "gather_s", "demote_s", "publish_s",
                ),
                0.0,
            )
            self._steps_seen = 0
            # Host-DRAM tier + prefetch (ISSUE 6): tier occupancy, pages
            # served back from host DRAM (by path: ahead-of-scheduler
            # prefetch vs blocking allocate), and prefetch-round wall time.
            self.host_pages_g = prom.Gauge(
                "kvcache_host_pages",
                "KV blocks currently cached in the host-DRAM tier",
                registry=self.registry,
            )
            self.host_hits = prom.Counter(
                "kvcache_host_hits_total",
                "KV blocks brought back from the host-DRAM tier, by path "
                "(prefetch = ahead of the scheduler, allocate = blocking)",
                ["path"], registry=self.registry,
            )
            self.host_prefetch_s = prom.Histogram(
                "kvcache_host_prefetch_seconds",
                "Host-tier prefetch round wall time (hash walk + restore "
                "queueing; the DMA itself overlaps the step's dispatch)",
                registry=self.registry, buckets=slo_buckets,
            )
            self._host_seen = {"restored": 0, "prefetched": 0}
            # SLO burn rate (PR 10): in-process evaluation of OBS_SLO
            # objectives against the same measurements the request
            # histograms observe; series appear only when an SLORecorder
            # feeds them (scrape-driven sync).
            self.slo_burn = prom.Gauge(
                "kvcache_slo_burn_rate",
                "Error-budget burn rate per OBS_SLO objective and sliding "
                "window (1.0 = budget burns at exactly its sustainable "
                "rate)",
                ["objective", "window"], registry=self.registry,
            )
        # Block-lifecycle families (ISSUE 15, OBS_LIFECYCLE): tier
        # transitions, per-tier residency, sampled reuse distance. Built
        # only under the lifecycle knob so the default exposition surface
        # stays unchanged; fed by the ledger/estimator callbacks.
        if self._lifecycle:
            self.block_transitions = prom.Counter(
                "kvcache_block_tier_transitions_total",
                "KV-block tier transitions recorded by the lifecycle "
                "ledger: from/to in {none, tpu_hbm, host_dram, remote}, "
                "reason = allocate/import/spill/restore/prefetch/demote "
                "(hand-off to the pusher; corrected by demote_failed on "
                "drop/failure)/evict",
                ["from", "to", "reason"], registry=self.registry,
            )
            self.block_residency = prom.Histogram(
                "kvcache_block_tier_residency_seconds",
                "How long a KV block stayed resident in a tier before "
                "leaving it (observed at departure)",
                ["tier"], registry=self.registry,
                buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                         120.0, 300.0, 600.0, 1800.0, 3600.0),
            )
            self.reuse_distance = prom.Histogram(
                "kvcache_reuse_distance_blocks",
                "Sampled LRU stack distance of prefix-block lookups, in "
                "blocks: P[distance < C] is the modeled hit rate of a "
                "C-block tier (the MRC behind /debug/mrc); cold accesses "
                "land in +Inf",
                registry=self.registry,
                buckets=tuple(
                    float(b) for b in lifecycle_mod.REUSE_DISTANCE_BUCKETS
                ),
            )
        # Tenant-sliced SLO burn (TENANT_QOS): same arithmetic as
        # kvcache_slo_burn_rate over the recorder's per-tenant slices.
        # Built only under the tenant knob so the default exposition
        # surface stays unchanged; tenant label values are the serving
        # layer's bounded slice keys, never raw header values.
        if self._tenant_qos:
            self.tenant_slo_burn = prom.Gauge(
                "kvcache_tenant_slo_burn_rate",
                "Error-budget burn rate per tenant, OBS_SLO objective and "
                "sliding window (the per-tenant slice of "
                "kvcache_slo_burn_rate; 1.0 = budget burns at exactly its "
                "sustainable rate)",
                ["tenant", "objective", "window"], registry=self.registry,
            )
        # KV-block integrity families (ISSUE 19, KV_INTEGRITY): built only
        # under the knob so the default exposition surface stays
        # unchanged; delta-synced from ``BlockIntegrity.stats`` on the
        # engine loop (same pattern as spec/host).
        if self._integrity:
            self.integrity_checks = prom.Counter(
                "kvcache_integrity_checks_total",
                "KV-block content-digest checks at tier transitions, by "
                "outcome (ok / corrupt / unverified = no recorded digest, "
                "served on the legacy trust model)",
                ["outcome"], registry=self.registry,
            )
            self.integrity_quarantined = prom.Counter(
                "kvcache_integrity_quarantined_total",
                "KV-block copies quarantined after a failed digest check "
                "(chain truncated; suffix recomputed cold)",
                registry=self.registry,
            )
            self.integrity_scrub_pages = prom.Counter(
                "kvcache_integrity_scrub_pages_total",
                "Resident host-tier slots verified by the background "
                "integrity scrubber",
                registry=self.registry,
            )
            self._integrity_seen = {
                "checks_ok": 0,
                "checks_corrupt": 0,
                "checks_unverified": 0,
                "quarantined": 0,
                "scrub_pages": 0,
            }

    def observe_tier_transition(self, frm: str, to: str, reason: str) -> None:
        if self._prom is None or not self._lifecycle:
            return
        self.block_transitions.labels(frm, to, reason).inc()

    def observe_tier_residency(self, tier: str, seconds: float) -> None:
        if self._prom is None or not self._lifecycle:
            return
        self.block_residency.labels(tier=tier).observe(seconds)

    def observe_reuse_distance(self, distance_blocks: float) -> None:
        """Cold (inf) distances are clamped to a finite over-the-top
        value: they belong in the +Inf bucket, not in the _sum series."""
        if self._prom is None or not self._lifecycle:
            return
        self.reuse_distance.observe(
            min(distance_blocks, lifecycle_mod.COLD_DISTANCE_CLAMP)
        )

    def sync_integrity_stats(self, stats: dict) -> None:
        """Mirror the ``BlockIntegrity`` monotone counters into Prometheus
        (delta sync, same pattern as spec/host/lifecycle)."""
        if self._prom is None or not self._integrity:
            return
        for key, outcome in (
            ("checks_ok", "ok"),
            ("checks_corrupt", "corrupt"),
            ("checks_unverified", "unverified"),
        ):
            d = stats.get(key, 0) - self._integrity_seen[key]
            if d > 0:
                self.integrity_checks.labels(outcome=outcome).inc(d)
                self._integrity_seen[key] += d
        for key, counter in (
            ("quarantined", self.integrity_quarantined),
            ("scrub_pages", self.integrity_scrub_pages),
        ):
            d = stats.get(key, 0) - self._integrity_seen[key]
            if d > 0:
                counter.inc(d)
                self._integrity_seen[key] += d

    def set_slo_burn(self, objective: str, window: str, rate: float) -> None:
        if self._prom is None or not self._obs:
            return
        self.slo_burn.labels(objective=objective, window=window).set(rate)

    def set_tenant_slo_burn(
        self, tenant: str, objective: str, window: str, rate: float
    ) -> None:
        if self._prom is None or not self._tenant_qos:
            return
        self.tenant_slo_burn.labels(
            tenant=tenant, objective=objective, window=window
        ).set(rate)

    def observe_pull(
        self, seconds: float, outcome: str, trace_id: Optional[str] = None
    ) -> None:
        """One ``pull_prefix`` attempt: outcome ok (imported >= 1 block),
        empty (nothing to pull — no hashes, or peer had no warm blocks),
        skipped (never attempted: deadline budget exhausted or the pod is
        shutting down — the overload signal, kept distinct from empty),
        failed (fetch/import error, fell back to cold), or canceled (the
        sequence died while an async fetch was in flight). Under
        OBS_EXEMPLARS the pulling request's trace_id rides the bucket as
        an OpenMetrics exemplar."""
        if self._prom is None or not self._obs:
            return
        hist = self.transfer_pull.labels(outcome=outcome)
        if self._exemplars and trace_id:
            hist.observe(seconds, exemplar={"trace_id": trace_id})
        else:
            hist.observe(seconds)

    def observe_pull_overlap(self, hidden_s: float, exposed_s: float) -> None:
        """One async pull's wall-time split: ``hidden`` = before the
        scheduler first wanted the sequence (overlapped with other work),
        ``exposed`` = the remainder (it held this sequence's prefill)."""
        if self._prom is None or not self._obs:
            return
        self.pull_overlap.labels(kind="hidden").observe(max(hidden_s, 0.0))
        self.pull_overlap.labels(kind="exposed").observe(max(exposed_s, 0.0))

    def sync_step_stats(self, step_stats: dict, lag_s: Optional[float]) -> None:
        """Mirror the engine's cumulative step-phase seconds into the
        labeled counter (delta sync, same pattern as spec/lifecycle)."""
        if self._prom is None or not self._obs:
            return
        steps = step_stats.get("steps", 0)
        if steps > self._steps_seen:
            self.engine_steps.inc(steps - self._steps_seen)
            self._steps_seen = steps
        for key, seen in self._step_seen.items():
            delta = step_stats.get(key, 0.0) - seen
            if delta > 0:
                self.engine_phase_s.labels(phase=key[:-2]).inc(delta)
                self._step_seen[key] = step_stats[key]
        if lag_s is not None:
            self.engine_loop_lag.set(lag_s)

    def set_engine_gauges(self, occupancy: float, free_pages: int) -> None:
        if self._prom is None or not self._obs:
            return
        self.engine_occupancy.set(occupancy)
        self.engine_free_pages.set(free_pages)

    def observe_host_prefetch(self, seconds: float) -> None:
        if self._prom is None or not self._obs:
            return
        self.host_prefetch_s.observe(seconds)

    def sync_host_stats(self, host_stats: dict, host_cached: int) -> None:
        """Mirror the block manager's monotone host-tier counters (delta
        sync, same pattern as spec/lifecycle). ``restored`` counts every
        bring-back; the prefetch stage's share is broken out by label."""
        if self._prom is None or not self._obs:
            return
        self.host_pages_g.set(host_cached)
        d_pref = host_stats.get("prefetched", 0) - self._host_seen["prefetched"]
        d_rest = host_stats.get("restored", 0) - self._host_seen["restored"]
        if d_pref > 0:
            self.host_hits.labels(path="prefetch").inc(d_pref)
            self._host_seen["prefetched"] = host_stats["prefetched"]
        d_alloc = d_rest - d_pref
        if d_alloc > 0:
            self.host_hits.labels(path="allocate").inc(d_alloc)
        if d_rest > 0:
            self._host_seen["restored"] = host_stats["restored"]

    @staticmethod
    def request_labels(seq: Sequence) -> tuple[str, str]:
        """(outcome, finish) labels for the request histograms: outcome =
        "pull" when the router's verdict was a transfer pull, else the
        measured prefix-cache hit ("warm"/"cold"); finish = the
        early-finish reason or the normal stop/length verdict."""
        # Ground truth decides warm vs cold (a router that said "warm" on
        # a cold fleet still ran a cold prefill here — the pod's
        # histograms must agree with the scorer-side route_decisions
        # correction in router.py, not with the router's optimism); only
        # the "pull" verdict is kept as its own class.
        if seq.route_action == "pull":
            outcome = "pull"
        else:
            outcome = "warm" if seq.num_cached_prompt else "cold"
        finish = seq.finish_reason
        if finish is None:
            finish = (
                "length"
                if seq.num_generated >= seq.sampling.max_new_tokens
                else "stop"
            )
        return outcome, finish

    def observe_request_decomposition(self, seq: Sequence) -> None:
        """Latency-decomposition histograms from the timestamps the engine
        already stamps (no extra clock reads on the hot path)."""
        if self._prom is None or not self._obs:
            return
        outcome, finish = self.request_labels(seq)
        lab = {"outcome": outcome, "finish": finish}
        # OBS_EXEMPLARS: the finishing request's trace id (still attached
        # here — spans are detached later, in _emit_request_spans) rides
        # the TTFT/ITL buckets it lands in.
        exemplar = None
        if self._exemplars and seq.trace_span is not None:
            ctx = getattr(seq.trace_span, "context", None)
            if ctx is not None:
                exemplar = {"trace_id": ctx.trace_id}
        if seq.ttft is not None:
            if exemplar is not None:
                self.req_ttft.labels(**lab).observe(seq.ttft, exemplar=exemplar)
            else:
                self.req_ttft.labels(**lab).observe(seq.ttft)
        if seq.prefill_start_time is not None:
            self.req_queue.labels(**lab).observe(
                max(seq.prefill_start_time - seq.arrival_time, 0.0)
            )
        if seq.finish_time is not None:
            self.req_e2e.labels(**lab).observe(
                max(seq.finish_time - seq.arrival_time, 0.0)
            )
            if seq.mean_itl is not None:
                if exemplar is not None:
                    self.req_itl.labels(**lab).observe(
                        seq.mean_itl, exemplar=exemplar
                    )
                else:
                    self.req_itl.labels(**lab).observe(seq.mean_itl)

    def sync_lifecycle_stats(self, stats: dict) -> None:
        """Mirror the engine's monotone lifecycle counters (deadline sheds/
        expiries, aborts) into Prometheus."""
        if self._prom is None:
            return
        for key, counter in (
            ("deadline_shed", self.deadline_shed),
            ("deadline_expired", self.deadline_expired),
            ("aborted", self.requests_aborted),
        ):
            delta = stats.get(key, 0) - self._lifecycle_seen[key]
            if delta > 0:
                counter.inc(delta)
                self._lifecycle_seen[key] = stats[key]

    def observe_rejected(self, draining: bool) -> None:
        if self._prom is None:
            return
        if draining:
            self.admission_rejected_draining.inc()
        else:
            self.admission_rejected.inc()

    def observe_drain(self, event: str, amount: int = 1) -> None:
        if self._prom is None:
            return
        counter = {
            "started": self.drain_started,
            "completed": self.drain_completed,
            "forced": self.drain_forced,
        }[event]
        counter.inc(amount)

    def sync_spec_stats(self, stats: dict) -> None:
        """Mirror the engine's monotone spec counters into Prometheus."""
        if self._prom is None:
            return
        for key, counter in (
            ("proposed", self.spec_proposed),
            ("accepted", self.spec_accepted),
            ("verify_steps", self.spec_verify),
            ("bursts", self.spec_bursts),
        ):
            delta = stats.get(key, 0) - self._spec_seen[key]
            if delta > 0:
                counter.inc(delta)
                self._spec_seen[key] = stats[key]

    def observe_finished(self, seq: Sequence) -> None:
        # Rate EMAs first (prometheus-independent): only requests that
        # produced tokens feed them — a shed/aborted request finishing
        # instantly would wildly overstate sustainable throughput.
        if seq.num_generated > 0:
            now = time.monotonic()
            if self._last_finish is not None:
                dt = max(now - self._last_finish, 1e-3)
                alpha = 0.3
                inst_r, inst_t = 1.0 / dt, seq.num_generated / dt
                self.request_rate = (
                    inst_r
                    if self.request_rate is None
                    else (1 - alpha) * self.request_rate + alpha * inst_r
                )
                self.token_rate = (
                    inst_t
                    if self.token_rate is None
                    else (1 - alpha) * self.token_rate + alpha * inst_t
                )
            self._last_finish = now
        if self._prom is None:
            return
        self.requests.inc()
        self.generated.inc(seq.num_generated)
        if seq.num_cached_prompt:
            self.cached_prompt.inc(seq.num_cached_prompt)
        if seq.ttft is not None:
            self.ttft.observe(seq.ttft)
        if self._obs:
            self.observe_request_decomposition(seq)

    def exposition(self) -> Optional[bytes]:
        if self._prom is None:
            return None
        if self._exemplars:
            # Exemplars render only in the OpenMetrics exposition — the
            # classic text format silently drops them.
            from prometheus_client.openmetrics import exposition as om

            return om.generate_latest(self.registry)
        return self._prom.generate_latest(self.registry)

    def exposition_content_type(self) -> str:
        """The Content-Type matching ``exposition()``'s format (the
        OpenMetrics one is parameterized — callers must set it via a
        headers dict; aiohttp's ``content_type=`` rejects parameters)."""
        if self._exemplars:
            from prometheus_client.openmetrics import exposition as om

            return om.CONTENT_TYPE_LATEST
        return "text/plain"


def _env_bool(name: str, default: str) -> bool:
    return os.environ.get(name, default).strip().lower() not in (
        "0",
        "false",
        "no",
        "off",
        "",
    )


@dataclass
class PodServerConfig:
    model_name: str = "tiny-llama"
    pod_identifier: str = field(default_factory=socket.gethostname)
    #: indexer-side SUB socket to connect the PUB to (SUB binds, we connect —
    #: reference zmq_subscriber.go:90 / publisher.go:59).
    zmq_endpoint: str = "tcp://localhost:5557"
    publish_events: bool = True
    data_parallel_rank: Optional[int] = None
    http_port: int = 8000
    #: cross-pod KV transfer: ROUTER bind address for this pod's page
    #: export service (``tcp://*:5558``-style). None (default) = transfer
    #: plane off — bit-identical legacy behavior, nothing binds.
    transfer_endpoint: Optional[str] = None
    #: cap on blocks per transfer response (both served and pulled)
    transfer_max_blocks: int = 64
    #: fetch deadline; an expired pull falls back to cold prefill
    transfer_timeout_s: float = 10.0
    #: async prefix import (``ASYNC_PULL``): a pull-routed request enters
    #: the waiting queue in an ``importing`` state while a worker thread
    #: fetches + verifies the chain in the background; the scheduler
    #: admits it only once the imported blocks land (or the fetch fails —
    #: cold-prefill fallback preserved), so decode batches and later
    #: arrivals never stall on the wire. Off (default) = the legacy
    #: blocking ``pull_prefix``-then-submit flow, bit-identical.
    async_pull: bool = False
    #: import worker threads for ASYNC_PULL — bounds concurrent in-flight
    #: fetches (each holds one DEALER socket + one staged import). Size to
    #: the expected concurrent pull-routed admissions; see
    #: docs/operations.md.
    pull_workers: int = 2
    #: disaggregated serving role (``POD_ROLE``): "mixed" (default) serves
    #: prefill and decode exactly as today — bit-identical legacy behavior
    #: and wire bytes. "prefill" runs ingest at full batch width and stops
    #: at the first token (submits are clamped to one generated token; the
    #: finished chain is exported over the transfer fabric and announced
    #: with a ``PrefillComplete`` event). "decode" admits handed-off
    #: requests (``pull_source``) and streams tokens; the scorer keeps it
    #: out of prefill placement via the heartbeat role advertisement.
    pod_role: str = "mixed"
    # -- remote tier (ISSUE 13; all off by default = bit-identical legacy
    # -- behavior and heartbeat/transfer/KV-event wire bytes) --------------
    #: master switch: evictions that would destroy the last local copy of
    #: a chain demote over the transfer fabric instead (pushed to a peer
    #: with advertised headroom / a ``POD_ROLE=kvstore`` pod), imports may
    #: recycle evictable pages (victims demote — lossless), heartbeats
    #: advertise remote-store headroom, and pushes from peers are
    #: accepted into this pod's remote store.
    remote_tier: bool = False
    #: remote-store capacity in pages (how many demoted blocks THIS pod
    #: holds for peers); 0 accepts nothing. A dedicated kvstore pod sets
    #: this large. Sizing guidance in docs/operations.md.
    remote_store_pages: int = 0
    #: comma-separated transfer endpoints of demotion targets (peer pods
    #: or kvstore pods). Empty = this pod never demotes (but can still
    #: accept pushes / serve pull-backs with the knob on).
    remote_peers: str = ""
    #: bound on payloads parked for the background pusher; overflow drops
    #: the OLDEST (coldest) payloads — plain eviction, counted.
    remote_demote_queue: int = 1024
    # -- fleet self-healing (all off by default = bit-identical legacy) ----
    #: seconds between Heartbeat events (liveness beacon + publisher drop
    #: report for the indexer's dead-pod sweep); 0 = no heartbeats.
    heartbeat_interval_s: float = 0.0
    #: seconds between periodic IndexSnapshot resyncs (replace-all-for-pod
    #: digest of resident blocks per tier); 0 = no periodic resync.
    resync_interval_s: float = 0.0
    #: transfer circuit breaker: consecutive pull failures per peer before
    #: the breaker opens and pulls skip straight to cold prefill; 0 = off.
    transfer_breaker_failures: int = 0
    #: first OPEN backoff; doubles per failed half-open probe (capped).
    transfer_breaker_backoff_s: float = 1.0
    transfer_breaker_backoff_max_s: float = 30.0
    # -- overload protection / request lifecycle (all off by default = ----
    # -- bit-identical legacy behavior) ------------------------------------
    #: admission control: max requests queued ahead of the engine (staged +
    #: scheduler waiting). Above it ``submit`` fails fast with 429 +
    #: ``Retry-After`` instead of queueing unboundedly. 0 = unbounded.
    admission_max_waiting: int = 0
    #: admission control: cap on outstanding admitted prompt tokens (a
    #: conservative proxy for queued prefill work — it includes requests
    #: currently in compute). 0 = unbounded.
    admission_max_queued_tokens: int = 0
    #: default per-request deadline in seconds when the client sends no
    #: ``X-Request-Deadline`` header. Expired waiting requests are shed
    #: before prefill; running requests finish early with
    #: ``finish_reason="deadline"``. 0 = no deadline.
    default_deadline_s: float = 0.0
    #: graceful drain: how long inflight requests get to finish after
    #: SIGTERM / ``POST /drain`` before being aborted.
    drain_timeout_s: float = 30.0
    # -- observability (PR 5; all off by default = bit-identical legacy ----
    # -- responses, /stats fields, and heartbeat wire bytes) ---------------
    #: request tracing: span recorder + W3C traceparent propagation
    #: (adopted from the ``traceparent`` request header, threaded through
    #: the engine and the transfer envelope); finished traces served at
    #: ``GET /debug/traces``.
    obs_tracing: bool = False
    #: finished-span ring size for /debug/traces
    obs_trace_buffer: int = 2048
    #: latency-decomposition histograms (TTFT/ITL/queue/e2e/pull) +
    #: engine-step phase timing, batch-occupancy / free-page / loop-lag
    #: gauges on /metrics, and an ``obs`` block on /stats.
    obs_metrics: bool = False
    #: OpenMetrics trace exemplars (ISSUE 20): the OBS_METRICS latency
    #: histograms (TTFT/ITL/pull) attach the observing request's trace_id
    #: per bucket and /metrics switches to the OpenMetrics exposition —
    #: a tail bucket resolves directly to ``/debug/traces?trace=<id>``.
    #: Off (default) = classic exposition, bit-identical bytes.
    obs_exemplars: bool = False
    #: directory for ``POST /debug/profile`` jax.profiler traces; unset =
    #: the endpoint is disabled.
    obs_profile_dir: Optional[str] = None
    # -- routing-quality audit + SLO recording (PR 10; off by default = --
    # -- bit-identical responses, /stats fields, and wire bytes) -----------
    #: publish a trailing-append ``RequestAudit`` KV event per finished
    #: request carrying the realized prefix-cache hit count, so the
    #: indexer's route auditor can join prediction with reality.
    obs_audit: bool = False
    #: SLO objectives evaluated in-process against the same measurements
    #: the PR 5 histograms observe, e.g. ``"ttft:0.5:0.99;itl:0.05:0.95"``
    #: (metric:threshold_s:target, ";"-separated). Unset = no recorder.
    obs_slo: str = ""
    #: burn-rate windows in seconds, e.g. ``"60,300"`` (unset = 60,300)
    obs_slo_windows: str = ""
    # -- KV-capacity observability (ISSUE 15; both off by default = -------
    # -- bit-identical responses, /stats fields, and wire bytes) -----------
    #: block-lifecycle ledger + reuse-distance MRC: record every cached
    #: block's tier transitions off the block-manager hooks and sample
    #: reuse distances off the allocate-time prefix walk. Surfaced at
    #: ``/debug/lifecycle`` / ``/debug/mrc``, a ``lifecycle`` /stats
    #: block, and the kvcache_block_tier_*/kvcache_reuse_distance_blocks
    #: metric families.
    obs_lifecycle: bool = False
    #: lifecycle-ledger ring depth (recent transitions kept for
    #: /debug/lifecycle)
    obs_lifecycle_ring: int = 4096
    #: MRC spatial sample rate in (0, 1]: fraction of blocks (by
    #: deterministic hash) whose reuse distances are tracked
    obs_mrc_sample: float = 1.0
    #: distinct sampled blocks the MRC stack tracks (distances beyond
    #: this read as cold — the curve saturates at this capacity)
    obs_mrc_tracked: int = 8192
    #: flight recorder: always-on bounded ring of per-step engine
    #: telemetry + fleet events, dumped as one causally-ordered timeline
    #: on a trigger (SLO burn-rate crossing, breaker OPEN, resync).
    #: Implies engine step timing (the ring needs the phase deltas).
    obs_flight: bool = False
    #: flight-recorder ring depth (per ring: steps and events)
    obs_flight_ring: int = 2048
    #: directory for triggered timeline dumps; unset = in-memory only
    #: (``/debug/flight`` still serves the latest timeline)
    obs_flight_dir: Optional[str] = None
    #: burn-rate threshold that triggers a flight dump (needs OBS_SLO for
    #: the recorder; 8.0 ≈ "budget gone in 1/8 of the window" — between
    #: the classic 14.4x page and 6x ticket multiwindow alert arms)
    obs_flight_burn: float = 8.0
    # -- fleet controller (ISSUE 17; off by default = bit-identical legacy
    # -- behavior, /stats fields, and wire bytes) ---------------------------
    #: master switch (``FLEET_CONTROLLER``): this pod participates in
    #: MRC-driven autoscaling — it accepts live-migrated in-flight decode
    #: sequences over the transfer fabric (admitted via the PR 7
    #: ``importing`` state and resumed mid-generation with greedy parity)
    #: and may migrate its own sequences out on a scale-down. Off
    #: (default) answers migrations with the same tolerant refusal a
    #: legacy service gives, and ``migrate_out`` refuses locally.
    fleet_controller: bool = False
    # -- multi-tenant QoS (ISSUE 18; off by default = bit-identical legacy
    # -- behavior, /stats fields, and wire bytes) ---------------------------
    #: ``TENANT_QOS`` policy spec (see server/qos.py for the grammar):
    #: semicolon-separated ``name:prio=..,weight=..,max_waiting=..,
    #: max_queued_tokens=..,rps=..,cache_share=..`` entries; ``*`` is the
    #: default tenant. Set = requests are sliced by the ``X-Tenant``
    #: header: per-tenant admission budgets (429 + Retry-After),
    #: priority-ordered scheduling with cross-class preemption,
    #: weighted-fair token shares within a class, per-tenant
    #: evictable-page caps, and tenant-sliced observability (ledger
    #: rows, MRC slices, SLO burn rates). Unset (default) = no tenant
    #: dimension anywhere: bit-identical legacy behavior.
    tenant_qos: str = ""
    # -- KV-block integrity (ISSUE 19; off by default = bit-identical ------
    # -- legacy behavior, /stats fields, and wire bytes) --------------------
    #: ``KV_INTEGRITY`` master switch (mirrored into the engine config):
    #: write-time content digests on every host spill / demote / export,
    #: verify-on-transition (restore, prefetch bring-back, remote
    #: pull-back, transfer import, migration install), quarantine +
    #: cold-recompute fallback on mismatch, and fleet-wide ``BadBlock``
    #: revocation.
    kv_integrity: bool = False
    #: seconds between background scrub batches over resident host-tier
    #: slots (``INTEGRITY_SCRUB_INTERVAL_S``); 0 = scrubber off. Scrub
    #: batches run on the engine thread between steps.
    integrity_scrub_interval_s: float = 0.0
    #: host slots verified per scrub batch (``INTEGRITY_SCRUB_PAGES``)
    integrity_scrub_pages: int = 32
    engine: EngineConfig = field(default_factory=EngineConfig)

    @classmethod
    def from_env(cls) -> "PodServerConfig":
        cfg = cls()
        cfg.model_name = os.environ.get("MODEL_NAME", cfg.model_name)
        cfg.pod_identifier = os.environ.get("POD_IDENTIFIER", cfg.pod_identifier)
        cfg.zmq_endpoint = os.environ.get("ZMQ_ENDPOINT", cfg.zmq_endpoint)
        cfg.publish_events = _env_bool("PUBLISH_EVENTS", "1")
        if "DP_RANK" in os.environ:
            cfg.data_parallel_rank = int(os.environ["DP_RANK"])
        cfg.http_port = int(os.environ.get("HTTP_PORT", cfg.http_port))
        # Cross-pod KV transfer (unset/empty = off, legacy behavior).
        cfg.transfer_endpoint = os.environ.get("TRANSFER_ENDPOINT") or None
        cfg.transfer_max_blocks = int(
            os.environ.get("TRANSFER_MAX_BLOCKS", cfg.transfer_max_blocks)
        )
        cfg.transfer_timeout_s = float(
            os.environ.get("TRANSFER_TIMEOUT_S", cfg.transfer_timeout_s)
        )
        cfg.async_pull = _env_bool("ASYNC_PULL", "0")
        cfg.pull_workers = int(os.environ.get("PULL_WORKERS", cfg.pull_workers))
        # Disaggregated serving role (unset/"mixed" = legacy single-tier).
        cfg.pod_role = os.environ.get("POD_ROLE", cfg.pod_role).strip() or "mixed"
        # Remote tier (unset/0 = off, legacy behavior + wire bytes).
        cfg.remote_tier = _env_bool("REMOTE_TIER", "0")
        cfg.remote_store_pages = int(
            os.environ.get("REMOTE_STORE_PAGES", cfg.remote_store_pages)
        )
        cfg.remote_peers = os.environ.get("REMOTE_PEERS", cfg.remote_peers)
        cfg.remote_demote_queue = int(
            os.environ.get("REMOTE_DEMOTE_QUEUE", cfg.remote_demote_queue)
        )
        # Fleet self-healing (0/unset = off, legacy behavior).
        cfg.heartbeat_interval_s = float(
            os.environ.get("HEARTBEAT_INTERVAL_S", cfg.heartbeat_interval_s)
        )
        cfg.resync_interval_s = float(
            os.environ.get("RESYNC_INTERVAL_S", cfg.resync_interval_s)
        )
        cfg.transfer_breaker_failures = int(
            os.environ.get(
                "TRANSFER_BREAKER_FAILURES", cfg.transfer_breaker_failures
            )
        )
        cfg.transfer_breaker_backoff_s = float(
            os.environ.get(
                "TRANSFER_BREAKER_BACKOFF_S", cfg.transfer_breaker_backoff_s
            )
        )
        cfg.transfer_breaker_backoff_max_s = float(
            os.environ.get(
                "TRANSFER_BREAKER_BACKOFF_MAX_S", cfg.transfer_breaker_backoff_max_s
            )
        )
        # Overload protection / request lifecycle (0/unset = off, legacy).
        cfg.admission_max_waiting = int(
            os.environ.get("ADMISSION_MAX_WAITING", cfg.admission_max_waiting)
        )
        cfg.admission_max_queued_tokens = int(
            os.environ.get(
                "ADMISSION_MAX_QUEUED_TOKENS", cfg.admission_max_queued_tokens
            )
        )
        cfg.default_deadline_s = float(
            os.environ.get("REQUEST_DEADLINE_S", cfg.default_deadline_s)
        )
        cfg.drain_timeout_s = float(
            os.environ.get("DRAIN_TIMEOUT_S", cfg.drain_timeout_s)
        )
        # Observability (0/unset = off, legacy behavior).
        cfg.obs_tracing = _env_bool("OBS_TRACING", "0")
        cfg.obs_trace_buffer = int(
            os.environ.get("OBS_TRACE_BUFFER", cfg.obs_trace_buffer)
        )
        cfg.obs_metrics = _env_bool("OBS_METRICS", "0")
        cfg.obs_exemplars = _env_bool("OBS_EXEMPLARS", "0")
        cfg.obs_profile_dir = os.environ.get("OBS_PROFILE_DIR") or None
        cfg.obs_audit = _env_bool("OBS_AUDIT", "0")
        cfg.obs_slo = os.environ.get("OBS_SLO", "")
        cfg.obs_slo_windows = os.environ.get("OBS_SLO_WINDOWS", "")
        # KV-capacity observability (ISSUE 15; 0/unset = off, legacy).
        cfg.obs_lifecycle = _env_bool("OBS_LIFECYCLE", "0")
        cfg.obs_lifecycle_ring = int(
            os.environ.get("OBS_LIFECYCLE_RING", cfg.obs_lifecycle_ring)
        )
        cfg.obs_mrc_sample = float(
            os.environ.get("OBS_MRC_SAMPLE", cfg.obs_mrc_sample)
        )
        cfg.obs_mrc_tracked = int(
            os.environ.get("OBS_MRC_TRACKED", cfg.obs_mrc_tracked)
        )
        cfg.obs_flight = _env_bool("OBS_FLIGHT", "0")
        cfg.obs_flight_ring = int(
            os.environ.get("OBS_FLIGHT_RING", cfg.obs_flight_ring)
        )
        cfg.obs_flight_dir = os.environ.get("OBS_FLIGHT_DIR") or None
        cfg.obs_flight_burn = float(
            os.environ.get("OBS_FLIGHT_BURN", cfg.obs_flight_burn)
        )
        # Fleet controller (ISSUE 17; 0/unset = off, legacy behavior).
        cfg.fleet_controller = _env_bool("FLEET_CONTROLLER", "0")
        # Multi-tenant QoS (ISSUE 18; unset/empty = off, legacy behavior).
        cfg.tenant_qos = os.environ.get("TENANT_QOS", cfg.tenant_qos)
        # KV-block integrity (ISSUE 19; 0/unset = off, legacy behavior).
        cfg.kv_integrity = _env_bool("KV_INTEGRITY", "0")
        cfg.integrity_scrub_interval_s = float(
            os.environ.get(
                "INTEGRITY_SCRUB_INTERVAL_S", cfg.integrity_scrub_interval_s
            )
        )
        cfg.integrity_scrub_pages = int(
            os.environ.get("INTEGRITY_SCRUB_PAGES", cfg.integrity_scrub_pages)
        )

        eng = cfg.engine
        eng.block_manager = BlockManagerConfig(
            total_pages=int(os.environ.get("TOTAL_PAGES", 1024)),
            page_size=int(os.environ.get("BLOCK_SIZE", 16)),
            # Reference parity: the engine's hash seed must match the
            # indexer's (token_processor.go:37-40).
            hash_seed=os.environ.get("PYTHONHASHSEED", ""),
            host_pages=int(os.environ.get("HOST_PAGES", 0)),
        )
        # Host-tier admission: "auto" (self-calibrating recompute-vs-
        # restore cost model) or "always" (unconditional spill/restore).
        eng.host_tier_policy = os.environ.get(
            "HOST_TIER_POLICY", eng.host_tier_policy
        )
        # Paged-KV quantization ("int8"): host-tier slots and transfer
        # wire bytes halve; pages dequantize before re-entering the
        # attention path. Unset = full-width pages, bit-identical legacy.
        eng.kv_quant = os.environ.get("KV_QUANT") or None
        # HBM-resident KV quantization ("int8"): the page pools themselves
        # hold int8 codes + per-page scales, doubling the blocks a chip's
        # HBM budget holds; the Pallas decode kernel dequantizes
        # in-register. Read the MRC's 2x point (docs/operations.md) before
        # enabling. Unset = full-width HBM pages, bit-identical legacy.
        eng.kv_quant_hbm = os.environ.get("KV_QUANT_HBM") or None
        # Host-tier prefetch: bring-back ahead of the scheduler instead of
        # blocking inside allocate (needs HOST_PAGES > 0).
        eng.host_prefetch = _env_bool("HOST_PREFETCH", "0")
        eng.max_model_len = int(os.environ.get("MAX_MODEL_LEN", eng.max_model_len))
        # Chunked prefill + mixed steps: per-step prefill token budget so a
        # long prompt's ingest never stalls running decode lanes (0/unset =
        # legacy either-or scheduling).
        cpt = int(os.environ.get("CHUNKED_PREFILL_TOKENS", 0))
        eng.scheduler.chunked_prefill_tokens = cpt if cpt > 0 else None
        eng.tp = int(os.environ.get("TP", eng.tp))
        # Sequence-parallel prefill degree (ring attention; long prompts).
        eng.sp = int(os.environ.get("SP", eng.sp))
        eng.decode_batch_size = int(
            os.environ.get("DECODE_BATCH_SIZE", eng.decode_batch_size)
        )
        eng.decode_steps_per_iter = int(
            os.environ.get("DECODE_STEPS_PER_ITER", eng.decode_steps_per_iter)
        )
        # Pipeline fused-decode bursts (host/device overlap); needs
        # DECODE_STEPS_PER_ITER > 1 to take effect.
        eng.decode_pipeline = _env_bool("DECODE_PIPELINE", "0")
        # Device-resident decode fast path: last-token ids/lengths stay on
        # device across steps at any burst width, and the sampled-token
        # device_get becomes one async transfer overlapping the next
        # dispatch. Off = bit-identical legacy decode.
        eng.decode_fused_sampling = _env_bool("DECODE_FUSED_SAMPLING", "0")
        # Speculative decoding ("off" | "prompt_lookup") + its knobs.
        eng.spec_decode = os.environ.get("SPEC_DECODE", eng.spec_decode)
        eng.spec_k = int(os.environ.get("SPEC_K", eng.spec_k))
        eng.spec_ngram = int(os.environ.get("SPEC_NGRAM", eng.spec_ngram))
        # Fused speculative rounds per dispatch (device-chained
        # propose/verify/accept; amortizes per-dispatch host latency).
        eng.spec_rounds = int(os.environ.get("SPEC_ROUNDS", eng.spec_rounds))
        # Adaptive-gate knobs (tune or disable the per-sequence acceptance
        # gate without an image rebuild; SPEC_MIN_ACCEPT=0 disables it).
        eng.spec_min_accept = float(
            os.environ.get("SPEC_MIN_ACCEPT", eng.spec_min_accept)
        )
        eng.spec_min_sample = int(
            os.environ.get("SPEC_MIN_SAMPLE", eng.spec_min_sample)
        )
        eng.spec_max_scan = int(
            os.environ.get("SPEC_MAX_SCAN", eng.spec_max_scan)
        )
        # Weight quantization ("int8" halves weight HBM; models/quant.py).
        eng.quantize = os.environ.get("QUANTIZE") or None
        # CPU smoke runs (Pallas interpreter mode); never set on real TPU.
        eng.interpret = _env_bool("INTERPRET", "0")
        # Remote tier reaches the engine (demotion hooks, store, import
        # eviction ladder) through its own config.
        eng.remote_tier = cfg.remote_tier
        eng.remote_store_pages = (
            cfg.remote_store_pages if cfg.remote_tier else 0
        )
        # KV integrity reaches the engine (digest table, verify hooks)
        # through its own config.
        eng.kv_integrity = cfg.kv_integrity
        eng.kv_integrity_table_cap = int(
            os.environ.get("INTEGRITY_TABLE_CAP", eng.kv_integrity_table_cap)
        )
        return cfg


class PodServer:
    """Engine + event publisher + HTTP front end for one TPU serving pod."""

    def __init__(
        self,
        config: Optional[PodServerConfig] = None,
        *,
        engine: Optional[Engine] = None,
        tokenizer=None,
        publisher: Optional[ZMQPublisher] = None,
        transfer_cost_model=None,
    ):
        """``transfer_cost_model``: the router's shared
        ``kvcache/transfer.TransferCostModel``, when this pod participates
        in transfer-aware routing. The pod feeds it the two measured rates
        the decide() arms need — transfer bytes/s from every fetch this
        pod performs, prefill tokens/s from the engine's own online EMA —
        so the model's pull/cold branches can ever activate."""
        self.config = config or PodServerConfig()
        if self.config.pod_role not in ("mixed", "prefill", "decode", "kvstore"):
            raise ValueError(
                f"POD_ROLE must be mixed/prefill/decode/kvstore, got "
                f"{self.config.pod_role!r}"
            )
        if self.config.remote_tier and engine is None:
            # Thread the knob family into the engine config BEFORE the
            # engine is built (attach points live in its ctor). Injected
            # engines configure themselves.
            self.config.engine.remote_tier = True
            self.config.engine.remote_store_pages = self.config.remote_store_pages
        if self.config.kv_integrity and engine is None:
            # Same pattern for the integrity plane (ISSUE 19): the digest
            # table + verify hooks attach inside the engine ctor.
            self.config.engine.kv_integrity = True
        self._tokenizer = tokenizer
        self.transfer_cost_model = transfer_cost_model
        #: request tracing (OBS_TRACING); a disabled tracer hands out one
        #: shared no-op span, so the default request path allocates nothing.
        self.tracer = Tracer(
            enabled=self.config.obs_tracing,
            max_spans=self.config.obs_trace_buffer,
            service=f"pod:{self.config.pod_identifier}",
        )

        self._publisher = publisher
        if self._publisher is None and self.config.publish_events:
            self._publisher = ZMQPublisher(
                ZMQPublisherConfig(
                    endpoint=self.config.zmq_endpoint,
                    pod_identifier=self.config.pod_identifier,
                    model_name=self.config.model_name,
                    data_parallel_rank=self.config.data_parallel_rank,
                )
            )

        on_events = self._publisher.publish if self._publisher is not None else None
        self.engine = engine or Engine(self.config.engine, on_events=on_events)
        if engine is not None and on_events is not None:
            # Injected engine: attach the publisher to its block manager.
            self.engine.block_manager.on_events = on_events
        if self.config.obs_metrics or self.config.obs_flight:
            # The flight recorder's step ring needs the phase deltas, so
            # OBS_FLIGHT implies engine step timing even without
            # OBS_METRICS (same clocks, no new series).
            self.engine.obs_step_timing = True

        #: staging guard — HTTP threads only touch the staging deque; the
        #: engine itself is single-threaded (loop thread only), so steps run
        #: without any lock and enqueueing never waits on device compute.
        self._mu = threading.Lock()
        self._work = threading.Condition(self._mu)
        #: staged request tuples: (tokens, sampling, deadline, rid,
        #: future, span, route_action, pull_source, tenant_key)
        self._staging: deque[tuple] = deque()  # guarded_by: _mu|_work
        self._futures: dict[int, Future] = {}  # loop-thread-only
        #: staged aborts: (request_id | None = all, future -> bool)
        self._aborts: deque[tuple[Optional[str], Future]] = deque()  # guarded_by: _mu|_work
        #: admission accounting (under _mu): requests admitted by submit
        #: whose futures have not resolved yet, and their prompt tokens.
        self._pending = 0  # guarded_by: _mu|_work
        self._pending_tokens = 0  # guarded_by: _mu|_work
        self.admission_rejected = 0  # guarded_by: _mu|_work
        self.admission_rejected_draining = 0  # guarded_by: _mu|_work
        #: graceful drain state
        self._draining = False  # guarded_by: _mu|_work
        self._drain_done = threading.Event()
        self._drain_clean: Optional[bool] = None
        self.drains_started = 0  # guarded_by: _mu|_work
        self.drain_forced_requests = 0  # guarded_by: _mu|_work
        self.metrics = _ServingMetrics(
            obs=self.config.obs_metrics,
            lifecycle=self.config.obs_lifecycle,
            tenant_qos=bool(self.config.tenant_qos.strip()),
            integrity=self.config.kv_integrity,
            exemplars=self.config.obs_exemplars,
        )
        # -- KV-block integrity plane (ISSUE 19; off = None, no hooks) -----
        #: the engine's ``BlockIntegrity`` (digest table + quarantine set),
        #: or None when KV_INTEGRITY is off / the injected engine has none.
        self.integrity = getattr(self.engine, "integrity", None)
        self._integrity_quarantine_seen = 0  # loop-thread-only
        # -- multi-tenant QoS (ISSUE 18; off = None, no hooks anywhere) ----
        #: parsed TENANT_QOS policy table + per-tenant admission budgets.
        #: A malformed spec raises HERE, at construction — a silently
        #: dropped tenant entry would read as an unbudgeted tenant.
        self.qos = None
        if self.config.tenant_qos.strip():
            from .qos import TenantQoS, parse_tenant_qos

            self.qos = TenantQoS(parse_tenant_qos(self.config.tenant_qos))
            # Priority ordering + weighted-fair shares in the scheduler,
            # per-tenant page accounting + evictable-share caps in the
            # block manager (both engine-thread-only state).
            self.engine.scheduler.attach_qos()
            self.engine.block_manager.attach_qos(
                self.qos,
                # Per-tenant MRC slices ride the OBS_LIFECYCLE knob: each
                # tenant's allocate-time chains feed its own estimator
                # (same sampling knobs as the global curve).
                mrc_factory=(
                    self._make_tenant_mrc
                    if self.config.obs_lifecycle
                    else None
                ),
            )
        # -- KV-capacity observability (ISSUE 15; off = None, no hooks) ----
        #: block-lifecycle ledger + reuse-distance MRC (OBS_LIFECYCLE)
        self.lifecycle = None
        self.mrc = None
        if self.config.obs_lifecycle:
            from ..obs.lifecycle import (
                BlockLifecycleLedger,
                ReuseDistanceEstimator,
            )

            self.lifecycle = BlockLifecycleLedger(
                ring=self.config.obs_lifecycle_ring,
                on_transition=self.metrics.observe_tier_transition,
                on_residency=self.metrics.observe_tier_residency,
            )
            self.mrc = ReuseDistanceEstimator(
                sample_rate=self.config.obs_mrc_sample,
                max_tracked=self.config.obs_mrc_tracked,
                on_distance=self.metrics.observe_reuse_distance,
            )
            self.engine.block_manager.attach_lifecycle(
                self.lifecycle, self.mrc
            )
        #: anomaly-triggered flight recorder (OBS_FLIGHT)
        self.flight = None
        if self.config.obs_flight:
            from ..obs.flight import FlightRecorder

            self.flight = FlightRecorder(
                ring=self.config.obs_flight_ring,
                out_dir=self.config.obs_flight_dir,
                pod=self.config.pod_identifier,
            )
        self._running = False
        self._failed: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        #: engine-loop lag EMA (OBS_METRICS): host-side gap between the end
        #: of one iteration and the start of the next while work was
        #: pending — the "how far behind the device is the loop" signal.
        self._loop_lag_s: Optional[float] = None
        self._loop_prev_end: Optional[float] = None
        self._loop_had_work = False
        #: /debug/profile serialization (one capture at a time)
        self._profile_mu = threading.Lock()

        # -- cross-pod KV transfer plane (off unless configured) -----------
        # Export requests and imports stage onto the ENGINE LOOP, the only
        # thread allowed to touch page pools (the service/HTTP threads just
        # park on a Future) — same ownership rule as request admission.
        self._transfer_exports: deque[tuple[list[int], Optional[int], Future]] = deque()  # guarded_by: _mu|_work
        self._transfer_imports: deque[tuple[list, str, Future]] = deque()  # guarded_by: _mu|_work
        #: per-endpoint DEALER reuse shared by pull_prefix, async-pull
        #: workers and demotion pushes — repeat traffic to one peer rides
        #: one connected socket (dial/reuse counters on the clients).
        self._transfer_pool = TransferClientPool(
            self._transfer_client_config,
            on_sample=self._observe_transfer_sample,
        )
        self._transfer_service: Optional[KVTransferService] = None
        self.transfer_pulls = 0  # pulls that imported >= 1 block  # guarded_by: _mu|_work
        self.transfer_pull_failures = 0  # fell back to cold  # guarded_by: _mu|_work
        # -- async prefix import (ASYNC_PULL; off = nothing below runs) -----
        #: worker pool for background fetches (built lazily on first use)
        self._pull_pool = None  # guarded_by: _mu|_work
        #: live import jobs, seq_id -> {"cancel": Event, ...} —
        #: abort/resolve flips "cancel" so a fetch landing after the
        #: sequence died installs nothing.
        self._pull_jobs: dict[int, dict] = {}  # guarded_by: _mu|_work
        #: completed imports staged for the engine loop (the only thread
        #: allowed to clear ``Sequence.importing``)
        self._import_dones: deque[Sequence] = deque()  # guarded_by: _mu|_work
        self.async_pulls = 0  # landed >= 1 block  # guarded_by: _mu|_work
        self.async_pull_fallbacks = 0  # -> cold prefill  # guarded_by: _mu|_work
        self.async_pull_canceled = 0  # seq died mid-fetch  # guarded_by: _mu|_work
        # -- disaggregated serving (POD_ROLE; "mixed" = nothing below runs) --
        #: prefill-role scheduler gate: submits whose max_new_tokens the
        #: role clamped to one (ingest stops at the first token)
        self.role_clamped_requests = 0  # guarded_by: _mu|_work
        #: PrefillComplete events published (handoff supply)
        self.prefill_completes_published = 0  # guarded_by: _mu|_work
        # -- routing-quality audit + SLO recording (PR 10; both off by ------
        # -- default = nothing below runs) -----------------------------------
        #: RequestAudit events published (realized-hit ground truth)
        self.audits_published = 0  # guarded_by: _mu|_work
        #: in-process SLO burn-rate recorder (OBS_SLO; None = off). A
        #: malformed spec raises HERE, at construction — a silently
        #: dropped objective would read as a perfectly green SLO.
        self.slo = None
        if self.config.obs_slo.strip():
            from ..obs.slo import SLORecorder, parse_slo_spec, parse_windows

            self.slo = SLORecorder(
                parse_slo_spec(self.config.obs_slo),
                windows_s=parse_windows(self.config.obs_slo_windows),
                # SLO burn crossing is the flight recorder's primary
                # trigger (ISSUE 15): every burn ships its own
                # postmortem. No recorder (OBS_FLIGHT off) = legacy
                # observe path, no burn checks.
                on_burn=(
                    self._on_slo_burn if self.flight is not None else None
                ),
                burn_threshold=(
                    self.config.obs_flight_burn
                    if self.flight is not None
                    else 0.0
                ),
                # Per-tenant burn slices (TENANT_QOS): same observations,
                # sliced by the request's tenant key. Off = the recorder
                # holds no tenant state.
                track_tenants=self.qos is not None,
            )

        # -- fleet self-healing (heartbeats + periodic resync) --------------
        # Digest reads hop onto the engine loop like exports/imports: page
        # bookkeeping is engine-loop-owned state.
        self._digest_requests: deque[Future] = deque()  # guarded_by: _mu|_work
        self.heartbeats_published = 0  # guarded_by: _mu|_work
        self.snapshots_published = 0  # guarded_by: _mu|_work
        self._self_heal_stop = threading.Event()
        self._self_heal_thread: Optional[threading.Thread] = None
        # -- background integrity scrubber (KV_INTEGRITY + interval > 0) ----
        self._scrub_stop = threading.Event()
        self._scrub_thread: Optional[threading.Thread] = None
        # -- remote tier (REMOTE_TIER; off = none of this runs) -------------
        #: demotion pushes from peers staged for the engine loop (the
        #: remote store shares the event stream's ordering)
        self._remote_pushes: deque[tuple[str, list, Future]] = deque()  # guarded_by: _mu|_work
        #: wire-ready payloads parked for the background pusher
        self._demote_queue: deque = deque()  # guarded_by: _mu|_work
        self._demote_thread: Optional[threading.Thread] = None
        self._demote_stop = threading.Event()
        #: last push-ack headroom per peer endpoint (None = never heard;
        #: refreshed on every successful push — the between-heartbeats
        #: feed for target selection)
        self._peer_headroom: dict[str, Optional[int]] = {}  # guarded_by: _mu|_work
        self.demote_pushed_blocks = 0  # guarded_by: _mu|_work
        self.demote_failed_blocks = 0  # fell back to plain eviction  # guarded_by: _mu|_work
        self.demote_dropped = 0  # queue overflow (plain eviction)  # guarded_by: _mu|_work
        self._remote_peers = [
            p.strip() for p in self.config.remote_peers.split(",") if p.strip()
        ]
        if self.config.remote_tier and self._remote_peers:
            self.engine.on_demotion = self._stage_demotions
        # -- fleet controller / live migration (FLEET_CONTROLLER; off = ----
        # -- none of this runs) ---------------------------------------------
        #: sequence freeze+export requests staged for the engine loop:
        #: (request_id, future -> (seq, MigrationPayload) | None)
        self._migrate_freezes: deque[tuple[str, Future]] = deque()  # guarded_by: _mu|_work
        #: migration verdicts staged for the engine loop:
        #: (seq, migrated: bool, future)
        self._migrate_settles: deque[tuple] = deque()  # guarded_by: _mu|_work
        #: inbound migrations staged for the engine loop:
        #: (source_pod, MigrationPayload, future -> (accepted, resumed))
        self._migrations_in: deque[tuple] = deque()  # guarded_by: _mu|_work
        #: continuation futures for migrated-in sequences, request_id ->
        #: Future (resolves with the resumed sequence — the controller's
        #: handle on the moved request)
        self._migrated_in_futures: dict[str, Future] = {}  # guarded_by: _mu|_work
        #: controller read hop: zero-arg callables run on the engine loop
        #: (warm-chain walks, live-request snapshots — engine-owned state)
        self._controller_reads: deque[tuple] = deque()  # guarded_by: _mu|_work
        self.migrations_out = 0  # sequences resumed on a peer  # guarded_by: _mu|_work
        self.migrations_in = 0  # sequences resumed here  # guarded_by: _mu|_work
        self.migration_fallbacks = 0  # -> local cold recompute  # guarded_by: _mu|_work
        if self.config.transfer_endpoint:
            self._transfer_service = KVTransferService(
                TransferServiceConfig(
                    endpoint=self.config.transfer_endpoint,
                    model_name=self.config.model_name,
                    max_blocks=self.config.transfer_max_blocks,
                ),
                handler=self._serve_export,
                tracer=self.tracer,
                # Push acceptance only with the knob on AND a store to
                # hold the blocks; otherwise pushes answer with the same
                # tolerant refusal a legacy service gives.
                push_handler=(
                    self._serve_push
                    if self.config.remote_tier
                    and self.config.remote_store_pages > 0
                    else None
                ),
                # Live-migration acceptance rides the FLEET_CONTROLLER
                # knob the same way: off answers with the tolerant
                # refusal the source treats as "resume locally".
                migrate_handler=(
                    self._serve_migrate
                    if self.config.fleet_controller
                    else None
                ),
            )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        with self._mu:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._engine_loop, name="engine-loop", daemon=True
        )
        self._thread.start()
        if self._transfer_service is not None:
            self._transfer_service.start()
        if self.engine.on_demotion is not None:
            self._demote_stop.clear()
            self._demote_thread = threading.Thread(
                target=self._demote_loop, name="kv-demote", daemon=True
            )
            self._demote_thread.start()
        if self._publisher is not None and (
            self.config.heartbeat_interval_s > 0
            or self.config.resync_interval_s > 0
        ):
            self._self_heal_stop.clear()
            self._self_heal_thread = threading.Thread(
                target=self._self_heal_loop, name="self-heal", daemon=True
            )
            self._self_heal_thread.start()
        if (
            self.integrity is not None
            and self.config.integrity_scrub_interval_s > 0
        ):
            self._scrub_stop.clear()
            self._scrub_thread = threading.Thread(
                target=self._scrub_loop, name="kv-scrub", daemon=True
            )
            self._scrub_thread.start()

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful drain for rolling restarts. Flips the pod to draining
        (new submits raise ``DrainingError`` → 503; ``/healthz`` turns 503
        so k8s readiness agrees; heartbeats advertise ``draining`` so the
        scorer stops picking this pod immediately), lets inflight requests
        finish for up to ``drain_timeout_s``, aborts whatever is left
        (their futures resolve with the partial sequence,
        ``finish_reason="abort"``), then publishes a final
        ``IndexSnapshot`` plus the ``PodDrained`` goodbye — the fleet
        evicts this pod's entries at once instead of waiting out
        ``POD_TTL_S``. The engine loop stays up so ``/stats`` remains
        queryable until the process exits (``shutdown`` still applies).
        Idempotent: concurrent calls wait for the first drain. Returns
        True when every inflight request finished within the budget."""
        with self._work:
            first = not self._draining
            if first:
                self._draining = True
                self.drains_started += 1
        if not first:
            self._drain_done.wait()
            return bool(self._drain_clean)
        self.metrics.observe_drain("started")
        self._flight_event("drain_started")
        log.warning(
            "drain started",
            pod=self.config.pod_identifier,
            timeout_s=timeout_s or self.config.drain_timeout_s,
        )
        # Advertise NOW, not at the next heartbeat tick: every second of
        # stale routing sends this pod prefixes it is about to evict.
        if self.config.heartbeat_interval_s > 0:
            self._publish_heartbeat()
        budget = self.config.drain_timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            with self._mu:
                if self._pending == 0:
                    break
            time.sleep(0.02)
        with self._mu:
            leftover = self._pending
        clean = leftover == 0
        if not clean:
            # Wedged clients / runaway generations past the budget: abort
            # them (pages released, futures resolve with partial output)
            # rather than holding the rolling restart hostage.
            with self._mu:
                self.drain_forced_requests += leftover
            self.metrics.observe_drain("forced", leftover)
            log.error(
                "drain timeout; aborting inflight requests",
                leftover=leftover,
                timeout_s=budget,
            )
            try:
                self.abort(None).result(timeout=30)
            except Exception:
                log.exception("drain abort-all failed")
        # Final goodbye, ordered: the snapshot (engine-loop read, so it
        # reflects post-abort truth) lands before PodDrained evicts the
        # pod — consumers without PodDrained support still get a truthful
        # final view instead of a stale one.
        if self._publisher is not None:
            self.publish_index_snapshot(timeout_s=30.0, wait=True)
            try:
                self._publisher.publish([PodDrained()])
            except Exception:
                log.exception("PodDrained publish failed")
        self._drain_clean = clean
        if clean:
            self.metrics.observe_drain("completed")
        self._flight_event("drain_complete", clean=clean, forced=leftover)
        self._drain_done.set()
        log.warning("drain complete", pod=self.config.pod_identifier, clean=clean)
        return clean

    @property
    def is_draining(self) -> bool:
        with self._mu:
            return self._draining

    @property
    def is_alive(self) -> bool:
        """Running with a healthy engine — the planner's ``dead`` signal
        (one locked read; the fleet view must not see a torn state)."""
        with self._mu:
            return self._running and self._failed is None

    @property
    def queue_depth(self) -> int:
        """Outstanding work: staged + scheduler waiting/prefilling/running
        — the decode tier's ITL-headroom signal for the two-hop planner.
        len() snapshots of engine-owned lists, momentarily stale is fine
        (same contract as admission's depth read)."""
        sch = self.engine.scheduler
        with self._mu:
            staged = len(self._staging)
        return staged + len(sch.waiting) + len(sch.prefilling) + len(sch.running)

    @property
    def prefill_rate(self) -> Optional[float]:
        """Measured prefill tokens/s (the engine's online EMA; None until
        the first prefill) — the planner's prefill-hop speed signal, the
        same number heartbeats/`/stats` carry."""
        return self.engine._prefill_rate

    @property
    def open_breaker_endpoints(self) -> set:
        """Transfer endpoints this pod currently holds an OPEN circuit
        breaker for — a pull through them would skip straight to cold.
        The disagg planner view aggregates these across the fleet to keep
        suspect exporters out of the prefill hop."""
        return {
            endpoint
            for endpoint, client in self._transfer_pool.clients().items()
            if client.breaker is not None and client.breaker.state == "open"
        }

    def shutdown(self) -> None:
        self._self_heal_stop.set()
        if self._self_heal_thread is not None:
            self._self_heal_thread.join(timeout=5)
            self._self_heal_thread = None
        self._scrub_stop.set()
        if self._scrub_thread is not None:
            self._scrub_thread.join(timeout=5)
            self._scrub_thread = None
        self._demote_stop.set()
        if self._demote_thread is not None:
            self._demote_thread.join(timeout=10)
            self._demote_thread = None
        if self._transfer_service is not None:
            self._transfer_service.shutdown()
        with self._mu:
            pool, self._pull_pool = self._pull_pool, None
            for job in self._pull_jobs.values():
                job["cancel"].set()
        if pool is not None:
            # Workers unwind on their own (fetch timeouts are bounded and
            # submit_import fails fast once _running flips); don't block
            # shutdown on a slow peer.
            pool.shutdown(wait=False)
        with self._work:
            self._running = False
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self._fail_outstanding(RuntimeError("pod server shut down"))
        self._transfer_pool.close_all()
        if self._publisher is not None:
            self._publisher.close()

    def _fail_outstanding(self, exc: BaseException) -> None:
        with self._mu:
            staged = list(self._staging)
            self._staging.clear()
            aborts = list(self._aborts)
            self._aborts.clear()
            transfers = (
                list(self._transfer_exports)
                + list(self._transfer_imports)
                + list(self._remote_pushes)
                + list(self._migrate_freezes)
                + list(self._migrate_settles)
                + list(self._migrations_in)
                + list(self._controller_reads)
                + [(fut,) for fut in self._digest_requests]
            )
            self._transfer_exports.clear()
            self._transfer_imports.clear()
            self._remote_pushes.clear()
            self._migrate_freezes.clear()
            self._migrate_settles.clear()
            self._migrations_in.clear()
            self._controller_reads.clear()
            self._demote_queue.clear()
            self._digest_requests.clear()
            migrated_futs = list(self._migrated_in_futures.values())
            self._migrated_in_futures.clear()
            self._import_dones.clear()
            jobs = list(self._pull_jobs.values())
            self._pull_jobs.clear()
            self._pending = 0
            self._pending_tokens = 0
            if self.qos is not None:
                # Per-tenant budgets mirror the shared counters: nothing
                # outstanding survives an engine failure.
                self.qos.reset_pending()
        for job in jobs:
            job["cancel"].set()
        for _, _, _, _, fut, span, _, _, _ in staged:
            span.set_attr("error", str(exc))
            span.end()
            if not fut.done():
                fut.set_exception(exc)
        for _, afut in aborts:
            if not afut.done():
                afut.set_result(False)  # nothing left alive to abort
        for item in transfers:
            fut = item[-1]
            if not fut.done():
                fut.set_exception(exc)
        for fut in list(self._futures.values()) + migrated_futs:
            if not fut.done():
                fut.set_exception(exc)
        self._futures.clear()

    def _forget_pending(self, n_tokens: int, tenant: str = "") -> None:
        """Release one request's admission accounting (engine loop only).
        ``tenant`` releases the same request's per-tenant budget when
        TENANT_QOS is on ("" = untenanted, nothing to release)."""
        with self._mu:
            self._pending = max(self._pending - 1, 0)
            self._pending_tokens = max(self._pending_tokens - n_tokens, 0)
            if self.qos is not None and tenant:
                self.qos.on_resolved(tenant, n_tokens)

    def _resolve(self, seq: Sequence) -> None:
        """Resolve a finished/aborted sequence's future and release its
        admission accounting (engine loop only)."""
        with self._mu:
            job = self._pull_jobs.pop(seq.seq_id, None)
        if job is not None:
            # Aborted/shed while its async import was in flight: the fetch
            # cannot be recalled off the wire, but cancel ensures the
            # worker installs nothing when it lands — pages stay at
            # baseline (the PR 4 abort-accounting contract, extended to
            # the importing state).
            job["cancel"].set()
        self.metrics.observe_finished(seq)
        if self.slo is not None:
            # Same measurements the latency histograms observe (the
            # shared Sequence.ttft/mean_itl definitions), so the burn
            # rate stays a faithful in-process cross-check of them.
            self.slo.observe(seq.ttft, seq.mean_itl, tenant=seq.tenant)
        if seq.trace_span is not None:
            self._emit_request_spans(seq)
        if (
            self.config.obs_audit
            and self._publisher is not None
            and seq.prefill_start_time is not None
        ):
            # Realized-hit ground truth for the route audit: how many
            # prompt blocks this pod's prefix cache actually served at
            # first prefill. Requests that never reached prefill
            # (shed/aborted while queued) realized nothing measurable —
            # reporting 0 for them would charge the scorer with misses
            # the routing never caused. Failures are swallowed like
            # heartbeats: auditing must never fail a request.
            try:
                self._publisher.publish(
                    [
                        RequestAudit(
                            request_id=seq.request_id or "",
                            realized_blocks=(
                                seq.num_cached_prompt
                                // max(
                                    self.config.engine.block_manager.page_size,
                                    1,
                                )
                            ),
                        )
                    ]
                )
                with self._mu:
                    self.audits_published += 1
            except Exception:
                log.exception("RequestAudit publish failed")
        if (
            self.config.pod_role == "prefill"
            and self._publisher is not None
            and seq.finish_reason not in ("abort", "deadline")
            and seq.num_generated >= 1
        ):
            # Trailing-append handoff announcement: the ingest finished and
            # the chain is registered + exportable. Failures are swallowed
            # like heartbeats — the serving-plane handoff (which carries
            # the first token) does not depend on the event landing.
            try:
                self._publisher.publish(
                    [
                        PrefillComplete(
                            request_id=seq.request_id or "",
                            num_blocks=seq.num_registered_pages,
                        )
                    ]
                )
                with self._mu:
                    self.prefill_completes_published += 1
            except Exception:
                log.exception("PrefillComplete publish failed")
        fut = self._futures.pop(seq.seq_id, None)
        if fut is not None:
            self._forget_pending(seq.user_prompt_len, seq.tenant)
            if not fut.done():
                fut.set_result(seq)

    def _emit_request_spans(self, seq: Sequence) -> None:
        """End the request span and reconstruct its queue/prefill/decode
        children from the timestamps the engine already stamps — zero
        per-token tracing cost; the whole decomposition is derived once at
        request completion."""
        span, seq.trace_span = seq.trace_span, None
        if span.context is None:  # noop span (tracing off)
            return
        end = seq.finish_time if seq.finish_time is not None else time.monotonic()
        if seq.prefill_start_time is not None:
            self.tracer.record_span(
                "pod.queue", span, span.start_mono, seq.prefill_start_time
            )
            prefill_end = (
                seq.first_token_time
                if seq.first_token_time is not None
                else end
            )
            self.tracer.record_span(
                "pod.prefill",
                span,
                seq.prefill_start_time,
                prefill_end,
                attrs={
                    "cached_prompt_tokens": seq.num_cached_prompt,
                    "prompt_tokens": seq.user_prompt_len,
                },
            )
            if seq.first_token_time is not None and seq.num_generated > 1:
                self.tracer.record_span(
                    "pod.decode",
                    span,
                    seq.first_token_time,
                    end,
                    attrs={"generated_tokens": seq.num_generated},
                )
        else:
            # Never reached prefill (shed/aborted while queued): the whole
            # life was queueing.
            self.tracer.record_span("pod.queue", span, span.start_mono, end)
        outcome, finish = _ServingMetrics.request_labels(seq)
        span.set_attr("outcome", outcome)
        span.set_attr("finish", finish)
        span.set_attr("generated_tokens", seq.num_generated)
        if seq.error:
            span.set_attr("error", seq.error)
        span.end(end_mono=end)

    # -- flight recorder (OBS_FLIGHT) ----------------------------------------
    def _make_tenant_mrc(self):
        """Factory for one tenant's reuse-distance estimator (TENANT_QOS
        + OBS_LIFECYCLE): same sampling knobs as the global curve, but no
        ``on_distance`` hook — the global estimator already feeds the
        reuse-distance histogram, and a second feed would double-count
        every sampled access."""
        from ..obs.lifecycle import ReuseDistanceEstimator

        return ReuseDistanceEstimator(
            sample_rate=self.config.obs_mrc_sample,
            max_tracked=self.config.obs_mrc_tracked,
        )

    def _on_slo_burn(self, objective: str, window: str, rate: float) -> None:
        """SLORecorder burn-crossing callback: the flight recorder's
        primary trigger. The burn sample itself rides the timeline, so a
        dump always contains what tripped it."""
        flight = self.flight
        if flight is None:
            return
        flight.record_event(
            "slo_burn", objective=objective, window=window,
            rate=round(rate, 4),
        )
        flight.trigger(
            "slo_burn", objective=objective, window=window,
            rate=round(rate, 4),
        )

    def _flight_event(self, kind: str, **attrs) -> None:
        """Record a fleet event on the flight ring (noop with the knob
        off) — breaker transitions, resyncs, drains, sheds/429s."""
        if self.flight is not None:
            self.flight.record_event(kind, **attrs)

    def _engine_loop(self) -> None:
        try:
            while True:
                with self._work:
                    # has_ready_work, not has_work: an engine whose only
                    # work is waiting on an in-flight async import parks
                    # here (woken by the import-done notify) instead of
                    # busy-spinning no-op steps against the wire.
                    while self._running and not (
                        self._staging
                        or self._aborts
                        or self._transfer_exports
                        or self._transfer_imports
                        or self._remote_pushes
                        or self._digest_requests
                        or self._import_dones
                        or self._migrate_freezes
                        or self._migrate_settles
                        or self._migrations_in
                        or self._controller_reads
                        or self.engine.has_ready_work
                    ):
                        self._work.wait(timeout=0.1)
                    if not self._running:
                        return
                    staged = list(self._staging)
                    self._staging.clear()
                    aborts = list(self._aborts)
                    self._aborts.clear()
                    exports = list(self._transfer_exports)
                    self._transfer_exports.clear()
                    imports = list(self._transfer_imports)
                    self._transfer_imports.clear()
                    pushes = list(self._remote_pushes)
                    self._remote_pushes.clear()
                    digests = list(self._digest_requests)
                    self._digest_requests.clear()
                    import_dones = list(self._import_dones)
                    self._import_dones.clear()
                    freezes = list(self._migrate_freezes)
                    self._migrate_freezes.clear()
                    settles = list(self._migrate_settles)
                    self._migrate_settles.clear()
                    migrations_in = list(self._migrations_in)
                    self._migrations_in.clear()
                    controller_reads = list(self._controller_reads)
                    self._controller_reads.clear()
                # Engine state is owned by this thread — no lock held while
                # admitting or stepping (device compute can take a while).
                # Imports land before admissions so a request staged with
                # its pull (pull_prefix -> submit) sees the warm pages.
                for fut in digests:
                    try:
                        # Engine-level digest: every tier incl. the remote
                        # store (a resync must not wipe demoted entries
                        # this pod holds for the fleet).
                        fut.set_result(self.engine.block_digest())
                    except Exception as e:
                        fut.set_exception(e)
                for blocks, src_pod, fut in imports:
                    try:
                        fut.set_result(
                            self.engine.import_kv_blocks(
                                blocks, source_pod=src_pod
                            )
                        )
                    except Exception as e:
                        fut.set_exception(e)
                for source_pod, blocks, fut in pushes:
                    try:
                        fut.set_result(
                            self.engine.accept_remote_blocks(source_pod, blocks)
                        )
                    except Exception as e:
                        fut.set_exception(e)
                for hashes, max_blocks, fut in exports:
                    try:
                        fut.set_result(
                            self.engine.export_kv_blocks(hashes, max_blocks)
                        )
                    except Exception as e:
                        fut.set_exception(e)
                # Import completions clear `importing` HERE (the flag is
                # scheduler-read state, engine-loop-owned): the sequence
                # becomes admittable the very step its warm pages are
                # committed.
                for seq in import_dones:
                    seq.importing = False
                # Migration ops in causal order: freezes (park + export)
                # before settles (commit/rollback a previous freeze) before
                # inbound admissions — all engine-loop-owned state.
                for rid, fut in freezes:
                    try:
                        fut.set_result(self._freeze_for_migration(rid))
                    except Exception as e:
                        fut.set_exception(e)
                for seq, migrated, fut in settles:
                    try:
                        fut.set_result(self._settle_migration(seq, migrated))
                    except Exception as e:
                        fut.set_exception(e)
                for source_pod, migration, fut in migrations_in:
                    try:
                        fut.set_result(
                            self._admit_migration(source_pod, migration)
                        )
                    except Exception as e:
                        fut.set_exception(e)
                for call, fut in controller_reads:
                    try:
                        fut.set_result(call())
                    except Exception as e:
                        fut.set_exception(e)
                for (
                    tokens, sampling, deadline, rid, fut, span, action,
                    pull, tenant,
                ) in staged:
                    try:
                        if self.qos is not None:
                            # The policy's class/weight ride the Sequence
                            # into the scheduler and block manager.
                            pol = self.qos.policy(tenant)
                            seq = self.engine.add_request(
                                tokens, sampling, request_id=rid,
                                deadline=deadline, tenant=tenant,
                                priority=pol.priority,
                                qos_weight=pol.weight,
                            )
                        else:
                            seq = self.engine.add_request(
                                tokens, sampling, request_id=rid,
                                deadline=deadline,
                            )
                    except ValueError as e:
                        self._forget_pending(len(tokens), tenant)
                        span.set_attr("error", str(e))
                        span.end()
                        # done() guard: a disconnected client may have
                        # CANCELLED this future already; set_exception on a
                        # cancelled future raises InvalidStateError — which
                        # would kill the engine loop and fail the pod.
                        if not fut.done():
                            fut.set_exception(e)
                        continue
                    seq.trace_span = span if span.context is not None else None
                    seq.route_action = action
                    self._futures[seq.seq_id] = fut
                    if pull is not None:
                        self._start_async_pull(seq, pull, span)
                # Aborts AFTER admissions: a submit-then-abort staged in
                # the same drain cycle must find its sequence in the engine.
                for rid, afut in aborts:
                    try:
                        seqs = (
                            self.engine.abort_all()
                            if rid is None
                            else list(filter(None, [self.engine.abort(rid)]))
                        )
                    except Exception as e:
                        afut.set_exception(e)
                        continue
                    for seq in seqs:
                        self._resolve(seq)
                    afut.set_result(bool(seqs))
                if aborts:
                    # An idle engine may not step again for a while; the
                    # abort counters must not lag until it does.
                    self.metrics.sync_lifecycle_stats(
                        self.engine.lifecycle_stats
                    )
                if self.engine.has_ready_work:
                    obs = self.config.obs_metrics
                    if obs:
                        t_start = time.perf_counter()
                        if self._loop_had_work and self._loop_prev_end is not None:
                            # Lag only counts gaps while work was pending at
                            # the previous iteration's end — idle waits are
                            # not loop lag.
                            sample = max(t_start - self._loop_prev_end, 0.0)
                            self._loop_lag_s = (
                                sample
                                if self._loop_lag_s is None
                                else 0.7 * self._loop_lag_s + 0.3 * sample
                            )
                    finished = self.engine.step()
                    if self.flight is not None:
                        # Per-step telemetry onto the flight ring: phase
                        # deltas (engine step timing is forced on by the
                        # knob) + the occupancy/free-page/loop-lag gauges.
                        sch_f = self.engine.scheduler
                        self.flight.record_step(
                            self.engine.step_stats,
                            occupancy=len(sch_f.running)
                            / max(self.config.engine.decode_batch_size, 1),
                            free_pages=self.engine.block_manager.num_free,
                            loop_lag_s=self._loop_lag_s,
                        )
                    lp = self.engine.last_prefetch
                    if lp is not None:
                        # Host-tier bring-back ran ahead of the scheduler
                        # this step: one span + one histogram sample per
                        # prefetch round (noop with both OBS_* knobs off).
                        self.engine.last_prefetch = None
                        pages, t0, t1 = lp
                        self.metrics.observe_host_prefetch(t1 - t0)
                        self.tracer.record_span(
                            "pod.host_bringback",
                            None,
                            t0,
                            t1,
                            attrs={
                                "pages": pages,
                                "pod": self.config.pod_identifier,
                            },
                        )
                    if (
                        self.transfer_cost_model is not None
                        and self.engine._prefill_rate
                    ):
                        # Prefill-rate feed for the transfer decision: the
                        # engine's own online EMA, re-pinned per step.
                        self.transfer_cost_model.seed_rates(
                            prefill_tokens_s=self.engine._prefill_rate
                        )
                    self.metrics.sync_spec_stats(self.engine.spec_stats)
                    self.metrics.sync_lifecycle_stats(
                        self.engine.lifecycle_stats
                    )
                    if self.integrity is not None:
                        istats = self.integrity.stats
                        q = istats["quarantined"]
                        if q > self._integrity_quarantine_seen:
                            # A corrupt block surfaced this step: preserve
                            # the forensic window around it (step ring,
                            # recent lifecycle) before it scrolls away.
                            delta = q - self._integrity_quarantine_seen
                            self._integrity_quarantine_seen = q
                            self._flight_event(
                                "kv_quarantine", blocks=delta
                            )
                            if self.flight is not None:
                                self.flight.trigger(
                                    "quarantine", blocks=delta
                                )
                        self.metrics.sync_integrity_stats(istats)
                    if obs:
                        self._loop_prev_end = time.perf_counter()
                        self._loop_had_work = self.engine.has_ready_work
                        sch = self.engine.scheduler
                        self.metrics.sync_step_stats(
                            self.engine.step_stats, self._loop_lag_s
                        )
                        self.metrics.set_engine_gauges(
                            len(sch.running)
                            / max(self.config.engine.decode_batch_size, 1),
                            self.engine.block_manager.num_free,
                        )
                        if self.config.engine.block_manager.host_pages:
                            bm = self.engine.block_manager
                            self.metrics.sync_host_stats(
                                bm.host_stats, bm.num_host_cached_pages
                            )
                    for seq in finished:
                        self._resolve(seq)
        except Exception as e:  # engine wedged: fail fast and visibly
            log.error("engine loop died", error=repr(e))
            self._failed = f"{type(e).__name__}: {e}"
            self._fail_outstanding(RuntimeError(f"engine failed: {self._failed}"))

    # -- fleet self-healing --------------------------------------------------
    def _self_heal_loop(self) -> None:
        """Heartbeat / periodic-resync publisher. Runs only when a knob is
        enabled; all failures are swallowed — self-healing must never take
        a serving pod down."""
        hb = self.config.heartbeat_interval_s
        rs = self.config.resync_interval_s
        tick = min(x for x in (hb, rs) if x > 0)
        next_hb = 0.0 if hb > 0 else float("inf")
        # First snapshot goes out after one full interval: at process start
        # the digest is empty and the normal event stream covers warm-up.
        import time as _time

        now = _time.monotonic()
        next_rs = now + rs if rs > 0 else float("inf")
        while not self._self_heal_stop.wait(min(tick, 0.25)):
            now = _time.monotonic()
            if now >= next_hb:
                next_hb = now + hb
                self._publish_heartbeat()
            if now >= next_rs:
                next_rs = now + rs
                # Fire-and-forget: the snapshot publishes from the engine
                # loop when the digest resolves. Blocking here would starve
                # heartbeats behind a long device step — a slow resync must
                # never make a live pod look dead.
                self.publish_index_snapshot(wait=False)

    def _scrub_loop(self) -> None:
        """Background integrity scrubber (KV_INTEGRITY=1 +
        ``INTEGRITY_SCRUB_INTERVAL_S`` > 0): every interval, hop onto the
        engine loop and re-digest a bounded batch of resident host-tier
        pages. Latent rot (a cosmic-ray flip in a page nothing is reading)
        surfaces within ``pages / rate`` instead of at restore time — or
        never, if the chain dies cold. Failures are swallowed: the
        scrubber must never take a serving pod down."""
        interval = self.config.integrity_scrub_interval_s
        while not self._scrub_stop.wait(interval):
            try:
                self._controller_read(
                    lambda: self.engine.scrub_host_pages(
                        self.config.integrity_scrub_pages
                    )
                )
            except Exception as e:
                log.warning("integrity scrub pass failed", error=repr(e))

    def _publish_heartbeat(self) -> None:
        if self._publisher is None:
            return
        # Flag read under the lock; the (bounded-blocking) publish stays
        # outside it so a retrying socket never convoys submit/drain.
        with self._mu:
            draining = self._draining
        try:
            self._publisher.publish(
                [
                    Heartbeat(
                        dropped_batches=getattr(
                            self._publisher, "dropped_batches", 0
                        ),
                        draining=draining,
                        # Role rides only on non-mixed pods: a mixed pod's
                        # heartbeat bytes stay bit-identical legacy.
                        role=(
                            self.config.pod_role
                            if self.config.pod_role != "mixed"
                            else None
                        ),
                        # Remote-store headroom advertisement: None with
                        # REMOTE_TIER off — heartbeat bytes stay legacy.
                        headroom=self.engine.remote_headroom,
                    )
                ]
            )
            with self._mu:
                self.heartbeats_published += 1
        except Exception:
            log.exception("heartbeat publish failed")

    def publish_index_snapshot(
        self, timeout_s: float = 30.0, wait: bool = True
    ) -> bool:
        """Emit an ``IndexSnapshot`` resync. The digest is read AND
        published on the engine loop (digest-future callback), so no
        ``BlockStored``/``BlockRemoved`` the loop emits can interleave
        between reading the digest and shipping it — a stale snapshot
        would silently wipe the interleaved event from the index. Callable
        on demand (e.g. after the indexer flags this pod suspect) and
        periodically via ``RESYNC_INTERVAL_S`` (which passes ``wait=False``
        so a slow engine step can't starve heartbeats)."""
        if self._publisher is None:
            return False
        done: Future = Future()

        def on_digest(f: Future) -> None:
            # Runs where the future is settled: the engine loop (ordered
            # with the event stream) or the failure path.
            try:
                digest = f.result()
                self._publisher.publish([IndexSnapshot(blocks_by_medium=digest)])
                with self._mu:
                    self.snapshots_published += 1
                if self.flight is not None:
                    # A resync is a repair event worth a postmortem: the
                    # timeline leading up to it explains what the index
                    # had to be repaired FROM (trigger dumps are
                    # rate-limited, so a periodic-resync cadence costs
                    # one file per window, not one per tick).
                    self.flight.record_event(
                        "resync", blocks={m: len(h) for m, h in digest.items()}
                    )
                    self.flight.trigger("resync")
                done.set_result(True)
            except Exception:
                log.exception("index snapshot publish failed")
                done.set_result(False)

        fut: Future = Future()
        fut.add_done_callback(on_digest)
        with self._work:
            if not self._running or self._failed is not None:
                return False
            self._digest_requests.append(fut)
            self._work.notify()
        if not wait:
            return True
        try:
            return done.result(timeout=timeout_s)
        except Exception:
            log.exception("index snapshot publish timed out")
            return False

    # -- cross-pod KV transfer ----------------------------------------------
    def _observe_transfer_sample(self, n_bytes: int, seconds: float) -> None:
        """KVTransferClient.on_sample → the router's cost model (when this
        pod participates in transfer-aware routing)."""
        if self.transfer_cost_model is not None:
            self.transfer_cost_model.observe_transfer(n_bytes, seconds)

    def _serve_export(self, hashes: list[int], max_blocks: int) -> list:
        """KVTransferService handler (service thread): hop onto the engine
        loop — the only thread allowed to read page pools — and wait."""
        fut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None:
                return []
            self._transfer_exports.append((hashes, max_blocks, fut))
            self._work.notify()
        return fut.result(timeout=max(self.config.transfer_timeout_s * 3, 30.0))

    def submit_import(self, blocks: list, source_pod: str = "") -> Future:
        """Stage fetched blocks for installation on the engine loop; the
        Future resolves to the number of blocks imported. ``source_pod``
        (the peer endpoint the blocks were pulled from) contextualizes
        integrity rejects and their ``BadBlock`` revocations."""
        fut: Future = Future()
        with self._work:
            if self._failed is not None:
                raise RuntimeError(f"engine failed: {self._failed}")
            if not self._running:
                raise RuntimeError("pod server not running")
            self._transfer_imports.append((blocks, source_pod, fut))
            self._work.notify()
        return fut

    def _transfer_client_config(self, endpoint: str) -> TransferClientConfig:
        """Pool factory: per-peer client config (timeouts + breaker)."""
        return TransferClientConfig(
            endpoint=endpoint,
            timeout_s=self.config.transfer_timeout_s,
            breaker_failures=self.config.transfer_breaker_failures,
            breaker_backoff_s=self.config.transfer_breaker_backoff_s,
            breaker_backoff_max_s=self.config.transfer_breaker_backoff_max_s,
        )

    def _get_client(self, endpoint: str) -> Optional[KVTransferClient]:
        """Pooled per-peer transfer client (one connected DEALER per
        endpoint, shared by pulls and demotion pushes). None when the pod
        is shutting down — a client created after the shutdown sweep
        would leak its socket."""
        with self._mu:  # races shutdown's running flip
            if not self._running:
                return None
        client = self._transfer_pool.get(endpoint)
        if (
            client is not None
            and self.flight is not None
            and client.breaker is not None
            and client.breaker.on_transition is None
        ):
            # Breaker OPEN is a flight trigger (a dead peer explains the
            # burn that usually follows); transitions also ride the
            # timeline as fleet events. Wired once per pooled client.
            def _breaker_cb(state: str, endpoint: str = endpoint) -> None:
                flight = self.flight
                if flight is None:
                    return
                flight.record_event("breaker", endpoint=endpoint, state=state)
                if state == "open":
                    flight.trigger("breaker_open", endpoint=endpoint)

            client.breaker.on_transition = _breaker_cb
        return client

    # -- remote-tier demotion (REMOTE_TIER) ---------------------------------
    def _serve_push(self, source_pod: str, blocks: list) -> tuple[int, int]:
        """KVTransferService push handler (service thread): hop onto the
        engine loop — the remote store shares the event stream's ordering
        — and wait for the commit verdict."""
        fut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None:
                return 0, 0
            self._remote_pushes.append((source_pod, blocks, fut))
            self._work.notify()
        return fut.result(timeout=max(self.config.transfer_timeout_s * 3, 30.0))

    # -- live sequence migration (FLEET_CONTROLLER) --------------------------
    def migrate_out(
        self,
        request_id: str,
        target_endpoint: str,
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Live-migrate one in-flight request to the pod serving
        ``target_endpoint`` (its transfer endpoint). The engine loop
        freezes the sequence preemption-style (generated tokens fold into
        the prompt; registered pages survive in the prefix cache) and
        exports its KV chain; this thread ships decode state + chain over
        the transfer fabric; on the target's ``resumed`` ack the local
        half finishes with ``finish_reason="migrated"`` (its submit
        future resolves with the partial sequence — the target's
        continuation carries the rest). ANY failure — dead target,
        refusal, timeout, undecodable ack — rolls back to local
        recompute: the sequence re-enters scheduling exactly as a
        preemption would, pages back to baseline. Returns True only when
        the target resumed the sequence. ``FLEET_CONTROLLER`` off =
        False without touching the engine (bit-identical legacy)."""
        if not self.config.fleet_controller:
            return False
        wait = max(self.config.transfer_timeout_s * 3, 30.0)
        fut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None:
                return False
            self._migrate_freezes.append((request_id, fut))
            self._work.notify()
        try:
            frozen = fut.result(timeout=wait)
        except Exception:
            log.exception("migration freeze failed", request=request_id)
            return False
        if frozen is None:
            return False  # not live here (finished, unknown, or importing)
        seq, payload = frozen
        resumed = False
        client = self._get_client(target_endpoint)
        if client is not None:
            try:
                _accepted, resumed = client.migrate(
                    self.config.model_name,
                    self.config.pod_identifier,
                    payload,
                    timeout_s=timeout_s,
                )
            except TransferError as e:
                log.warning(
                    "migration transfer failed; resuming locally",
                    request=request_id,
                    target=target_endpoint,
                    error=str(e),
                )
            except Exception:
                log.exception("migration transfer failed; resuming locally")
        sfut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None:
                return False
            self._migrate_settles.append((seq, resumed, sfut))
            self._work.notify()
        try:
            ok = bool(sfut.result(timeout=wait))
        except Exception:
            log.exception("migration settle failed", request=request_id)
            return False
        with self._mu:
            if ok:
                self.migrations_out += 1
            else:
                self.migration_fallbacks += 1
        self._flight_event(
            "migration",
            direction="out",
            request=request_id,
            target=target_endpoint,
            resumed=ok,
            blocks=len(payload.blocks),
            tokens=len(payload.token_ids),
        )
        return ok

    def migrated_future(self, request_id: str) -> Optional[Future]:
        """The continuation future of a request migrated INTO this pod
        (resolves with the resumed sequence, whose ``generated_tokens``
        is the request's full user-visible output). None when no such
        migration was admitted. Entries are retained for the pod's
        lifetime — a migration is a rare, operator-scale event."""
        with self._mu:
            return self._migrated_in_futures.get(request_id)

    def purge_bad_blocks(
        self, holder: str, block_hashes: list, medium=None
    ) -> int:
        """Fleet-revocation consumer (ISSUE 19): a ``BadBlock`` published
        by ``holder`` reached the control plane; destroy any replica
        copies this pod's remote store still holds for those hashes (the
        wire-ready bytes a demotion pushed here — the only copies that
        share provenance with the corrupt ones; locally computed pages
        are independent and stay). Engine-loop hop, since the store is
        engine-thread-owned. Returns blocks dropped; 0 when the holder is
        this pod (its copy died at quarantine time) or there is no store.
        Input-driven, not knob-gated — a legacy pod honors revocations
        too."""
        if (
            self.engine.remote_store is None
            or not block_hashes
            or holder == self.config.pod_identifier
        ):
            return 0
        try:
            return (
                self._controller_read(
                    lambda: self.engine.remote_store.purge(block_hashes)
                )
                or 0
            )
        except Exception as e:
            log.warning("bad-block purge failed", error=repr(e))
            return 0

    def _controller_read(self, call):
        """Run a zero-arg callable on the engine loop and wait — the fleet
        controller's read hop into engine-owned state (scheduler deques,
        the prefix cache). Returns None when the pod is down."""
        fut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None:
                return None
            self._controller_reads.append((call, fut))
            self._work.notify()
        return fut.result(timeout=max(self.config.transfer_timeout_s * 3, 30.0))

    def live_requests(self) -> list[str]:
        """Request ids of every live (admitted, unfinished) sequence — the
        fleet controller's scale-down migration plan, snapshotted on the
        engine loop so it can never tear against a step."""

        def read() -> list[str]:
            sch = self.engine.scheduler
            return [
                seq.request_id
                for bucket in (sch.waiting, sch.prefilling, sch.running)
                for seq in bucket
                if not seq.is_finished()
            ]

        return self._controller_read(read) or []

    def warm_chains(self, limit: int) -> list[list[int]]:
        """Chain-ordered block-hash lists of this pod's hottest resident
        prefix chains (longest first) — the donor side of fleet scale-up
        warm revival. Empty with ``FLEET_CONTROLLER`` off."""
        if not self.config.fleet_controller or limit <= 0:
            return []
        return (
            self._controller_read(
                lambda: self.engine.block_manager.hot_chains(limit)
            )
            or []
        )

    def revive_chain(
        self,
        chain_hashes: list[int],
        source_endpoint: str,
        timeout_s: Optional[float] = None,
    ) -> int:
        """Warm-set revival on fleet scale-up: pull one chain (hashes in
        chain order, from a donor's ``warm_chains``) over the transfer
        fabric and commit it locally. Returns blocks imported; 0 on ANY
        failure — revival is an optimization, the new pod just starts
        colder. 0 with ``FLEET_CONTROLLER`` off."""
        if not self.config.fleet_controller or not chain_hashes:
            return 0
        client = self._get_client(source_endpoint)
        if client is None:
            return 0
        try:
            blocks, _complete = client.fetch(
                self.config.model_name,
                list(chain_hashes),
                self.config.transfer_max_blocks,
                timeout_s=timeout_s,
            )
            if not blocks:
                return 0
            return self.submit_import(blocks, source_pod=source_endpoint).result(
                timeout=timeout_s or max(self.config.transfer_timeout_s * 3, 30.0)
            )
        except (TransferError, RuntimeError, FuturesTimeout) as e:
            log.warning(
                "warm revival pull failed; starting cold",
                source=source_endpoint,
                error=repr(e),
            )
            return 0

    def _freeze_for_migration(self, request_id: str):
        """Engine-loop half of ``migrate_out``: freeze the sequence and
        build the wire payload (decode state + exported KV chain) in ONE
        loop cycle, so no eviction can interleave between the freeze
        releasing the pages and the export reading them."""
        frozen = self.engine.freeze_for_migration(request_id)
        if frozen is None:
            return None
        seq, hashes = frozen
        blocks = self.engine.export_kv_blocks(hashes) if hashes else []
        payload = MigrationPayload(
            request_id=request_id,
            token_ids=list(seq.prompt_tokens),  # post-fold: full history
            user_prompt_len=seq.user_prompt_len,
            num_generated=seq.num_generated,
            max_new_tokens=seq.sampling.max_new_tokens,
            temperature=seq.sampling.temperature,
            top_k=seq.sampling.top_k,
            top_p=seq.sampling.top_p,
            stop_token_ids=tuple(seq.sampling.stop_token_ids),
            deadline_remaining_s=(
                max(seq.deadline - time.monotonic(), 0.0)
                if seq.deadline is not None
                else None
            ),
            blocks=blocks,
        )
        return seq, payload

    def _settle_migration(self, seq: Sequence, migrated: bool) -> bool:
        """Engine-loop half of ``migrate_out``'s verdict: commit (finish
        the local half; its future resolves) or roll back (clear
        ``importing`` so the scheduler re-admits the folded sequence —
        cold recompute at worst)."""
        if seq.is_finished():
            # Aborted/shed while the wire transfer ran (e.g. the drain
            # hammer): its future already resolved; nothing to settle.
            return False
        if not migrated:
            self.engine.cancel_migration(seq)
            return False
        self.engine.finish_migrated(seq)
        self._resolve(seq)
        return True

    def _serve_migrate(self, source_pod: str, migration) -> tuple[int, bool]:
        """KVTransferService migrate handler (service thread): hop onto
        the engine loop — install the chain, admit the continuation
        through the ``importing`` state — and wait for the verdict. A
        draining pod refuses (``resumed=False``): the source resumes
        locally rather than migrating onto a pod about to disappear."""
        fut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None or self._draining:
                return 0, False
            self._migrations_in.append((source_pod, migration, fut))
            self._work.notify()
        return fut.result(timeout=max(self.config.transfer_timeout_s * 3, 30.0))

    def _admit_migration(self, source_pod: str, migration) -> tuple[int, bool]:
        """Engine-loop half of an inbound migration: install the shipped
        chain, then admit the continuation — the full token history as
        the prompt (exactly the ``fold_for_preemption`` representation,
        so the warm prefill cache-hits the imported pages and greedy
        decode resumes token-identically) — entering through the PR 7
        ``importing`` state, cleared next cycle."""
        installed = 0
        if migration.blocks:
            try:
                installed = self.engine.import_kv_blocks(
                    migration.blocks, source_pod=source_pod
                )
            except Exception:
                # Geometry/chain verification failures already degrade
                # inside import_kv_blocks; anything past that just means
                # the continuation prefills colder.
                log.exception("migration import failed; continuation recomputes")
        sampling = SamplingParams(
            max_new_tokens=migration.max_new_tokens,
            temperature=migration.temperature,
            top_k=migration.top_k,
            top_p=migration.top_p,
            stop_token_ids=tuple(migration.stop_token_ids),
        )
        try:
            seq = self.engine.add_request(
                list(migration.token_ids),
                sampling,
                request_id=migration.request_id,
                deadline=(
                    time.monotonic() + migration.deadline_remaining_s
                    if migration.deadline_remaining_s is not None
                    else None
                ),
            )
        except ValueError as e:
            log.warning(
                "refusing migration; source resumes locally",
                request=migration.request_id,
                error=str(e),
            )
            return installed, False
        # Continue the source's bookkeeping: with generated tokens folded
        # into the prompt, ``generated_tokens`` and the max_new_tokens /
        # stop-token conditions line up exactly with an unmigrated run.
        seq.user_prompt_len = migration.user_prompt_len
        seq.num_generated = migration.num_generated
        seq.importing = True
        fut: Future = Future()
        fut.request_id = migration.request_id
        self._futures[seq.seq_id] = fut
        with self._work:
            self._pending += 1
            # _resolve releases user_prompt_len tokens; mirror it here.
            self._pending_tokens += seq.user_prompt_len
            self._migrated_in_futures[migration.request_id] = fut
            self.migrations_in += 1
            self._import_dones.append(seq)
            self._work.notify()
        self._flight_event(
            "migration",
            direction="in",
            source=source_pod,
            request=migration.request_id,
            blocks=installed,
            tokens=len(migration.token_ids),
        )
        return installed, True

    def _stage_demotions(self, payloads: list) -> None:
        """``Engine.on_demotion`` sink (engine loop): park wire-ready
        payloads for the background pusher. Bounded — overflow drops the
        OLDEST (coldest) payloads, which is exactly the plain eviction
        that would have happened without the tier, counted so a pusher
        that cannot keep up is visible rather than a memory leak."""
        dropped = 0
        dropped_hashes = []
        with self._mu:
            self._demote_queue.extend(payloads)
            cap = max(self.config.remote_demote_queue, 1)
            while len(self._demote_queue) > cap:
                dropped_hashes.append(self._demote_queue.popleft().block_hash)
                dropped += 1
            if dropped:
                self.demote_dropped += dropped
        self._demote_failed_lifecycle(dropped_hashes)

    def _demote_failed_lifecycle(self, hashes) -> None:
        """Correct the ledger's optimistic ``demote`` records for blocks
        the pusher dropped or failed: the block-manager hook records the
        hand-off (the engine cannot know the wire outcome), so every
        failure path here — the plain eviction PR 12 defines — must end
        the phantom remote residency. Guarded per block: a block
        re-registered locally meanwhile keeps its newer residency."""
        if self.lifecycle is None:
            return
        for h in hashes:
            if h is not None:
                self.lifecycle.end_if_tier(h, "remote", "demote_failed")

    def _demotion_targets(self) -> list[str]:
        """Peers ordered most-headroom-first (unknown counts as open-ended
        — optimistic until the first ack says otherwise), skipping only
        peers whose circuit breaker is OPEN (a push would fail instantly).
        A peer that last acked ZERO headroom ranks last but stays a
        target: a full remote store still accepts by LRU-rotating its
        coldest blocks, and the next ack refreshes the number — skipping
        it outright would permanently turn demotion off the first time
        the holder filled."""
        with self._mu:
            headroom = dict(self._peer_headroom)
        open_eps = self.open_breaker_endpoints
        ranked = []
        for ep in self._remote_peers:
            if ep in open_eps:
                continue
            h = headroom.get(ep)
            ranked.append((-(h if h is not None else 1 << 30), ep))
        ranked.sort()
        return [ep for _, ep in ranked]

    def _demote_loop(self) -> None:
        """Background pusher: drain parked demotions to the best target.
        EVERY failure path is plain eviction (the legacy outcome) — a
        partitioned or dead target costs bounded timeouts (then breaker
        fast-fails), never a stalled engine or a wedged shutdown."""
        while not self._demote_stop.wait(0.02):
            with self._mu:
                if not self._demote_queue:
                    continue
                batch = []
                cap = max(self.config.transfer_max_blocks, 1)
                while self._demote_queue and len(batch) < cap:
                    batch.append(self._demote_queue.popleft())
            self._push_batch(batch)

    def _push_batch(self, batch: list) -> None:
        for endpoint in self._demotion_targets():
            client = self._get_client(endpoint)
            if client is None:
                break  # shutting down; drop = plain eviction
            try:
                accepted, headroom = client.push_blocks(
                    self.config.model_name,
                    self.config.pod_identifier,
                    batch,
                    timeout_s=self.config.transfer_timeout_s,
                )
            except TransferError as e:
                log.warning(
                    "demotion push failed; trying next peer",
                    target=endpoint,
                    blocks=len(batch),
                    error=repr(e),
                )
                continue
            with self._mu:
                self._peer_headroom[endpoint] = headroom
                self.demote_pushed_blocks += accepted
                if accepted < len(batch):
                    # Validation rejects / duplicate holds: the remainder
                    # is plainly evicted, same as legacy.
                    self.demote_failed_blocks += len(batch) - accepted
            if accepted < len(batch):
                # The ack carries a count, not per-block verdicts; the
                # store validates in order, so charging the TAIL is the
                # closest honest attribution for the ledger correction.
                self._demote_failed_lifecycle(
                    [b.block_hash for b in batch[accepted:]]
                )
            return
        with self._mu:
            self.demote_failed_blocks += len(batch)
        self._demote_failed_lifecycle([b.block_hash for b in batch])

    # -- async prefix import (ASYNC_PULL) -----------------------------------
    def _start_async_pull(self, seq: Sequence, source: str, span) -> None:
        """Flip a just-admitted sequence into the ``importing`` state and
        hand its prefix fetch to the worker pool (engine loop only). The
        scheduler skips the sequence — admitting later arrivals past it —
        until ``_finish_async_pull`` clears the flag."""
        job = {"cancel": threading.Event(), "source": source}
        with self._mu:
            if not self._running:
                # Racing shutdown: skip the pull entirely — the sequence
                # stays admittable (cold) and _fail_outstanding resolves
                # its future; a pool touched here may already be torn down.
                return
            if self._pull_pool is None:
                self._pull_pool = ThreadPoolExecutor(
                    max_workers=max(self.config.pull_workers, 1),
                    thread_name_prefix="kv-pull",
                )
            pool = self._pull_pool
            self._pull_jobs[seq.seq_id] = job
        seq.importing = True
        trace_ctx = span.context if span is not None else None
        try:
            pool.submit(self._async_pull_worker, seq, source, job, trace_ctx)
        except RuntimeError:  # executor shut down between the lock and here
            seq.importing = False
            with self._mu:
                self._pull_jobs.pop(seq.seq_id, None)

    def _finish_async_pull(self, seq: Sequence, job: dict) -> None:
        """Stage the import completion back onto the engine loop (the only
        thread allowed to clear ``importing``) and wake it."""
        with self._work:
            self._pull_jobs.pop(seq.seq_id, None)
            if self._running:
                self._import_dones.append(seq)
                self._work.notify()
            else:
                seq.importing = False  # loop gone; unblock directly

    def _async_pull_worker(self, seq: Sequence, source: str, job, trace_ctx) -> None:
        """Background prefix import for one sequence (worker thread):
        fetch the warm chain from ``source``, verify + install it via the
        engine-loop import path, then release the sequence to the
        scheduler. EVERY exit — success, empty peer, fetch timeout, wire
        error, cancel — releases the sequence; failure means cold prefill,
        never a stuck or failed request. The fetch timeout is clamped to
        the request's remaining deadline budget, and a tripped per-peer
        breaker fails the fetch instantly (one skipped fetch, not one
        timeout). The ``pod.pull_prefix`` span gains async/overlap attrs:
        ``overlap`` is the share of the pull hidden behind other work
        (before the scheduler first wanted this sequence)."""
        span = self.tracer.start_span(
            "pod.pull_prefix",
            parent=trace_ctx,
            attrs={
                "source": source,
                "pod": self.config.pod_identifier,
                "async": True,
            },
        )
        t0 = time.monotonic()
        imported = 0
        outcome = "failed"
        try:
            fetch_timeout: Optional[float] = None
            wait_timeout = self.config.transfer_timeout_s * 3
            if seq.deadline is not None:
                remaining = seq.deadline - t0
                if remaining <= 0:
                    outcome = "skipped"
                    return
                fetch_timeout = min(self.config.transfer_timeout_s, remaining)
                wait_timeout = min(wait_timeout, remaining)
            hashes = self.engine.block_manager.token_db.prefix_hashes(
                seq.prompt_tokens
            )
            if not hashes:
                outcome = "empty"
                return
            client = self._get_client(source)
            if client is None or job["cancel"].is_set():
                outcome = "skipped"
                return
            blocks, _complete = client.fetch(
                self.config.model_name,
                hashes,
                self.config.transfer_max_blocks,
                timeout_s=fetch_timeout,
                traceparent=(
                    format_traceparent(span.context)
                    if span.context is not None
                    else None
                ),
            )
            if job["cancel"].is_set():
                # The sequence died (abort/shed) while the bytes were in
                # flight: install nothing — pages stay at baseline.
                outcome = "canceled"
                return
            imported = (
                self.submit_import(blocks, source_pod=source).result(
                    timeout=wait_timeout
                )
                if blocks
                else 0
            )
            outcome = "ok" if imported else "empty"
        except (TransferError, RuntimeError, FuturesTimeout) as e:
            log.warning(
                "async KV pull failed; sequence falls back to cold prefill",
                source=source,
                seq=seq.seq_id,
                error=repr(e),
            )
            span.set_attr("error", repr(e))
            outcome = "failed"
        finally:
            t1 = time.monotonic()
            if outcome != "ok" and job["cancel"].is_set():
                # The sequence died while the fetch was in flight: whatever
                # the wire did (timed out, errored, returned nothing), this
                # is a cancel, not a cold-prefill fallback — there is no
                # sequence left to fall back.
                outcome = "canceled"
            with self._mu:  # += is not atomic; workers finish concurrently
                if outcome == "canceled":
                    self.async_pull_canceled += 1
                elif imported:
                    self.transfer_pulls += 1
                    self.async_pulls += 1
                elif outcome == "failed":
                    self.transfer_pull_failures += 1
                    self.async_pull_fallbacks += 1
            # Overlap decomposition: time before the scheduler first
            # wanted this sequence was hidden behind other work; the
            # remainder exposed (it held this sequence's prefill).
            wanted = seq.import_wanted_time
            hidden = t1 - t0 if wanted is None else min(max(wanted - t0, 0.0), t1 - t0)
            exposed = (t1 - t0) - hidden
            span.set_attr("outcome", outcome)
            span.set_attr("imported_blocks", imported)
            span.set_attr("overlap", round(hidden, 6))
            span.end()
            self.metrics.observe_pull(
                t1 - t0,
                outcome,
                trace_id=(
                    span.context.trace_id if span.context is not None else None
                ),
            )
            self.metrics.observe_pull_overlap(hidden, exposed)
            self._finish_async_pull(seq, job)

    def pull_prefix(
        self,
        prompt_tokens: list[int],
        source_endpoint: str,
        timeout_s: Optional[float] = None,
        deadline: Optional[float] = None,
        trace_ctx=None,
    ) -> int:
        """Pull ``prompt_tokens``' warm prefix from a peer pod's export
        service and commit it locally (the router's "pull-then-compute"
        arm). Returns blocks imported; 0 on ANY failure — a pull is an
        optimization, so every error degrades to cold prefill, never to a
        failed request. ``deadline`` (absolute monotonic, the requesting
        request's deadline): the fetch and import waits are clamped to the
        remaining budget, and a pull with no budget left is skipped
        outright — cold prefill starts immediately instead of burning the
        deadline on a transfer the client can no longer wait for.
        ``trace_ctx``: parent span context — the pull span (and, via the
        transfer envelope's traceparent, the exporting peer's spans) joins
        that trace."""
        span = self.tracer.start_span(
            "pod.pull_prefix",
            parent=trace_ctx,
            attrs={"source": source_endpoint, "pod": self.config.pod_identifier},
        )
        t_pull = time.monotonic()

        def done(n: int, outcome: str) -> int:
            span.set_attr("outcome", outcome)
            span.set_attr("imported_blocks", n)
            span.end()
            self.metrics.observe_pull(
                time.monotonic() - t_pull,
                outcome,
                trace_id=(
                    span.context.trace_id if span.context is not None else None
                ),
            )
            return n

        fetch_timeout: Optional[float] = None  # None = client's configured
        wait_timeout = timeout_s or self.config.transfer_timeout_s * 3
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # Budget exhausted before the fetch — NOT "peer had
                # nothing": under deadline pressure this is the overload
                # signal the decomposition exists to expose.
                return done(0, "skipped")
            fetch_timeout = min(self.config.transfer_timeout_s, remaining)
            wait_timeout = min(wait_timeout, remaining)
        hashes = self.engine.block_manager.token_db.prefix_hashes(prompt_tokens)
        if not hashes:
            return done(0, "empty")
        client = self._get_client(source_endpoint)
        if client is None:
            return done(0, "skipped")
        try:
            blocks, _complete = client.fetch(
                self.config.model_name,
                hashes,
                self.config.transfer_max_blocks,
                timeout_s=fetch_timeout,
                traceparent=(
                    format_traceparent(span.context)
                    if span.context is not None
                    else None
                ),
            )
            imported = (
                self.submit_import(blocks, source_pod=source_endpoint).result(
                    timeout=wait_timeout
                )
                if blocks
                else 0
            )
        except (TransferError, RuntimeError, FuturesTimeout) as e:
            with self._mu:  # concurrent HTTP pulls race this counter
                self.transfer_pull_failures += 1
            log.warning(
                "KV pull failed; falling back to cold prefill",
                source=source_endpoint,
                error=repr(e),
            )
            span.set_attr("error", repr(e))
            return done(0, "failed")
        if imported:
            with self._mu:  # concurrent HTTP pulls race this counter
                self.transfer_pulls += 1
        return done(imported, "ok" if imported else "empty")

    # -- request path -------------------------------------------------------
    def _retry_after_s(self, depth: int, queued_tokens: int) -> float:
        """Retry-After hint from the measured serving rates: time to drain
        the queue at the observed request-completion rate, falling back to
        queued prefill work over the engine's online prefill-rate EMA.
        Floored at 1 s (sub-second retries just re-overload) and capped at
        60 s (past that the estimate is noise; the client should re-route).
        """
        est = None
        if self.metrics.request_rate:
            est = depth / self.metrics.request_rate
        elif self.engine._prefill_rate and queued_tokens:
            est = queued_tokens / self.engine._prefill_rate
        return float(min(max(est if est is not None else 1.0, 1.0), 60.0))

    def _check_admission(  # kvlint: holds=_work
        self, n_tokens: int, tenant: str = ""
    ) -> None:
        """Admission control (caller holds ``_mu``): reject fast — before
        the request touches the engine — when the configured queue-depth or
        queued-token cap would be exceeded. ``tenant`` is the request's
        QoS slice key; with TENANT_QOS on its per-tenant budgets
        (max_waiting / max_queued_tokens / rps) are checked FIRST — a
        tenant over ITS budget gets the tenant-shaped 429 even when the
        pod as a whole has headroom. Both caps off (0) and no QoS policy
        = legacy unbounded admission."""
        cfg = self.config
        if self.qos is not None:
            verdict = self.qos.admit(tenant, n_tokens)
            if verdict is not None:
                cap, message, rate_hint, t_depth, t_queued = verdict
                self.admission_rejected += 1
                self.metrics.observe_rejected(draining=False)
                self._flight_event(
                    "admission_reject", cap=f"tenant_{cap}", tenant=tenant
                )
                # Rate rejections carry an exact hint (when the oldest
                # window event expires); budget rejections fall back to
                # the measured-rate estimate over the tenant's own queue.
                raise AdmissionError(
                    message,
                    (
                        rate_hint
                        if rate_hint is not None
                        else self._retry_after_s(t_depth, t_queued)
                    ),
                )
        if cfg.admission_max_waiting <= 0 and cfg.admission_max_queued_tokens <= 0:
            return
        sch = self.engine.scheduler
        # len() snapshots of engine-owned lists: momentarily stale is fine,
        # admission is a load shedder, not an exact semaphore.
        active = len(sch.running) + len(sch.prefilling)
        depth = max(self._pending - active, 0)
        queued_tokens = self._pending_tokens
        if cfg.admission_max_waiting > 0 and depth >= cfg.admission_max_waiting:
            self.admission_rejected += 1
            self.metrics.observe_rejected(draining=False)
            self._flight_event("admission_reject", cap="waiting", depth=depth)
            raise AdmissionError(
                f"overloaded: {depth} requests waiting >= "
                f"ADMISSION_MAX_WAITING={cfg.admission_max_waiting}",
                self._retry_after_s(depth, queued_tokens),
            )
        if (
            cfg.admission_max_queued_tokens > 0
            and queued_tokens + n_tokens > cfg.admission_max_queued_tokens
        ):
            self.admission_rejected += 1
            self.metrics.observe_rejected(draining=False)
            self._flight_event(
                "admission_reject", cap="tokens", queued_tokens=queued_tokens
            )
            raise AdmissionError(
                f"overloaded: {queued_tokens} + {n_tokens} queued prompt "
                f"tokens > ADMISSION_MAX_QUEUED_TOKENS="
                f"{cfg.admission_max_queued_tokens}",
                self._retry_after_s(depth, queued_tokens),
            )

    def submit(
        self,
        prompt_tokens: list[int],
        sampling: Optional[SamplingParams] = None,
        *,
        deadline_s: Optional[float] = None,
        request_id: Optional[str] = None,
        trace_ctx=None,
        route_action: Optional[str] = None,
        pull_source: Optional[str] = None,
        tenant: str = "",
    ) -> Future:
        """Enqueue a request; the Future resolves to the finished Sequence
        (or raises: invalid request, engine failure, shutdown). Raises
        ``AdmissionError`` when over the admission caps (fast 429 — never
        touches the engine) and ``DrainingError`` while draining (503).
        ``deadline_s``: per-request deadline budget in seconds (falls back
        to ``default_deadline_s``; 0/None = none). The returned Future
        carries ``request_id`` for ``abort``. ``trace_ctx`` (an
        ``obs.SpanContext``, e.g. parsed from a ``traceparent`` header):
        parent for this request's spans — with tracing enabled the pod
        mints its own trace when None. ``route_action``: the router's
        verdict ("route_warm"/"pull"/"cold") labeling the latency
        histograms; None derives warm/cold from the prefix-cache hit.
        ``pull_source``: a peer pod's transfer endpoint whose warm prefix
        should be imported for this request. Honored only with
        ``async_pull`` on: the request enters the queue ``importing`` and
        a worker fetches the chain in the background (the scheduler
        admits it once the blocks land, or on any fetch failure — cold
        prefill). With the knob off the argument is ignored; callers use
        the legacy blocking ``pull_prefix``-then-``submit`` flow.
        ``tenant``: the request's tenant name (the ``X-Tenant`` header).
        With ``TENANT_QOS`` on it is collapsed onto a policy slice key
        and drives per-tenant admission budgets, priority scheduling,
        cache accounting and observability slices; with the knob off
        (the default) the argument is ignored."""
        # Surface obviously-bad requests synchronously with the same checks
        # add_request applies (the rest raise through the Future).
        if not prompt_tokens:
            raise ValueError("empty prompt")
        if self.config.pod_role == "kvstore":
            # A kvstore pod is storage, not compute: it holds demoted
            # blocks and serves transfer pulls; its heartbeat role keeps
            # it out of every scorer placement, and a misrouted submit
            # fails loudly instead of silently burning its pages.
            raise ValueError("kvstore pods do not serve requests")
        clamped = False
        if self.config.pod_role == "prefill":
            # Role gate at admission: a prefill-tier pod runs ingest at
            # full batch width and stops at the first token — the engine
            # never dispatches a decode-only step because every sequence
            # finishes at its prefill commit. The scheduler itself is
            # untouched (its prefill-priority walk IS the gate's second
            # half); decode work belongs on the decode tier.
            sampling = sampling or SamplingParams()
            if sampling.max_new_tokens > 1:
                sampling = replace(sampling, max_new_tokens=1)
                clamped = True
        if deadline_s is None and self.config.default_deadline_s > 0:
            deadline_s = self.config.default_deadline_s
        deadline = (
            time.monotonic() + deadline_s
            if deadline_s is not None and deadline_s > 0
            else None
        )
        rid = request_id or str(uuid.uuid4())
        # Collapse the raw tenant header onto a policy slice key up front:
        # every downstream consumer (budgets, scheduler, block manager,
        # observability) sees only bounded key-space values.
        tkey = self.qos.key(tenant) if self.qos is not None else ""
        fut: Future = Future()
        fut.request_id = rid
        # Span starts at submit (queueing time includes staging), after the
        # reject paths — a 429/503 is not a served request.
        span = None
        with self._work:
            if self._failed is not None:
                raise RuntimeError(f"engine failed: {self._failed}")
            if not self._running:
                raise RuntimeError("pod server not running")
            if self._draining:
                self.admission_rejected_draining += 1
                self.metrics.observe_rejected(draining=True)
                self._flight_event("admission_reject", cap="draining")
                raise DrainingError(
                    "pod is draining; retry against another pod"
                )
            self._check_admission(len(prompt_tokens), tkey)
            if clamped:
                self.role_clamped_requests += 1
            span = self.tracer.start_span(
                "pod.request",
                parent=trace_ctx,
                attrs={
                    "request_id": rid,
                    "pod": self.config.pod_identifier,
                    "prompt_tokens": len(prompt_tokens),
                },
            )
            fut.trace_context = span.context
            self._pending += 1
            self._pending_tokens += len(prompt_tokens)
            if self.qos is not None:
                self.qos.on_admitted(tkey, len(prompt_tokens))
            pull = (
                pull_source
                if pull_source and self.config.async_pull
                else None
            )
            self._staging.append(
                (list(prompt_tokens), sampling, deadline, rid, fut, span,
                 route_action, pull, tkey)
            )
            self._work.notify()
        return fut

    def abort(self, request_id: Optional[str]) -> Future:
        """Stage an abort onto the engine loop — the only thread allowed to
        free pages. The Future resolves to True when a live sequence was
        aborted (pages/slots released; its submit future resolves with the
        partial sequence, ``finish_reason="abort"``), False when the
        request already finished or was never admitted. ``request_id=None``
        aborts every live request (the drain-timeout hammer)."""
        fut: Future = Future()
        with self._work:
            if not self._running or self._failed is not None:
                fut.set_result(False)
                return fut
            self._aborts.append((request_id, fut))
            self._work.notify()
        return fut

    def generate(
        self,
        prompt_tokens: list[int],
        sampling: Optional[SamplingParams] = None,
        timeout: Optional[float] = None,
        *,
        deadline_s: Optional[float] = None,
    ) -> Sequence:
        fut = self.submit(prompt_tokens, sampling, deadline_s=deadline_s)
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeout:
            # The caller stopped waiting — the sequence must not keep
            # decoding into the void and holding KV pages. Abort frees
            # them; the timeout still propagates.
            try:
                self.abort(fut.request_id).result(timeout=30)
            except Exception:
                log.exception("post-timeout abort failed")
            raise

    # -- HTTP surface -------------------------------------------------------
    def build_app(self):
        from aiohttp import web

        async def completions(request: web.Request) -> web.Response:
            import asyncio

            try:
                body = await request.json()
            except Exception:
                return web.json_response({"error": "invalid JSON"}, status=400)

            prompt = body.get("prompt")
            token_ids = body.get("prompt_token_ids")
            if token_ids is None:
                if not isinstance(prompt, str) or not prompt:
                    return web.json_response(
                        {"error": "prompt or prompt_token_ids required"}, status=400
                    )
                if self._tokenizer is None:
                    return web.json_response(
                        {"error": "no tokenizer loaded; pass prompt_token_ids"},
                        status=400,
                    )
                token_ids, _ = self._tokenizer.encode(prompt, self.config.model_name)

            try:
                stop_ids = [int(t) for t in body.get("stop_token_ids", [])]
                sampling = SamplingParams(
                    max_new_tokens=int(body.get("max_tokens", 64)),
                    temperature=float(body.get("temperature", 0.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 1.0)),
                    stop_token_ids=tuple(stop_ids),
                )
                token_ids = [int(t) for t in token_ids]
            except (TypeError, ValueError) as e:
                return web.json_response(
                    {"error": f"invalid request field: {e}"}, status=400
                )
            # Per-request deadline: X-Request-Deadline header (seconds of
            # budget), falling back to the configured default inside submit.
            deadline_s = None
            hdr = request.headers.get("X-Request-Deadline")
            if hdr is not None:
                import math

                try:
                    deadline_s = float(hdr)
                    # NaN fails every comparison, so `<= 0` alone would
                    # silently accept it as "no deadline" — reject instead.
                    if not math.isfinite(deadline_s) or deadline_s <= 0:
                        raise ValueError
                except ValueError:
                    return web.json_response(
                        {"error": "invalid X-Request-Deadline (want seconds > 0)"},
                        status=400,
                    )
            # W3C trace propagation: adopt the caller's traceparent (the
            # scoring service / router minted it) so this pod's spans join
            # the request's fleet-wide trace. Parsed only when tracing is
            # on — the off path reads no headers it didn't before.
            trace_ctx = None
            if self.tracer.enabled:
                trace_ctx = parse_traceparent(request.headers.get("traceparent"))
            route_action = request.headers.get("X-Route-Action")
            if route_action not in ("route_warm", "pull", "cold"):
                route_action = None
            # Async prefix import: the router names the warm peer in
            # X-Pull-Source and this pod fetches in the background while
            # the request queues. Read only when ASYNC_PULL is on — the
            # knobs-off request path touches no headers it didn't before.
            pull_source = (
                request.headers.get("X-Pull-Source")
                if self.config.async_pull
                else None
            )
            # Tenant identity (X-Tenant): read only with TENANT_QOS on —
            # the knobs-off request path touches no headers it didn't
            # before. Unknown/absent tenants collapse onto the "*" policy
            # entry inside submit.
            tenant = (
                request.headers.get("X-Tenant", "")
                if self.qos is not None
                else ""
            )
            try:
                fut = self.submit(
                    token_ids,
                    sampling,
                    deadline_s=deadline_s,
                    trace_ctx=trace_ctx,
                    route_action=route_action,
                    pull_source=pull_source,
                    tenant=tenant,
                )
            except AdmissionError as e:  # overloaded: fast 429, engine untouched
                return admission_reject_response(web, e)
            except DrainingError as e:  # rolling restart: go elsewhere
                return web.json_response({"error": str(e)}, status=503)
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            except RuntimeError as e:  # engine failure / shutdown
                return web.json_response({"error": str(e)}, status=503)
            ctx = getattr(fut, "trace_context", None)
            with log_context(
                request_id=fut.request_id,
                trace_id=ctx.trace_id if ctx is not None else None,
            ):
                try:
                    seq = await asyncio.wrap_future(fut)
                except asyncio.CancelledError:
                    # Client disconnected (or the handler was cancelled):
                    # abort the sequence instead of decoding into the void —
                    # its pages free as soon as the engine loop picks the
                    # abort up.
                    self.abort(fut.request_id)
                    raise
                except ValueError as e:  # rejected by engine admission checks
                    return web.json_response({"error": str(e)}, status=400)
                except RuntimeError as e:  # engine failure / shutdown / drain
                    return web.json_response({"error": str(e)}, status=503)
            if seq.error:
                return web.json_response({"error": seq.error}, status=500)

            # Preemption-stable outputs (output_tokens may have been folded
            # into the prompt when a sequence was preempted and recomputed).
            out_tokens = seq.generated_tokens
            text = None
            if self._tokenizer is not None:
                try:
                    text = self._tokenizer.decode(out_tokens, self.config.model_name)
                except Exception as e:
                    # Generation succeeded; a broken/unloadable tokenizer must
                    # not turn the response into a 500 — token ids suffice.
                    log.warning("decode failed", error=repr(e))
            stopped = bool(out_tokens) and out_tokens[-1] in sampling.stop_token_ids
            finish_reason = seq.finish_reason or (
                "stop" if stopped else "length"
            )
            # traceparent echo ONLY when tracing is on: with knobs off the
            # response (body AND headers) is bit-identical legacy.
            headers = (
                {"traceparent": format_traceparent(ctx)}
                if ctx is not None
                else None
            )
            return web.json_response(
                {
                    "id": seq.request_id,
                    "object": "text_completion",
                    "model": self.config.model_name,
                    "choices": [
                        {
                            "index": 0,
                            "text": text,
                            "token_ids": out_tokens,
                            "finish_reason": finish_reason,
                        }
                    ],
                    "usage": {
                        "prompt_tokens": seq.user_prompt_len,
                        "completion_tokens": seq.num_generated,
                        "cached_prompt_tokens": seq.num_cached_prompt,
                    },
                    "ttft_s": seq.ttft,
                },
                headers=headers,
            )

        async def healthz(_request: web.Request) -> web.Response:
            if self._failed is not None:
                return web.json_response(
                    {"status": "failed", "error": self._failed}, status=503
                )
            with self._mu:
                draining = self._draining
            if draining:
                # k8s readiness must agree with admission: a draining pod
                # takes no new traffic.
                return web.json_response({"status": "draining"}, status=503)
            return web.json_response({"status": "ok"})

        async def drain_endpoint(_request: web.Request) -> web.Response:
            """Operator-triggered graceful drain (same path as SIGTERM).
            Returns immediately; poll /stats (drain block) or /healthz for
            progress. Idempotent."""
            threading.Thread(
                target=self.drain, name="drain", daemon=True
            ).start()
            return web.json_response(
                {
                    "status": "draining",
                    "drain_timeout_s": self.config.drain_timeout_s,
                },
                status=202,
            )

        async def stats(_request: web.Request) -> web.Response:
            bm = self.engine.block_manager
            with self._mu:
                # One consistent cut of everything _mu guards (kvlint
                # lock-discipline: counters outside the lock could pair a
                # new value with stale queue depths in the same scrape).
                staged = len(self._staging)
                pending = self._pending
                pending_tokens = self._pending_tokens
                clients = self._transfer_pool.clients()
                breakers = {
                    ep: client.breaker.snapshot()
                    for ep, client in clients.items()
                    if client.breaker is not None
                }
                breaker_skips = sum(
                    client.breaker_skips for client in clients.values()
                )
                pulls = self.transfer_pulls
                pull_failures = self.transfer_pull_failures
                heartbeats_published = self.heartbeats_published
                snapshots_published = self.snapshots_published
                rejected = self.admission_rejected
                rejected_draining = self.admission_rejected_draining
                draining = self._draining
                drains_started = self.drains_started
                drain_forced = self.drain_forced_requests
                importing = len(self._pull_jobs)
                async_pulls = self.async_pulls
                async_fallbacks = self.async_pull_fallbacks
                async_canceled = self.async_pull_canceled
                role_clamped = self.role_clamped_requests
                prefill_completes = self.prefill_completes_published
                audits_published = self.audits_published
                demote_pushed = self.demote_pushed_blocks
                demote_failed = self.demote_failed_blocks
                demote_dropped = self.demote_dropped
                demote_queued = len(self._demote_queue)
                peer_headroom = dict(self._peer_headroom)
                tenant_qos_snap = (
                    self.qos.snapshot() if self.qos is not None else None
                )
                # Fleet-controller counters in the SAME cut (ISSUE 20
                # consistency fix): the fleet block below used to
                # re-acquire _mu, so a migration landing between the two
                # holds could pair fresh migration counts with stale
                # queue/pull state in one scrape.
                migrations_out = self.migrations_out
                migrations_in = self.migrations_in
                migration_fallbacks = self.migration_fallbacks
            payload = {
                "pod": self.config.pod_identifier,
                "model": self.config.model_name,
                "data_parallel_rank": self.config.data_parallel_rank,
                "staged": staged,
                "waiting": len(self.engine.scheduler.waiting),
                "running": len(self.engine.scheduler.running),
                "free_pages": bm.num_free,
                "total_pages": bm.config.total_pages,
                "prefill": dict(self.engine.prefill_stats),
                "transfer": {
                    **self.engine.transfer_stats,
                    "endpoint": self.config.transfer_endpoint,
                    "pulls": pulls,
                    "pull_failures": pull_failures,
                    "breaker_skips": breaker_skips,
                    "breakers": breakers,
                    "requests_served": (
                        self._transfer_service.requests_served
                        if self._transfer_service
                        else 0
                    ),
                },
                "self_heal": {
                    "heartbeat_interval_s": self.config.heartbeat_interval_s,
                    "resync_interval_s": self.config.resync_interval_s,
                    "heartbeats_published": heartbeats_published,
                    "snapshots_published": snapshots_published,
                    "event_batches_dropped": getattr(
                        self._publisher, "dropped_batches", 0
                    ),
                },
                "admission": {
                    "max_waiting": self.config.admission_max_waiting,
                    "max_queued_tokens": self.config.admission_max_queued_tokens,
                    "default_deadline_s": self.config.default_deadline_s,
                    "pending_requests": pending,
                    "pending_prompt_tokens": pending_tokens,
                    "rejected": rejected,
                    "rejected_draining": rejected_draining,
                    **dict(self.engine.lifecycle_stats),
                },
                "drain": {
                    "draining": draining,
                    "drain_timeout_s": self.config.drain_timeout_s,
                    "drains_started": drains_started,
                    "forced_requests": drain_forced,
                },
            }
            if self.config.pod_role != "mixed":
                # Disagg block only for role-assigned pods: the knobs-off
                # /stats payload stays bit-identical.
                payload["disagg"] = {
                    "role": self.config.pod_role,
                    "role_clamped_requests": role_clamped,
                    "prefill_completes_published": prefill_completes,
                }
            if self.config.async_pull:
                # Async-import block only when the knob is on: the
                # knobs-off /stats payload stays bit-identical.
                payload["transfer"]["async_pull"] = {
                    "workers": self.config.pull_workers,
                    "importing": importing,
                    "pulls": async_pulls,
                    "fallbacks": async_fallbacks,
                    "canceled": async_canceled,
                }
            if self.config.remote_tier:
                # Remote-tier block only with the knob on: the knobs-off
                # /stats payload stays bit-identical.
                store = self.engine.remote_store
                payload["remote"] = {
                    "peers": list(self._remote_peers),
                    "store_pages": self.config.remote_store_pages,
                    "store_cached": len(store) if store is not None else 0,
                    "headroom": self.engine.remote_headroom,
                    "peer_headroom": peer_headroom,
                    **dict(self.engine.remote_stats),
                    "pushed_blocks": demote_pushed,
                    "push_failed_blocks": demote_failed,
                    "queue_dropped": demote_dropped,
                    "queued": demote_queued,
                    "store_stats": (
                        dict(store.stats) if store is not None else {}
                    ),
                    "pushes_served": (
                        self._transfer_service.pushes_served
                        if self._transfer_service
                        else 0
                    ),
                    # Connection reuse on the shared client pool (pulls +
                    # demotion pushes ride the same DEALER per peer).
                    "clients": self._transfer_pool.snapshot(),
                }
            if bm.config.host_pages > 0:
                # Host tier + KV quant block only when the tier knob is on:
                # the knobs-off /stats payload stays bit-identical.
                payload["host"] = {
                    "host_pages": bm.config.host_pages,
                    "cached": bm.num_host_cached_pages,
                    "kv_quant": self.config.engine.kv_quant,
                    "prefetch_enabled": self.config.engine.host_prefetch,
                    **dict(bm.host_stats),
                    "prefetch": dict(self.engine.host_prefetch_stats),
                }
            if self.integrity is not None:
                # Integrity block only with KV_INTEGRITY on: the knobs-off
                # /stats payload stays bit-identical.
                payload["integrity"] = self.integrity.snapshot()
            if self.config.engine.kv_quant_hbm is not None:
                # Only when the HBM-quant knob is on: the knobs-off /stats
                # payload stays bit-identical (same rule as every tier
                # block above).
                payload["kv_quant_hbm"] = {
                    "mode": self.config.engine.kv_quant_hbm,
                    "total_pages": bm.config.total_pages,
                    "pool_dtype": str(self.engine.k_pages.dtype),
                }
            if self.config.obs_tracing or self.config.obs_metrics:
                # Only with an OBS_* knob on: the knobs-off /stats payload
                # stays bit-identical to previous rounds.
                payload["obs"] = {
                    "tracing": self.tracer.snapshot(),
                    "step_stats": {
                        k: round(v, 6) if isinstance(v, float) else v
                        for k, v in self.engine.step_stats.items()
                    },
                    "loop_lag_s": self._loop_lag_s,
                }
            if self.config.obs_audit:
                # Audit block only with the knob on: the knobs-off /stats
                # payload stays bit-identical.
                payload["audit"] = {"published": audits_published}
            if self.slo is not None:
                # SLO block only when OBS_SLO configured an objective.
                payload["slo"] = self.slo.snapshot()
            if self.config.obs_lifecycle:
                # Lifecycle block only with the knob on: the knobs-off
                # /stats payload stays bit-identical.
                payload["lifecycle"] = {
                    **self.lifecycle.snapshot(),
                    "mrc": self.mrc.snapshot(),
                }
            if self.config.obs_flight:
                # Flight block only with the knob on: the knobs-off
                # /stats payload stays bit-identical.
                payload["flight"] = self.flight.snapshot()
            if self.qos is not None:
                # Tenant-QoS block only with the knob on: the knobs-off
                # /stats payload stays bit-identical. Scheduler/block-
                # manager tenant state is engine-thread-owned; these are
                # the same tolerated point-in-time reads as the queue
                # depths above.
                sch = self.engine.scheduler
                tenant_qos_snap["qos_served_tokens"] = {
                    t: round(v, 1) for t, v in dict(sch._qos_served).items()
                }
                tenant_qos_snap["cache"] = {
                    "evictable_pages": dict(bm._tenant_evictable),
                    "stats": {
                        t: dict(s) for t, s in dict(bm.tenant_stats).items()
                    },
                }
                if self.slo is not None:
                    tenant_qos_snap["slo_burn"] = self.slo.tenant_burn_rates()
                payload["tenant_qos"] = tenant_qos_snap
            if self.config.fleet_controller:
                # Fleet block only with the knob on: the knobs-off
                # /stats payload stays bit-identical. Counters come from
                # the single locked cut at the top of this handler.
                payload["fleet"] = {
                    "migrations_out": migrations_out,
                    "migrations_in": migrations_in,
                    "migration_fallbacks": migration_fallbacks,
                    "migrations_served": (
                        self._transfer_service.migrations_served
                        if self._transfer_service
                        else 0
                    ),
                    "migration_blocks_accepted": (
                        self._transfer_service.migration_blocks_accepted
                        if self._transfer_service
                        else 0
                    ),
                }
            return web.json_response(payload)

        async def metrics(_request: web.Request) -> web.Response:
            if self.slo is not None:
                # Scrape-driven: burn rates recompute here, like the
                # indexer's occupancy gauges.
                self.slo.sync_gauges(self.metrics.set_slo_burn)
                if self.qos is not None:
                    self.slo.sync_tenant_gauges(
                        self.metrics.set_tenant_slo_burn
                    )
            body = self.metrics.exposition()
            if body is None:
                return web.json_response(
                    {"error": "prometheus_client not installed"}, status=501
                )
            return web.Response(
                body=body,
                headers={
                    "Content-Type": self.metrics.exposition_content_type()
                },
            )

        async def debug_traces(request: web.Request) -> web.Response:
            """Finished traces from the bounded ring, filterable by
            ``?trace_id=`` / ``?request_id=``. Empty (with enabled=false)
            when OBS_TRACING is off — the endpoint itself is harmless."""
            from ..obs.tracing import debug_traces_payload

            status, payload = debug_traces_payload(self.tracer, request.query)
            return web.json_response(payload, status=status)

        async def debug_lifecycle(request: web.Request) -> web.Response:
            """Recent block tier transitions from the bounded ledger ring,
            filterable by ``?chain=`` / ``?block=`` hash. Reports itself
            disabled until OBS_LIFECYCLE — the endpoint is harmless."""
            from ..obs.lifecycle import debug_lifecycle_payload

            status, payload = debug_lifecycle_payload(
                self.lifecycle, request.query
            )
            return web.json_response(payload, status=status)

        async def debug_mrc(request: web.Request) -> web.Response:
            """The sampled miss-ratio-vs-capacity curve plus the ladder's
            cumulative tier capacities evaluated on it — the tier-sizing
            answer (docs/operations.md runbook). Disabled-shaped until
            OBS_LIFECYCLE."""
            from ..obs.lifecycle import debug_mrc_payload

            bm_cfg = self.config.engine.block_manager
            caps = {"tpu_hbm": bm_cfg.total_pages - 1}
            if bm_cfg.host_pages > 0:
                caps["tpu_hbm+host_dram"] = (
                    bm_cfg.total_pages - 1 + bm_cfg.host_pages
                )
            status, payload = debug_mrc_payload(
                self.mrc, tier_capacities=caps, query=request.query
            )
            if status != 200:
                return web.json_response(payload, status=status)
            if self.qos is not None:
                # Per-tenant MRC slices (TENANT_QOS + OBS_LIFECYCLE):
                # each tenant's own reuse-distance curve — the "how much
                # cache does THIS tenant's hit rate actually need" input
                # for cache_share sizing. Key presence only with the
                # knob on keeps the legacy payload bit-identical. The
                # slices share the request's limit via the same helper.
                payload["tenants"] = {
                    t: debug_mrc_payload(
                        est, tier_capacities=caps, query=request.query
                    )[1]
                    for t, est in sorted(
                        dict(self.engine.block_manager._tenant_mrc).items()
                    )
                }
            return web.json_response(payload)

        async def debug_flight(request: web.Request) -> web.Response:
            """Flight-recorder counters + the latest triggered timeline
            (causally ordered). Disabled-shaped until OBS_FLIGHT."""
            from ..obs.flight import debug_flight_payload

            status, payload = debug_flight_payload(
                self.flight, query=request.query
            )
            return web.json_response(payload, status=status)

        async def debug_profile(request: web.Request) -> web.Response:
            """Capture a jax.profiler trace of the live engine for
            ``?seconds=N`` (default 3, capped at 60) into
            ``OBS_PROFILE_DIR``. Disabled (400) until that knob is set;
            one capture at a time."""
            import asyncio

            profile_dir = self.config.obs_profile_dir
            if not profile_dir:
                return web.json_response(
                    {"error": "profiling disabled; set OBS_PROFILE_DIR"},
                    status=400,
                )
            try:
                seconds = float(request.query.get("seconds", "3"))
            except ValueError:
                return web.json_response(
                    {"error": "invalid seconds"}, status=400
                )
            if not (0 < seconds <= 60):
                return web.json_response(
                    {"error": "seconds must be in (0, 60]"}, status=400
                )
            if not self._profile_mu.acquire(blocking=False):
                return web.json_response(
                    {"error": "a profile capture is already running"},
                    status=409,
                )

            def capture() -> None:
                # The lock is released HERE, not in the handler: a client
                # disconnect cancels the awaiting handler, but executor
                # work is uncancellable — releasing from the handler would
                # let a second capture collide with the still-running
                # profiler (start_trace raises while one is active).
                try:
                    import jax

                    jax.profiler.start_trace(profile_dir)
                    try:
                        time.sleep(seconds)
                    finally:
                        jax.profiler.stop_trace()
                finally:
                    self._profile_mu.release()

            try:
                fut = asyncio.get_running_loop().run_in_executor(None, capture)
            except RuntimeError:
                self._profile_mu.release()  # never dispatched
                raise
            try:
                await fut
            except Exception as e:
                return web.json_response(
                    {"error": f"profile capture failed: {e!r}"}, status=500
                )
            return web.json_response(
                {"profile_dir": profile_dir, "seconds": seconds}
            )

        app = web.Application()
        app.router.add_post("/v1/completions", completions)
        app.router.add_get("/healthz", healthz)
        app.router.add_post("/drain", drain_endpoint)
        app.router.add_get("/stats", stats)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/debug/traces", debug_traces)
        app.router.add_get("/debug/lifecycle", debug_lifecycle)
        app.router.add_get("/debug/mrc", debug_mrc)
        app.router.add_get("/debug/flight", debug_flight)
        app.router.add_post("/debug/profile", debug_profile)
        return app


def _resolve_model(name: str) -> LlamaConfig:
    from .. import models

    presets = {
        "tiny-llama": models.TINY_LLAMA,
        "tiny-moe": models.TINY_MOE,
        "meta-llama/Llama-3.1-8B-Instruct": models.LLAMA_3_8B,
        "meta-llama/Meta-Llama-3-8B": models.LLAMA_3_8B,
        "meta-llama/Llama-3.1-70B-Instruct": models.LLAMA_3_70B,
        "Qwen/Qwen2.5-0.5B-Instruct": models.QWEN2_5_0_5B,
        "Qwen/Qwen3-32B": models.QWEN3_32B,
        "mistralai/Mixtral-8x7B-Instruct-v0.1": models.MIXTRAL_8X7B,
        "google/gemma-7b": models.GEMMA_7B,
        "tiny-gemma": models.TINY_GEMMA,
        "Qwen/Qwen3-30B-A3B": models.QWEN3_30B_A3B,
        "tiny-qwen3-moe": models.TINY_QWEN3_MOE,
    }
    if name in presets:
        return presets[name]
    raise SystemExit(
        f"unknown model {name!r}; known presets: {sorted(presets)} "
        "(HF checkpoint loading: see models.hf_loader.load_hf_state_dict)"
    )


def main() -> None:
    from aiohttp import web

    config = PodServerConfig.from_env()
    config.engine.model = _resolve_model(config.model_name)

    tokenizer = None
    if _env_bool("LOAD_TOKENIZER", "0"):
        from ..tokenization.tokenizer import CachedHFTokenizer, HFTokenizerConfig

        tokenizer = CachedHFTokenizer(
            HFTokenizerConfig(huggingface_token=os.environ.get("HF_TOKEN") or None)
        )

    server = PodServer(config, tokenizer=tokenizer)
    server.start()
    log.info(
        "TPU pod server listening",
        port=config.http_port,
        pod=config.pod_identifier,
        model=config.model_name,
        zmq=config.zmq_endpoint,
    )
    app = server.build_app()

    async def _drain_on_shutdown(_app):
        # SIGTERM path: aiohttp's GracefulExit lands here before the
        # process dies — drain (finish inflight up to DRAIN_TIMEOUT_S,
        # publish the final snapshot + PodDrained goodbye) so a rolling
        # restart never leaves stale locality in the fleet for POD_TTL_S.
        import asyncio

        await asyncio.get_running_loop().run_in_executor(None, server.drain)

    app.on_shutdown.append(_drain_on_shutdown)
    try:
        web.run_app(app, port=config.http_port)
    finally:
        server.shutdown()


if __name__ == "__main__":
    main()
