// Native two-level LRU block index — the read-path hot structure.
//
// Mirrors the semantics of kvcache/kvblock/in_memory.py (itself the parity
// port of the reference's in_memory.go two-level LRU): an LRU of
// (model, chunk_hash) -> per-key pod LRU, bounded by key count and
// pods-per-key. Lookup stops at a present-but-empty key (broken prefix
// chain); a *missing* key does not break the chain. Strings never cross
// this boundary: the Python binding interns model/pod names to u32 ids and
// tiers to u8, so the hot loop is integer-only.
//
// Thread safety: one shared_mutex over the whole index. Mutating calls
// (add/evict/evict_pod) and the promoting walks (lookup/score refresh LRU
// recency, which relinks list nodes) take the exclusive side — the same
// effective discipline as the Python/Go versions. The read-only side
// (lookup_ro) takes the SHARED side and skips promotion entirely, so any
// number of scorer-shard read fans can scan concurrently with each other
// and block only for the duration of an individual apply — the read API
// the sharded control plane serves score fan-outs from without ever
// touching a Python-level lock.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <unordered_map>
#include <vector>

namespace {

struct KeyT {
    uint64_t hash;
    uint32_t model;
    bool operator==(const KeyT& o) const {
        return hash == o.hash && model == o.model;
    }
};

struct KeyHash {
    size_t operator()(const KeyT& k) const {
        // splitmix64 over the xor-fold; chunk hashes are already uniform.
        uint64_t x = k.hash ^ (uint64_t(k.model) * 0x9E3779B97F4A7C15ull);
        x ^= x >> 30; x *= 0xBF58476D1CE4E5B9ull;
        x ^= x >> 27; x *= 0x94D049BB133111EBull;
        x ^= x >> 31;
        return size_t(x);
    }
};

struct Entry {
    uint32_t pod;
    uint8_t tier;
    bool operator==(const Entry& o) const {
        return pod == o.pod && tier == o.tier;
    }
};

struct Node {
    KeyT key;
    // Pod LRU: front = most recently used. Bounded by pods_per_key (small),
    // so a vector beats pointer-chasing list nodes.
    std::vector<Entry> pods;
    Node* prev = nullptr;
    Node* next = nullptr;
};

class LruIndex {
  public:
    LruIndex(uint64_t max_keys, uint32_t pods_per_key)
        : max_keys_(max_keys ? max_keys : 1),
          pods_per_key_(pods_per_key ? pods_per_key : 1) {
        map_.reserve(max_keys_ < (1u << 20) ? max_keys_ : (1u << 20));
    }

    ~LruIndex() {
        Node* n = head_;
        while (n) { Node* nx = n->next; delete n; n = nx; }
    }

    void add(uint32_t model, const uint64_t* hashes, uint64_t n_keys,
             const uint32_t* pods, const uint8_t* tiers, uint64_t n_entries) {
        std::unique_lock<std::shared_mutex> g(mu_);
        for (uint64_t i = 0; i < n_keys; ++i) {
            Node* node = get_or_create({hashes[i], model});
            for (uint64_t j = 0; j < n_entries; ++j) {
                touch_pod(node, Entry{pods[j], tiers[j]});
            }
        }
    }

    void evict(uint32_t model, uint64_t hash, const uint32_t* pods,
               const uint8_t* tiers, uint64_t n_entries) {
        std::unique_lock<std::shared_mutex> g(mu_);
        auto it = map_.find({hash, model});
        if (it == map_.end()) return;
        Node* node = it->second;
        for (uint64_t j = 0; j < n_entries; ++j) {
            Entry e{pods[j], tiers[j]};
            for (size_t p = 0; p < node->pods.size(); ++p) {
                if (node->pods[p] == e) {
                    node->pods.erase(node->pods.begin() + long(p));
                    break;
                }
            }
        }
        if (node->pods.empty()) remove_node(node);
    }

    // Returns the number of keys processed; processing stops early (before
    // key i) when key i exists with an empty pod set. out_counts[i] = pods
    // written for key i (0 for missing or fully-filtered keys).
    uint64_t lookup(uint32_t model, const uint64_t* hashes, uint64_t n_keys,
                    const uint32_t* filter, uint64_t n_filter,
                    uint32_t* out_pods, uint8_t* out_tiers,
                    uint32_t* out_counts) {
        std::unique_lock<std::shared_mutex> g(mu_);
        uint64_t w = 0;
        for (uint64_t i = 0; i < n_keys; ++i) {
            auto it = map_.find({hashes[i], model});
            if (it == map_.end()) {            // missing: chain continues
                out_counts[i] = 0;
                continue;
            }
            Node* node = it->second;
            promote(node);                      // lookup refreshes key recency
            if (node->pods.empty()) return i;   // present-but-empty: stop
            uint32_t c = 0;
            for (const Entry& e : node->pods) {
                if (n_filter) {
                    bool ok = false;
                    for (uint64_t f = 0; f < n_filter; ++f) {
                        if (filter[f] == e.pod) { ok = true; break; }
                    }
                    if (!ok) continue;
                }
                out_pods[w] = e.pod;
                out_tiers[w] = e.tier;
                ++w;
                ++c;
            }
            out_counts[i] = c;
        }
        return n_keys;
    }

    // Read-only lookup: identical walk and outputs to lookup(), but takes
    // the SHARED lock and never promotes recency — safe under concurrent
    // apply, and many readers proceed in parallel. The price is that a
    // read-side scan leaves LRU order untouched (a key served only via
    // lookup_ro ages as if unread); the sharded read fan accepts that so
    // score reads never serialise against event ingest.
    uint64_t lookup_ro(uint32_t model, const uint64_t* hashes,
                       uint64_t n_keys, const uint32_t* filter,
                       uint64_t n_filter, uint32_t* out_pods,
                       uint8_t* out_tiers, uint32_t* out_counts) const {
        std::shared_lock<std::shared_mutex> g(mu_);
        uint64_t w = 0;
        for (uint64_t i = 0; i < n_keys; ++i) {
            auto it = map_.find({hashes[i], model});
            if (it == map_.end()) {            // missing: chain continues
                out_counts[i] = 0;
                continue;
            }
            const Node* node = it->second;
            if (node->pods.empty()) return i;   // present-but-empty: stop
            uint32_t c = 0;
            for (const Entry& e : node->pods) {
                if (n_filter) {
                    bool ok = false;
                    for (uint64_t f = 0; f < n_filter; ++f) {
                        if (filter[f] == e.pod) { ok = true; break; }
                    }
                    if (!ok) continue;
                }
                out_pods[w] = e.pod;
                out_tiers[w] = e.tier;
                ++w;
                ++c;
            }
            out_counts[i] = c;
        }
        return n_keys;
    }

    // Fused longest-prefix scoring (the read path's lookup+score in one
    // call). Scoring semantics of kvcache/scorer.py LongestPrefixScorer:
    // pods hit at key 0 seed the active set with score 1; each following key
    // intersects it and increments the survivors; any miss (absent key or
    // empty intersection) ends the streak. Pod ids are deduped across tiers.
    //
    // The WALK matches InMemoryIndex.lookup exactly — every present key in
    // the chain is LRU-promoted even past holes or after the streak dies,
    // and only a present-but-empty key stops the walk — so backend recency
    // behavior is identical whether the fused or two-step path runs.
    // out_hits receives the number of keys with >=1 filter-surviving pod
    // (the plain path's lookup_hits metric). Returns the number of scored
    // pods written to out arrays (bounded by pods_per_key).
    uint64_t score(uint32_t model, const uint64_t* hashes, uint64_t n_keys,
                   const uint32_t* filter, uint64_t n_filter,
                   uint32_t* out_pods, uint32_t* out_scores,
                   uint64_t* out_hits) {
        std::unique_lock<std::shared_mutex> g(mu_);
        if (out_hits) *out_hits = 0;
        if (n_keys == 0) return 0;

        std::vector<uint32_t> scored_pods;   // pods seeded at key 0 (dedup)
        std::vector<uint32_t> scores;
        std::vector<uint32_t> active;        // indices into scored_pods
        bool streak = true;

        auto visible = [&](uint32_t pod) {
            if (!n_filter) return true;
            for (uint64_t f = 0; f < n_filter; ++f)
                if (filter[f] == pod) return true;
            return false;
        };

        for (uint64_t i = 0; i < n_keys; ++i) {
            auto it = map_.find({hashes[i], model});
            if (it == map_.end()) {  // hole: streak dies, walk continues
                streak = false;
                continue;
            }
            Node* node = it->second;
            promote(node);
            if (node->pods.empty()) break;  // lookup's early-stop

            if (out_hits) {
                for (const Entry& e : node->pods) {
                    if (visible(e.pod)) { ++*out_hits; break; }
                }
            }
            if (!streak) continue;

            if (i == 0) {
                for (const Entry& e : node->pods) {
                    if (!visible(e.pod)) continue;
                    bool seen = false;
                    for (uint32_t p : scored_pods)
                        if (p == e.pod) { seen = true; break; }
                    if (seen) continue;
                    active.push_back(uint32_t(scored_pods.size()));
                    scored_pods.push_back(e.pod);
                    scores.push_back(1);
                }
            } else {
                std::vector<uint32_t> next;
                next.reserve(active.size());
                for (uint32_t idx : active) {
                    for (const Entry& e : node->pods) {
                        if (e.pod == scored_pods[idx]) {
                            scores[idx] += 1;
                            next.push_back(idx);
                            break;
                        }
                    }
                }
                active.swap(next);
            }
            if (active.empty()) streak = false;
        }

        for (size_t i = 0; i < scored_pods.size(); ++i) {
            out_pods[i] = scored_pods[i];
            out_scores[i] = scores[i];
        }
        return scored_pods.size();
    }

    // Fleet self-healing sweep: remove every entry of `pod` (all models,
    // all tiers), deleting keys whose pod set empties. Walks the LRU list
    // once without touching recency. Returns entries removed.
    uint64_t evict_pod(uint32_t pod) {
        std::unique_lock<std::shared_mutex> g(mu_);
        uint64_t removed = 0;
        Node* n = head_;
        while (n) {
            Node* next = n->next;
            auto& v = n->pods;
            for (size_t p = v.size(); p > 0; --p) {
                if (v[p - 1].pod == pod) {
                    v.erase(v.begin() + long(p - 1));
                    ++removed;
                }
            }
            if (v.empty()) remove_node(n);
            n = next;
        }
        return removed;
    }

    uint64_t size() {
        std::unique_lock<std::shared_mutex> g(mu_);
        return map_.size();
    }

    // Read-only node fetch for the cross-shard fused scorer. Caller must
    // hold a shared lock on mutex() for the duration of use.
    const std::vector<Entry>* find_ro(uint32_t model, uint64_t hash) const {
        auto it = map_.find({hash, model});
        return it == map_.end() ? nullptr : &it->second->pods;
    }

    // Distinct pods currently holding >= 1 entry: exact occupancy for the
    // kvcache_index_pods / kvcache_index_shard_pods gauges (scrape-driven
    // O(entries) walk under the shared lock; recency untouched). Writes up
    // to `cap` pod ids into out_ids, returns the distinct count.
    uint64_t distinct_pods(uint32_t* out_ids, uint64_t cap) const {
        std::shared_lock<std::shared_mutex> g(mu_);
        std::unordered_map<uint32_t, bool> seen;
        uint64_t w = 0;
        for (const Node* n = head_; n; n = n->next) {
            for (const Entry& e : n->pods) {
                auto ins = seen.emplace(e.pod, true);
                if (ins.second && w < cap) out_ids[w++] = e.pod;
            }
        }
        return seen.size();
    }

    std::shared_mutex& mutex() const { return mu_; }

  private:
    Node* get_or_create(KeyT key) {
        auto it = map_.find(key);
        if (it != map_.end()) {
            promote(it->second);
            return it->second;
        }
        Node* node = new Node();
        node->key = key;
        node->pods.reserve(pods_per_key_);
        map_.emplace(key, node);
        push_front(node);
        if (map_.size() > max_keys_) remove_node(tail_);  // LRU key eviction
        return node;
    }

    void touch_pod(Node* node, Entry e) {
        auto& v = node->pods;
        for (size_t p = 0; p < v.size(); ++p) {
            if (v[p] == e) {                    // move-to-front
                v.erase(v.begin() + long(p));
                v.insert(v.begin(), e);
                return;
            }
        }
        v.insert(v.begin(), e);
        if (v.size() > pods_per_key_) v.pop_back();  // pod LRU eviction
    }

    void push_front(Node* node) {
        node->prev = nullptr;
        node->next = head_;
        if (head_) head_->prev = node;
        head_ = node;
        if (!tail_) tail_ = node;
    }

    void unlink(Node* node) {
        if (node->prev) node->prev->next = node->next; else head_ = node->next;
        if (node->next) node->next->prev = node->prev; else tail_ = node->prev;
        node->prev = node->next = nullptr;
    }

    void promote(Node* node) {
        if (node == head_) return;
        unlink(node);
        push_front(node);
    }

    void remove_node(Node* node) {
        unlink(node);
        map_.erase(node->key);
        delete node;
    }

    uint64_t max_keys_;
    uint32_t pods_per_key_;
    mutable std::shared_mutex mu_;
    std::unordered_map<KeyT, Node*, KeyHash> map_;
    Node* head_ = nullptr;
    Node* tail_ = nullptr;
};

// Cross-shard fused longest-prefix scoring: ONE call walks a chain whose
// keys are partitioned across several LruIndex instances (owners[i] names
// key i's shard), under every touched shard's SHARED lock — concurrent
// with applies on all shards, no recency mutation, and a single
// GIL-release round trip from Python instead of one per shard. Pod ids
// must be interned in one shared table across the shards (the Python
// binding's shard-group constructor guarantees it); scoring semantics are
// identical to LruIndex::score.
uint64_t score_sharded_impl(LruIndex** shards, uint64_t n_shards,
                            uint32_t model, const uint64_t* hashes,
                            const uint32_t* owners, uint64_t n_keys,
                            const uint32_t* filter, uint64_t n_filter,
                            uint32_t* out_pods, uint32_t* out_scores,
                            uint64_t* out_hits) {
    if (out_hits) *out_hits = 0;
    if (n_keys == 0 || n_shards == 0) return 0;

    // Shared-lock every distinct shard once, in address order (a canonical
    // order makes multi-lock acquisition cycle-free by construction).
    std::vector<LruIndex*> uniq(shards, shards + n_shards);
    std::sort(uniq.begin(), uniq.end());
    uniq.erase(std::unique(uniq.begin(), uniq.end()), uniq.end());
    std::vector<std::shared_lock<std::shared_mutex>> locks;
    locks.reserve(uniq.size());
    for (LruIndex* s : uniq) locks.emplace_back(s->mutex());

    std::vector<uint32_t> scored_pods;
    std::vector<uint32_t> scores;
    std::vector<uint32_t> active;
    bool streak = true;

    auto visible = [&](uint32_t pod) {
        if (!n_filter) return true;
        for (uint64_t f = 0; f < n_filter; ++f)
            if (filter[f] == pod) return true;
        return false;
    };

    for (uint64_t i = 0; i < n_keys; ++i) {
        if (owners[i] >= n_shards) { streak = false; continue; }
        const std::vector<Entry>* pods =
            shards[owners[i]]->find_ro(model, hashes[i]);
        if (pods == nullptr) {  // hole: streak dies, walk continues
            streak = false;
            continue;
        }
        if (pods->empty()) break;  // lookup's early-stop

        if (out_hits) {
            for (const Entry& e : *pods) {
                if (visible(e.pod)) { ++*out_hits; break; }
            }
        }
        if (!streak) continue;

        if (i == 0) {
            for (const Entry& e : *pods) {
                if (!visible(e.pod)) continue;
                bool seen = false;
                for (uint32_t p : scored_pods)
                    if (p == e.pod) { seen = true; break; }
                if (seen) continue;
                active.push_back(uint32_t(scored_pods.size()));
                scored_pods.push_back(e.pod);
                scores.push_back(1);
            }
        } else {
            std::vector<uint32_t> next;
            next.reserve(active.size());
            for (uint32_t idx : active) {
                for (const Entry& e : *pods) {
                    if (e.pod == scored_pods[idx]) {
                        scores[idx] += 1;
                        next.push_back(idx);
                        break;
                    }
                }
            }
            active.swap(next);
        }
        if (active.empty()) streak = false;
    }

    for (size_t i = 0; i < scored_pods.size(); ++i) {
        out_pods[i] = scored_pods[i];
        out_scores[i] = scores[i];
    }
    return scored_pods.size();
}

}  // namespace

extern "C" {

void* lruidx_create(uint64_t max_keys, uint32_t pods_per_key) {
    return new LruIndex(max_keys, pods_per_key);
}

void lruidx_destroy(void* h) { delete static_cast<LruIndex*>(h); }

void lruidx_add(void* h, uint32_t model, const uint64_t* hashes,
                uint64_t n_keys, const uint32_t* pods, const uint8_t* tiers,
                uint64_t n_entries) {
    static_cast<LruIndex*>(h)->add(model, hashes, n_keys, pods, tiers,
                                   n_entries);
}

void lruidx_evict(void* h, uint32_t model, uint64_t hash,
                  const uint32_t* pods, const uint8_t* tiers,
                  uint64_t n_entries) {
    static_cast<LruIndex*>(h)->evict(model, hash, pods, tiers, n_entries);
}

uint64_t lruidx_lookup(void* h, uint32_t model, const uint64_t* hashes,
                       uint64_t n_keys, const uint32_t* filter,
                       uint64_t n_filter, uint32_t* out_pods,
                       uint8_t* out_tiers, uint32_t* out_counts) {
    return static_cast<LruIndex*>(h)->lookup(model, hashes, n_keys, filter,
                                             n_filter, out_pods, out_tiers,
                                             out_counts);
}

uint64_t lruidx_lookup_ro(void* h, uint32_t model, const uint64_t* hashes,
                          uint64_t n_keys, const uint32_t* filter,
                          uint64_t n_filter, uint32_t* out_pods,
                          uint8_t* out_tiers, uint32_t* out_counts) {
    return static_cast<LruIndex*>(h)->lookup_ro(model, hashes, n_keys, filter,
                                                n_filter, out_pods, out_tiers,
                                                out_counts);
}

uint64_t lruidx_score(void* h, uint32_t model, const uint64_t* hashes,
                      uint64_t n_keys, const uint32_t* filter,
                      uint64_t n_filter, uint32_t* out_pods,
                      uint32_t* out_scores, uint64_t* out_hits) {
    return static_cast<LruIndex*>(h)->score(model, hashes, n_keys, filter,
                                            n_filter, out_pods, out_scores,
                                            out_hits);
}

uint64_t lruidx_evict_pod(void* h, uint32_t pod) {
    return static_cast<LruIndex*>(h)->evict_pod(pod);
}

uint64_t lruidx_distinct_pods(void* h, uint32_t* out_ids, uint64_t cap) {
    return static_cast<LruIndex*>(h)->distinct_pods(out_ids, cap);
}

uint64_t lruidx_score_sharded(void** shard_handles, uint64_t n_shards,
                              uint32_t model, const uint64_t* hashes,
                              const uint32_t* owners, uint64_t n_keys,
                              const uint32_t* filter, uint64_t n_filter,
                              uint32_t* out_pods, uint32_t* out_scores,
                              uint64_t* out_hits) {
    return score_sharded_impl(reinterpret_cast<LruIndex**>(shard_handles),
                              n_shards, model, hashes, owners, n_keys,
                              filter, n_filter, out_pods, out_scores,
                              out_hits);
}

uint64_t lruidx_size(void* h) { return static_cast<LruIndex*>(h)->size(); }

}  // extern "C"
