"""Cross-pod KV-block transfer: pull warm prefixes instead of recomputing.

The indexer/scorer/router stack can only route *toward* warmth; this
subsystem moves the KV pages themselves, turning every pod's HBM + host
tiers into a fleet-wide prefix cache (Mooncake/LMCache-style disaggregated
KV). Three pieces:

- ``protocol``: msgpack wire format for block-chain fetches (the event
  plane's framing idioms, applied to bulk page payloads);
- ``service`` / ``client``: ZMQ ROUTER/DEALER request channel — each pod
  binds an export service; peers fetch prefix chains by block hash;
- ``cost_model``: measured bytes/s-vs-tokens/s accounting behind the
  router's route-to-warm / pull-then-compute / cold-recompute decision;
- ``remote_store``: the ``remote`` tier's holder side (``REMOTE_TIER``) —
  wire-ready demoted blocks, LRU-bounded, published to the index under
  the HOLDER's identity with ``medium="remote"``.

The engine-side export/import endpoints live in ``server/engine.py`` and
``server/block_manager.py``; ``server/serve.py`` wires the service into a
pod (``TRANSFER_ENDPOINT``; off by default = legacy behavior).
"""

from .client import (
    CircuitBreaker,
    KVTransferClient,
    TransferClientConfig,
    TransferClientPool,
    TransferError,
)
from .cost_model import TransferCostModel, TransferCostModelConfig
from .protocol import (
    BlockPayload,
    MigrationPayload,
    decode_migrate,
    decode_migrate_ack,
    decode_push,
    decode_push_ack,
    decode_request,
    decode_response,
    encode_migrate,
    encode_migrate_ack,
    encode_push,
    encode_push_ack,
    encode_request,
    encode_response,
)
from .remote_store import RemoteBlockStore, RemoteStoreConfig
from .service import KVTransferService, TransferServiceConfig

__all__ = [
    "BlockPayload",
    "CircuitBreaker",
    "KVTransferClient",
    "KVTransferService",
    "MigrationPayload",
    "RemoteBlockStore",
    "RemoteStoreConfig",
    "TransferClientConfig",
    "TransferClientPool",
    "TransferCostModel",
    "TransferCostModelConfig",
    "TransferError",
    "TransferServiceConfig",
    "decode_migrate",
    "decode_migrate_ack",
    "encode_migrate",
    "encode_migrate_ack",
    "decode_push",
    "decode_push_ack",
    "decode_request",
    "decode_response",
    "encode_push",
    "encode_push_ack",
    "encode_request",
    "encode_response",
]
