"""Headline benchmark: KV-cache-aware ("precise") routing vs round-robin.

Reproduces the reference's capacity benchmarks (`benchmarking/37-capacity`,
`73-capacity`: precise vs random/default scheduling under shared-prefix
Poisson load) on TPU with the in-tree JAX serving engine, per the
BASELINE.json north star: *p50-TTFT reduction vs round-robin on
shared-prefix load*.

Method — virtual-clock fleet co-simulation on one real chip:

- N "pods", each a real `Engine` (own KV page pool, block manager,
  continuous-batching scheduler) running the real Pallas paged-attention
  model; all pods share one copy of the weights (pods differ only by KV
  cache state, which is what routing exploits).
- Each pod has a virtual clock advanced by the *measured wall time* of its
  engine steps on the TPU. Pods are independent machines in a real
  deployment, so time-slicing them on one chip while accounting time
  per-pod is a faithful simulation of fleet behavior.
- KV events flow through the real write path: BlockStored/BlockRemoved →
  msgpack EventBatch → sharded KVEventsPool → shared in-memory block index
  (SURVEY §3.2). The router's read path is `KVCacheIndexer.score_tokens`
  (chunked sha256-CBOR hashing + longest-prefix scorer, SURVEY §3.1).
- Workload: G prefix groups (default 32-way), each a shared prefix of
  `PREFIX_LEN` tokens plus a unique suffix; Poisson arrivals.
- Policies: `round_robin` and `precise` (max indexer score, ties to the
  least-loaded pod). p50 TTFT measured in virtual time for each.

Prints ONE JSON line:
  {"metric": "p50_ttft_reduction_vs_round_robin", "value": <pct>,
   "unit": "%", "vs_baseline": <pct/50>}
vs_baseline >= 1.0 means the north-star target (>=50% reduction) is met.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

MODEL_NAME = "bench/llama"


def build_workload(rng, n_groups, reqs_per_group, prefix_len, suffix_len, vocab, qps):
    """Poisson arrival schedule over shared-prefix groups.

    Returns [(arrival_time, group_id, tokens)] sorted by arrival, with
    group order shuffled so consecutive arrivals mix groups.
    """
    prefixes = [
        rng.integers(0, vocab, prefix_len).tolist() for _ in range(n_groups)
    ]
    reqs = []
    for g in range(n_groups):
        for _ in range(reqs_per_group):
            reqs.append((g, prefixes[g] + rng.integers(0, vocab, suffix_len).tolist()))
    rng.shuffle(reqs)
    t = 0.0
    out = []
    for g, toks in reqs:
        t += float(rng.exponential(1.0 / qps))
        out.append((t, g, toks))
    return out


class Pod:
    """One simulated serving replica: a real engine + a virtual clock."""

    def __init__(self, pod_id, engine_cfg, params, publish):
        from llm_d_kv_cache_manager_tpu.server.engine import Engine

        self.pod_id = pod_id
        self.engine = Engine(engine_cfg, params=params, on_events=publish(pod_id))
        self.clock = 0.0
        self._first_token_seen: set[int] = set()

    @property
    def load(self) -> int:
        s = self.engine.scheduler
        return len(s.waiting) + len(s.running)

    def step_timed(self, ttfts, arrivals):
        t0 = time.perf_counter()
        done = self.engine.step()
        self.clock += time.perf_counter() - t0
        # Record first-token virtual times (running lanes catch prefill
        # first-tokens; `done` catches sequences that finished this step).
        sched = self.engine.scheduler
        for seq in list(sched.running) + done:
            if seq.num_generated >= 1 and seq.seq_id not in self._first_token_seen:
                self._first_token_seen.add(seq.seq_id)
                if seq.seq_id in arrivals:
                    ttfts.append(self.clock - arrivals[seq.seq_id])

    def advance_to(self, t, ttfts, arrivals):
        while self.engine.has_work and self.clock < t:
            self.step_timed(ttfts, arrivals)

    def drain(self, ttfts, arrivals, max_steps=200_000):
        for _ in range(max_steps):
            if not self.engine.has_work:
                return
            self.step_timed(ttfts, arrivals)
        raise RuntimeError("pod failed to drain")


def make_event_pipeline(index, n_pods):
    """Real write path: msgpack-encode batches, shard into the events pool."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
        KVEventsPool,
        KVEventsPoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import EventBatch
    from llm_d_kv_cache_manager_tpu.kvcache.kvevents.pool import Message

    pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=min(4, n_pods)))
    pool.start()

    def publish(pod_id):
        pod_name = f"tpu-pod-{pod_id}"

        def on_events(events):
            batch = EventBatch(ts=0.0, events=list(events))
            pool.add_task(
                Message(
                    topic=f"kv@{pod_name}@{MODEL_NAME}",
                    pod_identifier=pod_name,
                    model_name=MODEL_NAME,
                    payload=batch.to_payload(),
                )
            )

        return on_events

    return pool, publish


def run_policy(policy, workload, params, engine_cfg, n_pods, max_new_tokens):
    """Run one routing policy over the workload; returns virtual-time TTFTs."""
    from llm_d_kv_cache_manager_tpu.kvcache import (
        KVCacheIndexer,
        KVCacheIndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    page = engine_cfg.block_manager.page_size
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=page))
    )
    pool, publish = make_event_pipeline(indexer.kv_block_index, n_pods)
    pods = [Pod(i, engine_cfg, params, publish) for i in range(n_pods)]
    pod_names = [f"tpu-pod-{i}" for i in range(n_pods)]

    ttfts: list[float] = []
    arrivals: dict[int, float] = {}
    rr = 0
    for t, _group, tokens in workload:
        # Advance every pod to the arrival instant so the index reflects
        # fleet state at routing time, then drain in-flight events.
        for pod in pods:
            pod.advance_to(t, ttfts, arrivals)
        if policy == "precise":
            pool.drain(timeout=10.0)
            scores = indexer.score_tokens(tokens, MODEL_NAME, pod_names)
            best = max(
                range(n_pods),
                key=lambda i: (scores.get(pod_names[i], 0), -pods[i].load, -i),
            )
        else:
            best = rr % n_pods
            rr += 1
        pod = pods[best]
        if not pod.engine.has_work:
            pod.clock = max(pod.clock, t)
        seq = pod.engine.add_request(
            tokens, SamplingParams(max_new_tokens=max_new_tokens)
        )
        arrivals[seq.seq_id] = t
    for pod in pods:
        pod.drain(ttfts, arrivals)
    pool.drain(timeout=10.0)
    pool.shutdown()
    indexer.shutdown()
    n_req = len(workload)
    assert len(ttfts) == n_req, f"lost requests: {len(ttfts)}/{n_req}"
    return np.asarray(ttfts)


def warmup(params, engine_cfg, prefix_len, suffix_len, vocab, max_new_tokens):
    """Compile every jit shape the measured runs will hit (cold prefill,
    warm suffix-only prefill, mixed batch, decode) on a scratch engine."""
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    rng = np.random.default_rng(1234)
    eng = Engine(engine_cfg, params=params)
    prefix = rng.integers(0, vocab, prefix_len).tolist()

    def req():
        return eng.add_request(
            prefix + rng.integers(0, vocab, suffix_len).tolist(),
            SamplingParams(max_new_tokens=max_new_tokens),
        )

    req()  # cold: (chunk=full, ctx=0)
    eng.run_until_complete()
    req()  # warm: (chunk=suffix bucket, ctx=max)
    eng.run_until_complete()
    cold = rng.integers(0, vocab, prefix_len + suffix_len).tolist()
    eng.add_request(cold, SamplingParams(max_new_tokens=max_new_tokens))
    req()  # mixed cold+warm batch: (chunk=full, ctx=max)
    eng.run_until_complete()


def main() -> int:
    import jax
    import jax.numpy as jnp

    from llm_d_kv_cache_manager_tpu.models import llama
    from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig
    from llm_d_kv_cache_manager_tpu.server.block_manager import BlockManagerConfig
    from llm_d_kv_cache_manager_tpu.server.engine import EngineConfig
    from llm_d_kv_cache_manager_tpu.server.scheduler import SchedulerConfig

    on_tpu = jax.default_backend() == "tpu"
    smoke = os.environ.get("BENCH_SMOKE") == "1" or not on_tpu

    if smoke:
        model_cfg = llama.TINY_LLAMA
        n_pods, n_groups, reqs_per_group = 2, 4, 3
        prefix_len, suffix_len, max_new = 64, 16, 4
        total_pages, page = 256, 16
        decode_burst = 2
        interpret = not on_tpu
    else:
        # Llama-3-8B-family architecture scaled (1.4B) so a 4-pod fleet
        # (one weight copy + 4 KV pools) fits one v5e chip while cold
        # prefills stay compute-bound — the analogue of the reference's
        # 8k-prefix/70B capacity runs.
        model_cfg = LlamaConfig(
            vocab_size=32_000,
            hidden_size=3072,
            intermediate_size=8192,
            n_layers=12,
            n_heads=24,
            n_kv_heads=8,
            rope_scaling=llama.LLAMA_3_8B.rope_scaling,
            dtype=jnp.bfloat16,
        )
        n_pods, n_groups, reqs_per_group = 4, 32, 8
        prefix_len, suffix_len, max_new = 4096, 48, 16
        # Pool sized so a precise pod's share of prefixes (~8 groups ×
        # 257 pages) stays resident while a round-robin pod (which sees
        # all 32 prefixes) thrashes its prefix cache — the regime of the
        # reference's capacity benchmarks.
        total_pages, page = 2560, 16
        decode_burst = 8
        interpret = False

    max_len = prefix_len + suffix_len + max_new + page
    engine_cfg = EngineConfig(
        model=model_cfg,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=page),
        scheduler=SchedulerConfig(max_prefill_batch=4, max_prefill_tokens=8192),
        max_model_len=max_len,
        decode_batch_size=8,
        decode_steps_per_iter=decode_burst,
        prefill_bucket=64,
        # Pin warm prefills AND decode tables to a single width → one
        # compiled shape each. Mid-run XLA compiles (~30-60s on this model)
        # otherwise land in whichever pod's virtual clock hits a fresh
        # decode width first, blowing up its tail latencies.
        prefill_ctx_bucket=-(-max_len // page),
        decode_pages_bucket=-(-max_len // page),
        interpret=interpret,
    )

    params = llama.init_params(jax.random.PRNGKey(0), model_cfg)
    jax.block_until_ready(params)

    warmup(params, engine_cfg, prefix_len, suffix_len, model_cfg.vocab_size, max_new)

    # Calibrate the arrival rate off the measured cold-request service time
    # so round-robin saturates (its regime in the reference benchmarks:
    # random/RR explodes to ~85 s TTFT while precise stays sub-second)
    # without hand-tuned absolute QPS.
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    cal_rng = np.random.default_rng(7)
    cal_eng = Engine(engine_cfg, params=params)
    batch_w = engine_cfg.scheduler.max_prefill_batch
    t0 = time.perf_counter()
    for _ in range(batch_w):
        cal_eng.add_request(
            cal_rng.integers(0, model_cfg.vocab_size, prefix_len + suffix_len).tolist(),
            SamplingParams(max_new_tokens=max_new),
        )
    cal_eng.run_until_complete()
    t_cold = (time.perf_counter() - t0) / batch_w  # per-request, batched cold
    del cal_eng  # release its KV pool before building the fleet
    qps = 1.4 * n_pods / max(t_cold, 1e-4)

    rng = np.random.default_rng(42)
    workload = build_workload(
        rng, n_groups, reqs_per_group, prefix_len, suffix_len,
        model_cfg.vocab_size, qps,
    )

    results = {}
    for policy in ("round_robin", "precise"):
        ttfts = run_policy(policy, workload, params, engine_cfg, n_pods, max_new)
        results[policy] = {
            "p50_ttft_s": float(np.median(ttfts)),
            "p90_ttft_s": float(np.percentile(ttfts, 90)),
            "mean_ttft_s": float(np.mean(ttfts)),
        }

    p50_rr = results["round_robin"]["p50_ttft_s"]
    p50_pr = results["precise"]["p50_ttft_s"]
    reduction = 100.0 * (p50_rr - p50_pr) / p50_rr if p50_rr > 0 else 0.0

    detail = {
        "backend": jax.default_backend(),
        "smoke": smoke,
        "n_pods": n_pods,
        "n_groups": n_groups,
        "n_requests": len(workload),
        "prefix_len": prefix_len,
        "qps": round(qps, 2),
        "results": results,
    }
    print(json.dumps(detail), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "p50_ttft_reduction_vs_round_robin",
                "value": round(reduction, 2),
                "unit": "%",
                "vs_baseline": round(reduction / 50.0, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
