from .events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    Event,
    EventBatch,
    decode_event_batch,
)
from .pool import KVEventsPool, KVEventsPoolConfig, Message, fnv1a_32
from .zmq_subscriber import ZMQSubscriber, ZMQSubscriberConfig, parse_topic
from .publisher import ZMQPublisher, ZMQPublisherConfig

__all__ = [
    "AllBlocksCleared",
    "BlockRemoved",
    "BlockStored",
    "Event",
    "EventBatch",
    "decode_event_batch",
    "KVEventsPool",
    "KVEventsPoolConfig",
    "Message",
    "fnv1a_32",
    "ZMQSubscriber",
    "ZMQSubscriberConfig",
    "parse_topic",
    "ZMQPublisher",
    "ZMQPublisherConfig",
]
