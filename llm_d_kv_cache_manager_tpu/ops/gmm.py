"""Grouped (ragged) matmul for MoE routed dispatch on the MXU.

The routed MoE pipeline (``models/llama._moe_mlp_routed``) sorts the
``n*k`` (token, slot) rows by expert so each expert's rows form one
contiguous segment, then needs ``out[r] = lhs[r] @ rhs[g(r)]`` where
``g(r)`` is the expert owning row ``r``. ``jax.lax.ragged_dot`` expresses
this but runs far below MXU utilization at our shapes (~19 TFLOP/s
effective vs the dense einsum's ~141 at Qwen3-30B geometry —
``benchmarking/results/moe_dispatch.md``), and XLA does not fuse int8
dequantization into its group-streamed operand, making int8 experts 2.5×
SLOWER than bf16 there.

Two kernels, one wrapper:

- **bf16/f32**: the Pallas megablox ``gmm``
  (``jax.experimental.pallas.ops.tpu.megablox`` — tiled MXU grouped
  matmul; boundary tiles are visited once per intersecting group with
  masked stores, so there is no capacity padding and no dropped tokens).
- **int8 experts** (``QuantizedTensor`` rhs): our own kernel below, same
  tiling scheme, with the two int8-specific pieces megablox rejects:
  the int8 payload tile is DMA'd at half the HBM bytes and converted to
  f32 IN VMEM right before the MXU dot (the fusion ``ragged_dot``
  can't do), and the per-output-channel scale — constant along the
  contraction axis, so it commutes out of the dot — is applied as a
  per-row gathered multiply on the output, where XLA fuses it into the
  consuming elementwise ops.

No reference counterpart: the reference delegates model execution to
vLLM; this is in-tree TPU serving work (SURVEY §7 stage 4-5).
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.quant import QuantizedTensor

# JAX renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams (~0.5);
# resolve whichever spelling this install has so the kernel runs on both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

#: (tm, tk, tn) tile-size ceilings, from the on-chip sweep at Qwen3-30B
#: geometry (128 experts, d=2048, f=768, 16k rows): 256-row tiles balance
#: boundary-visit waste (visits ≈ max(m_tiles, nonempty groups) whatever
#: tm is) against MXU pipeline depth, and large tk/tn cut grid steps —
#: (256,1024,768) measured 5.0 ms vs 7.1 ms at (256,512,512) and 7.5 ms
#: at (512,512,512) for one 16k-row grouped matmul. Tiles stay well
#: under VMEM (rhs tile 1.5 MB bf16).
DEFAULT_TILING = (256, 1024, 768)


def _round8(m: int) -> int:
    return -(-m // 8) * 8


def _divisor_tile(dim: int, cap: int) -> int:
    """Largest lane-aligned tile <= cap that divides ``dim`` exactly (the
    kernels skip remainder-tile masking). A dim with no such divisor runs
    as ONE full-width tile — fine for small (tiny-test) geometries, but a
    LARGE unaligned dim would silently blow VMEM with no pointer at the
    cause, so that case fails loudly instead."""
    if dim % 128 == 0:
        for t in range(min(cap, dim), 127, -128):
            if dim % t == 0:
                return t
    if dim > cap:
        raise ValueError(
            f"gmm kernel tiling: dim {dim} is not 128-aligned and exceeds "
            f"the tile cap {cap} (a full-width tile would exhaust VMEM); "
            "use moe_gmm='xla' (ragged_dot) for this geometry"
        )
    return dim


def grouped_matmul(
    lhs: jnp.ndarray,  # [rows, d] group-sorted (expert-contiguous) rows
    rhs: Union[jnp.ndarray, QuantizedTensor],  # [E, d, f] expert stack
    group_sizes: jnp.ndarray,  # [E] int32 rows per expert
    *,
    row_group_ids: Optional[jnp.ndarray] = None,  # [rows] expert of row
    interpret: bool = False,
    use_kernel: bool = True,
) -> jnp.ndarray:
    """``out[r] = lhs[r] @ rhs[g(r)]`` over expert-contiguous rows.

    With a ``QuantizedTensor`` rhs, ``row_group_ids`` (the sorted expert id
    per row — the caller already has it) is required to apply the
    per-output-channel scales to the output rows.

    ``use_kernel=False`` falls back to ``jax.lax.ragged_dot`` with
    whole-stack dequantization — the parity oracle for tests.
    """
    if isinstance(rhs, QuantizedTensor):
        if row_group_ids is None:
            raise ValueError("row_group_ids required for quantized rhs")
        q, scale = rhs.q, rhs.scale  # [E, d, f] int8, [E, 1, f] f32
        if not use_kernel:
            w = q.astype(lhs.dtype) * scale.astype(lhs.dtype)
            return jax.lax.ragged_dot(lhs, w, group_sizes)
        out = _gmm_int8(lhs, q, group_sizes, interpret=interpret)  # f32
        # Per-row scale: scale[g(r), 0, :] — fuses downstream.
        row_scale = scale[row_group_ids, 0, :]  # [rows, f]
        return (out * row_scale).astype(lhs.dtype)
    if not use_kernel:
        return jax.lax.ragged_dot(lhs, rhs, group_sizes)
    return _gmm_library(lhs, rhs, group_sizes, interpret=interpret)


def _gmm_library(lhs, rhs, group_sizes, *, interpret: bool):
    from jax.experimental.pallas.ops.tpu.megablox import gmm as mb_gmm

    rows, d = lhs.shape
    f = rhs.shape[2]
    tm, tk, tn = DEFAULT_TILING
    tm = min(tm, max(_round8(rows), 8))
    # megablox requires m % tm == 0: pad rows (beyond every group — the
    # pad region's output is garbage and sliced off).
    pad = (-rows) % tm
    if pad:
        lhs = jnp.pad(lhs, ((0, pad), (0, 0)))
    out = mb_gmm(
        lhs,
        rhs,
        group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.float32,
        tiling=(tm, _divisor_tile(d, tk), _divisor_tile(f, tn)),
        interpret=interpret,
    )
    return out[:rows].astype(lhs.dtype)


# -- int8-rhs grouped matmul kernel --------------------------------------
#
# Same scheme as megablox gmm: grid (tiles_n, active_m_tiles, tiles_k)
# where the middle dimension walks (m-tile, group) intersections in row
# order — a boundary m-tile spanning G groups is visited G times, each
# visit computing the full tile on the MXU but storing only its own
# group's rows. Group metadata (which group / which m-tile per grid step)
# comes from the library's make_group_metadata; lhs rows are pre-padded to
# a tile multiple and the pad region (beyond every group) is sliced off.


def _int8_gmm_kernel(
    group_metadata, lhs_ref, q_ref, out_ref, acc_ref, *, tiles_k, tm, tn
):
    group_offsets, group_ids, m_tile_ids = group_metadata
    grid_id = pl.program_id(1)
    k_i = pl.program_id(2)

    @pl.when(k_i == 0)
    def _zero_acc():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8 tile -> f32 happens HERE, in VMEM: HBM only ever streams the
    # 1-byte payload.
    acc_ref[...] += jax.lax.dot_general(
        lhs_ref[...].astype(jnp.float32),
        q_ref[...].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k_i == tiles_k - 1)
    def _store():
        # Store only this visit's group rows; preserve rows written by the
        # other groups sharing this m-tile (visited at adjacent grid ids).
        group_id = group_ids[grid_id]
        start = group_offsets[group_id]
        end = group_offsets[group_id + 1]
        row = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 0) + (
            m_tile_ids[grid_id] * tm
        )
        mask = (row >= start) & (row < end)
        out_ref[...] = jax.lax.select(mask, acc_ref[...], out_ref[...])


def _gmm_int8(lhs, q, group_sizes, *, interpret: bool):
    """Grouped matmul with an int8 expert stack; returns f32 [rows, f].

    Scales are NOT applied here — per-output-channel scales commute out of
    the contraction and are cheaper as a fused elementwise on the output.
    """
    from jax.experimental.pallas.ops.tpu.megablox.gmm import make_group_metadata

    rows, d = lhs.shape
    n_groups, _, f = q.shape
    tm = min(DEFAULT_TILING[0], max(_round8(rows), 8))
    tk = _divisor_tile(d, DEFAULT_TILING[1])
    tn = _divisor_tile(f, DEFAULT_TILING[2])
    tiles_k = d // tk
    tiles_n = f // tn

    pad = (-rows) % tm
    m = rows + pad
    if pad:
        lhs = jnp.pad(lhs, ((0, pad), (0, 0)))

    group_metadata, num_active_tiles = make_group_metadata(
        group_sizes=group_sizes.astype(jnp.int32),
        m=m,
        tm=tm,
        start_group=jnp.asarray(0, jnp.int32),
        num_nonzero_groups=n_groups,
        visit_empty_groups=False,
    )

    def lhs_index(n_i, grid_id, k_i, meta):
        _, _, m_tile_ids = meta
        del n_i
        return m_tile_ids[grid_id], k_i

    def q_index(n_i, grid_id, k_i, meta):
        _, group_ids, _ = meta
        return group_ids[grid_id], k_i, n_i

    def out_index(n_i, grid_id, k_i, meta):
        _, _, m_tile_ids = meta
        del k_i
        return m_tile_ids[grid_id], n_i

    flops = 2 * m * d * f
    bytes_accessed = (
        lhs.size * lhs.itemsize * tiles_n + d * f * q.itemsize + m * f * 4
    )
    out = pl.pallas_call(
        functools.partial(_int8_gmm_kernel, tiles_k=tiles_k, tm=tm, tn=tn),
        out_shape=jax.ShapeDtypeStruct((m, f), jnp.float32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            in_specs=[
                pl.BlockSpec((tm, tk), lhs_index),
                pl.BlockSpec((None, tk, tn), q_index),
            ],
            out_specs=pl.BlockSpec((tm, tn), out_index),
            grid=(tiles_n, num_active_tiles, tiles_k),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops, bytes_accessed=bytes_accessed, transcendentals=0
        ),
        interpret=interpret,
    )(group_metadata, lhs, q)
    return out[:rows]
