"""Minimal kustomize build + schema validation for `deploy/`.

The reference smoke-tests its full stack against a real API server
(`/root/reference/tests/kind-vllm-cpu.sh:22-80`); this image has neither
kind nor the kustomize/kubeconform binaries, so this module implements the
EXACT feature subset our kustomizations use — `resources` (files and
nested kustomization dirs), `namespace`, `configMapGenerator`
(`envs`, `behavior: create|replace`, `disableNameSuffixHash`), and
`replicas` — then validates the rendered objects the way kubeconform +
an apply dry-run would catch drift:

- minimal per-kind schema shapes (apiVersion/kind/metadata.name, selector
  vs template labels, ports, container basics);
- cross-references: every `envFrom.configMapRef` resolves to a rendered
  ConfigMap, StatefulSet `serviceName` resolves to a headless Service,
  Service selectors match some workload's pod labels, `replicas`
  overrides name an existing workload;
- generator contract: `behavior: replace` must replace a map the base
  actually generates, env files must exist and parse.

Real kustomize remains the authority; any feature outside the subset
fails loudly here rather than silently rendering wrong.
"""

from __future__ import annotations

import pathlib

import yaml


class KustomizeError(ValueError):
    pass


_SUPPORTED_KEYS = {
    "apiVersion", "kind", "namespace", "resources", "configMapGenerator",
    "replicas",
}
_SUPPORTED_GEN_KEYS = {"name", "behavior", "envs", "options"}
#: cluster-scoped kinds never get the kustomization namespace
_CLUSTER_SCOPED = {"Namespace"}


def _load_env_file(path: pathlib.Path) -> dict[str, str]:
    if not path.exists():
        raise KustomizeError(f"configMapGenerator env file missing: {path}")
    out: dict[str, str] = {}
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            if "=" not in line:
                raise KustomizeError(f"{path}: malformed env line {line!r}")
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def build(dir_path: str | pathlib.Path) -> list[dict]:
    """Render a kustomization directory to a list of manifest objects."""
    root = pathlib.Path(dir_path)
    kfile = root / "kustomization.yaml"
    if not kfile.exists():
        raise KustomizeError(f"no kustomization.yaml in {root}")
    kust = yaml.safe_load(kfile.read_text()) or {}

    unknown = set(kust) - _SUPPORTED_KEYS
    if unknown:
        raise KustomizeError(
            f"{kfile}: unsupported kustomize features {sorted(unknown)} — "
            "extend kustomize_lite or validate with real kustomize"
        )

    docs: list[dict] = []
    for res in kust.get("resources", []):
        p = (root / res).resolve()
        if p.is_dir():
            docs.extend(build(p))
        else:
            for doc in yaml.safe_load_all(p.read_text()):
                if doc:
                    docs.append(doc)

    for gen in kust.get("configMapGenerator", []):
        unknown = set(gen) - _SUPPORTED_GEN_KEYS
        if unknown:
            raise KustomizeError(
                f"{kfile}: unsupported configMapGenerator keys "
                f"{sorted(unknown)}"
            )
        if not (gen.get("options") or {}).get("disableNameSuffixHash"):
            raise KustomizeError(
                f"{kfile}: configMapGenerator without "
                "disableNameSuffixHash — the lite builder does not "
                "implement suffix hashing"
            )
        data: dict[str, str] = {}
        for env in gen.get("envs", []):
            data.update(_load_env_file(root / env))
        cm = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": gen["name"]},
            "data": data,
        }
        behavior = gen.get("behavior", "create")
        existing = [
            i
            for i, d in enumerate(docs)
            if d.get("kind") == "ConfigMap"
            and d["metadata"]["name"] == gen["name"]
        ]
        if behavior == "replace":
            if not existing:
                raise KustomizeError(
                    f"{kfile}: behavior=replace but no base generates "
                    f"ConfigMap/{gen['name']}"
                )
            for i in existing:
                docs[i] = cm
        elif behavior == "create":
            if existing:
                raise KustomizeError(
                    f"{kfile}: ConfigMap/{gen['name']} already exists "
                    "(use behavior: replace)"
                )
            docs.append(cm)
        else:
            raise KustomizeError(f"{kfile}: unsupported behavior {behavior!r}")

    ns = kust.get("namespace")
    if ns:
        for doc in docs:
            if doc.get("kind") not in _CLUSTER_SCOPED:
                doc.setdefault("metadata", {})["namespace"] = ns

    for override in kust.get("replicas", []):
        matched = False
        for doc in docs:
            if (
                doc.get("kind") in ("StatefulSet", "Deployment")
                and doc["metadata"]["name"] == override["name"]
            ):
                doc["spec"]["replicas"] = override["count"]
                matched = True
        if not matched:
            raise KustomizeError(
                f"{kfile}: replicas override targets unknown workload "
                f"{override['name']!r}"
            )

    # Duplicate identity check (same kind+ns+name twice = apply conflict).
    seen: set[tuple] = set()
    for doc in docs:
        ident = (
            doc.get("kind"),
            (doc.get("metadata") or {}).get("namespace"),
            (doc.get("metadata") or {}).get("name"),
        )
        if ident in seen:
            raise KustomizeError(f"duplicate object {ident}")
        seen.add(ident)
    return docs


def _containers(doc: dict) -> list[dict]:
    return (
        doc.get("spec", {})
        .get("template", {})
        .get("spec", {})
        .get("containers", [])
    )


def validate(docs: list[dict]) -> None:
    """Schema-shape + cross-reference validation of rendered objects."""
    by_kind: dict[str, list[dict]] = {}
    for doc in docs:
        for key in ("apiVersion", "kind"):
            if not doc.get(key):
                raise KustomizeError(f"object missing {key}: {doc}")
        if not (doc.get("metadata") or {}).get("name"):
            raise KustomizeError(f"object missing metadata.name: {doc}")
        by_kind.setdefault(doc["kind"], []).append(doc)

    def names(kind):
        return {d["metadata"]["name"] for d in by_kind.get(kind, [])}

    # Namespaced objects must land in a namespace the build creates.
    created_ns = names("Namespace")
    for doc in docs:
        if doc["kind"] in _CLUSTER_SCOPED:
            continue
        ns = doc["metadata"].get("namespace")
        if ns and created_ns and ns not in created_ns:
            raise KustomizeError(
                f"{doc['kind']}/{doc['metadata']['name']} targets namespace "
                f"{ns!r} which the build does not create"
            )

    workloads = by_kind.get("StatefulSet", []) + by_kind.get("Deployment", [])
    pod_label_sets = []
    for wl in workloads:
        name = f"{wl['kind']}/{wl['metadata']['name']}"
        spec = wl.get("spec", {})
        tmpl_labels = (
            spec.get("template", {}).get("metadata", {}).get("labels", {})
        )
        pod_label_sets.append(tmpl_labels)
        sel = spec.get("selector", {}).get("matchLabels", {})
        if not sel:
            raise KustomizeError(f"{name}: missing selector.matchLabels")
        if any(tmpl_labels.get(k) != v for k, v in sel.items()):
            raise KustomizeError(
                f"{name}: selector {sel} does not match template labels "
                f"{tmpl_labels}"
            )
        if not _containers(wl):
            raise KustomizeError(f"{name}: no containers")
        for c in _containers(wl):
            if not c.get("image"):
                raise KustomizeError(f"{name}: container without image")
            for ef in c.get("envFrom", []):
                ref = (ef.get("configMapRef") or {}).get("name")
                if ref and ref not in names("ConfigMap"):
                    raise KustomizeError(
                        f"{name}: envFrom references ConfigMap {ref!r} "
                        "which the build does not render"
                    )
        if wl["kind"] == "StatefulSet":
            svc = spec.get("serviceName")
            if svc and svc not in names("Service"):
                raise KustomizeError(
                    f"{name}: serviceName {svc!r} has no rendered Service"
                )

    for svc in by_kind.get("Service", []):
        sel = svc.get("spec", {}).get("selector")
        if sel and not any(
            all(labels.get(k) == v for k, v in sel.items())
            for labels in pod_label_sets
        ):
            raise KustomizeError(
                f"Service/{svc['metadata']['name']}: selector {sel} matches "
                "no workload's pod labels"
            )
        if not svc.get("spec", {}).get("ports"):
            raise KustomizeError(
                f"Service/{svc['metadata']['name']}: no ports"
            )

    for cm in by_kind.get("ConfigMap", []):
        if not cm.get("data"):
            raise KustomizeError(
                f"ConfigMap/{cm['metadata']['name']}: empty data"
            )


def build_and_validate(dir_path: str | pathlib.Path) -> list[dict]:
    docs = build(dir_path)
    validate(docs)
    return docs


if __name__ == "__main__":  # pragma: no cover - CLI for fleet_smoke.sh
    import sys

    for d in sys.argv[1:]:
        rendered = build_and_validate(d)
        print(f"{d}: {len(rendered)} objects OK")
