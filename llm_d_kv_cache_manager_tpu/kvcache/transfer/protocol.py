"""KV-transfer wire format: msgpack-framed block-chain fetches.

Same framing discipline as the event plane (``kvevents/events.py``):
array-encoded tagged unions, positional and tolerant decoding (missing
trailing fields default, malformed messages decode to ``None`` rather than
raising — a poison request must never kill the export service).

- request: ``["FetchBlocks", model_name, [block_hash, ...], max_blocks,
  traceparent?]`` (the optional trailing W3C ``traceparent`` joins the
  exporting peer's spans to the puller's trace — appended ONLY when
  tracing is on, so default wire bytes are unchanged)
- response: ``["Blocks", complete, [[hash, parent_hash, token_ids,
  block_size, dtype, shape, k_data, v_data, quant?, k_scale?,
  v_scale?], ...]]`` (the optional trailing triple carries int8-KV
  compression — ``quant`` names the scheme, the scales are raw f32
  bytes of ``models/quant.kv_scale_shape``; appended ONLY when the
  exporter quantizes, so legacy wire bytes are unchanged and old
  importers, positional and tolerant, simply ignore it)
- error: ``["TransferError", message]``

Remote-tier demotion extension (``REMOTE_TIER``; never on the wire unless
a pod enables the knob, so default traffic is bit-identical and old
services answer an unknown tag with a tolerant ``TransferError`` the
pusher treats as "fall back to plain eviction"):

- push: ``["PushBlocks", model_name, source_pod, [block, ...]]`` — a pod
  about to destroy the last local copy of a chain ships the pages to a
  peer with headroom instead; block rows reuse the ``Blocks`` response
  encoding (including the optional trailing int8 quant triple, which
  halves demotion bytes exactly as it halves pull bytes).
- ack: ``["PushAck", accepted, headroom]`` — how many blocks the peer
  committed to its remote store, and how many more pages it will take
  (the pusher's per-peer headroom feed between heartbeats).

Hashes are uint64 (the sha256-CBOR chain the whole system keys on); page
payloads ride as raw bytes of the engine's ``[n_layers, page_size,
n_kv_heads, head_dim]`` page slice, dtype/shape-tagged so the importer can
verify geometry before committing anything.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import msgpack

FETCH_BLOCKS_TAG = "FetchBlocks"
BLOCKS_TAG = "Blocks"
ERROR_TAG = "TransferError"
PUSH_BLOCKS_TAG = "PushBlocks"
PUSH_ACK_TAG = "PushAck"


@dataclass
class BlockPayload:
    """One transferable KV block: chain identity + page bytes."""

    block_hash: int
    parent_block_hash: Optional[int]
    token_ids: list[int]
    block_size: int
    dtype: str
    #: per-page slice shape: (n_layers, page_size, n_kv_heads, head_dim)
    shape: tuple[int, ...]
    k_data: bytes
    v_data: bytes
    #: KV compression scheme ("int8") — None = full-width ``dtype`` bytes.
    #: ``dtype``/``shape`` stay the LOGICAL page geometry either way; with
    #: quant set, ``k_data``/``v_data`` are int8 bytes of that shape and
    #: the scales are raw f32 bytes of ``models/quant.kv_scale_shape``.
    quant: Optional[str] = None
    k_scale: bytes = b""
    v_scale: bytes = b""

    @property
    def wire_bytes(self) -> int:
        return (
            len(self.k_data)
            + len(self.v_data)
            + len(self.k_scale)
            + len(self.v_scale)
        )


def encode_request(
    model_name: str,
    block_hashes: Sequence[int],
    max_blocks: Optional[int] = None,
    traceparent: Optional[str] = None,
) -> bytes:
    arr: list = [
        FETCH_BLOCKS_TAG,
        model_name,
        [int(h) for h in block_hashes],
        max_blocks,
    ]
    if traceparent is not None:
        # Trailing optional field: only on the wire when tracing is on, so
        # the no-knobs request bytes stay bit-identical and old services
        # (positional, tolerant) simply ignore it.
        arr.append(traceparent)
    return msgpack.packb(arr, use_bin_type=True)


def decode_request(
    payload: bytes,
) -> Optional[tuple[str, list[int], Optional[int], Optional[str]]]:
    """``(model_name, block_hashes, max_blocks, traceparent)`` or None for
    garbage. ``traceparent`` is None when absent or non-string (tolerant:
    a malformed trace field must never fail the fetch)."""
    arr = _unpack(payload)
    if (
        not isinstance(arr, (list, tuple))
        or len(arr) < 3
        or _text(arr[0]) != FETCH_BLOCKS_TAG
        or not isinstance(arr[2], (list, tuple))
    ):
        return None
    model = _text(arr[1])
    if not isinstance(model, str) or not model:
        return None
    try:
        hashes = [int(h) for h in arr[2]]
    except (TypeError, ValueError):
        return None
    max_blocks = arr[3] if len(arr) > 3 else None
    if max_blocks is not None:
        try:
            max_blocks = int(max_blocks)
        except (TypeError, ValueError):
            return None
    traceparent = _text(arr[4]) if len(arr) > 4 else None
    if not isinstance(traceparent, str):
        traceparent = None
    return model, hashes, max_blocks, traceparent


def encode_block_row(b: BlockPayload) -> list:
    """One block's wire row — shared by the ``Blocks`` response and the
    ``PushBlocks`` demotion request so both sides of the fabric speak one
    block encoding (and the kvlint wire manifest pins it once)."""
    raw: list = [
        b.block_hash,
        b.parent_block_hash,
        list(b.token_ids),
        b.block_size,
        b.dtype,
        list(b.shape),
        b.k_data,
        b.v_data,
    ]
    if b.quant is not None:
        # Trailing optional triple: only on the wire for quantized
        # blocks, so unquantized response bytes stay bit-identical.
        raw.extend([b.quant, b.k_scale, b.v_scale])
    return raw


def encode_response(blocks: Sequence[BlockPayload], complete: bool) -> bytes:
    encoded = [encode_block_row(b) for b in blocks]
    return msgpack.packb(
        [BLOCKS_TAG, bool(complete), encoded], use_bin_type=True
    )


def encode_error(message: str) -> bytes:
    return msgpack.packb([ERROR_TAG, message], use_bin_type=True)


def decode_response(
    payload: bytes,
) -> Optional[tuple[list[BlockPayload], bool, Optional[str]]]:
    """``(blocks, complete, error)``; ``error`` set for service-side
    failures, None return for undecodable payloads."""
    arr = _unpack(payload)
    if not isinstance(arr, (list, tuple)) or not arr:
        return None
    tag = _text(arr[0])
    if tag == ERROR_TAG:
        return [], False, _text(arr[1]) if len(arr) > 1 else "unknown error"
    if tag != BLOCKS_TAG or len(arr) < 3 or not isinstance(arr[2], (list, tuple)):
        return None
    blocks: list[BlockPayload] = []
    for raw in arr[2]:
        blk = _decode_block(raw)
        if blk is None:
            return None  # a half-garbled block corrupts the chain: reject all
        blocks.append(blk)
    return blocks, bool(arr[1]), None


def _decode_block(raw: Any) -> Optional[BlockPayload]:
    if not isinstance(raw, (list, tuple)) or len(raw) < 8:
        return None
    (h, parent, token_ids, block_size, dtype, shape, k_data, v_data) = raw[:8]
    if not isinstance(k_data, (bytes, bytearray)) or not isinstance(
        v_data, (bytes, bytearray)
    ):
        return None
    # Optional trailing quant triple (int8 KV): absent on legacy frames.
    quant = _text(raw[8]) if len(raw) > 8 else None
    if quant is not None and not isinstance(quant, str):
        return None  # a malformed scheme tag corrupts the payload meaning
    k_scale = raw[9] if len(raw) > 9 else b""
    v_scale = raw[10] if len(raw) > 10 else b""
    if not isinstance(k_scale, (bytes, bytearray)) or not isinstance(
        v_scale, (bytes, bytearray)
    ):
        return None
    try:
        return BlockPayload(
            block_hash=int(h),
            parent_block_hash=None if parent is None else int(parent),
            token_ids=[int(t) for t in (token_ids or [])],
            block_size=int(block_size),
            dtype=_text(dtype) or "",
            shape=tuple(int(d) for d in (shape or ())),
            k_data=bytes(k_data),
            v_data=bytes(v_data),
            quant=quant,
            k_scale=bytes(k_scale),
            v_scale=bytes(v_scale),
        )
    except (TypeError, ValueError):
        return None


def encode_push(
    model_name: str, source_pod: str, blocks: Sequence[BlockPayload]
) -> bytes:
    """Demotion push request: ship ``blocks`` to a peer's remote store."""
    return msgpack.packb(
        [
            PUSH_BLOCKS_TAG,
            model_name,
            source_pod,
            [encode_block_row(b) for b in blocks],
        ],
        use_bin_type=True,
    )


def decode_push(
    payload: bytes,
) -> Optional[tuple[str, str, list[BlockPayload]]]:
    """``(model_name, source_pod, blocks)`` or None for non-push/garbage
    frames (the service tries ``decode_request`` first; a frame neither
    decoder accepts answers with a tolerant error, never a crash)."""
    arr = _unpack(payload)
    if (
        not isinstance(arr, (list, tuple))
        or len(arr) < 4
        or _text(arr[0]) != PUSH_BLOCKS_TAG
        or not isinstance(arr[3], (list, tuple))
    ):
        return None
    model = _text(arr[1])
    source = _text(arr[2])
    if not isinstance(model, str) or not model or not isinstance(source, str):
        return None
    blocks: list[BlockPayload] = []
    for raw in arr[3]:
        blk = _decode_block(raw)
        if blk is None:
            return None  # a half-garbled block corrupts the chain: reject all
        blocks.append(blk)
    return model, source, blocks


def encode_push_ack(accepted: int, headroom: int) -> bytes:
    return msgpack.packb(
        [PUSH_ACK_TAG, int(accepted), int(headroom)], use_bin_type=True
    )


def decode_push_ack(
    payload: bytes,
) -> Optional[tuple[int, int, Optional[str]]]:
    """``(accepted, headroom, error)``; ``error`` set for service-side
    refusals (including legacy services that do not speak the push op),
    None return for undecodable payloads."""
    arr = _unpack(payload)
    if not isinstance(arr, (list, tuple)) or not arr:
        return None
    tag = _text(arr[0])
    if tag == ERROR_TAG:
        return 0, 0, _text(arr[1]) if len(arr) > 1 else "unknown error"
    if tag != PUSH_ACK_TAG or len(arr) < 3:
        return None
    try:
        return int(arr[1]), int(arr[2]), None
    except (TypeError, ValueError):
        return None


def _unpack(payload: bytes) -> Any:
    try:
        return msgpack.unpackb(payload, raw=False)
    except Exception:
        return None


def _text(v: Any) -> Any:
    if isinstance(v, (bytes, bytearray)):
        return v.decode("utf-8", "replace")
    return v
