#!/usr/bin/env python
"""Mint `bert_prompt_ids.json` — bert-base-uncased ids for the golden prompt.

Needs network (or a populated HF cache). Run from the repo root:

    python tests/golden/mint_bert_ids.py

Contract (must match the reference's tokenize path,
`pkg/tokenization/tokenizer.go:110-123`): fast (Rust) tokenizer,
special tokens ADDED (`EncodeWithOptions(input, true, ...)`), no
truncation, no padding.
"""

import json
import pathlib

HERE = pathlib.Path(__file__).parent
MODEL = "bert-base-uncased"


def main() -> None:
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(MODEL, use_fast=True)
    prompt = (HERE / "bert_prompt.txt").read_text(encoding="utf-8")
    ids = tok.encode(prompt, add_special_tokens=True, truncation=False)
    out = {
        "model": MODEL,
        "add_special_tokens": True,
        "prompt_sha256": __import__("hashlib").sha256(prompt.encode()).hexdigest(),
        "ids": ids,
    }
    (HERE / "bert_prompt_ids.json").write_text(json.dumps(out))
    print(f"wrote {len(ids)} ids")


if __name__ == "__main__":
    main()
