"""knob-default: every config field / env knob must default to off.

The fleet's compatibility story is "no knobs set = bit-identical legacy
behavior". That only holds if every knob introduced anywhere defaults to
off/0/None/False. Knobs that legitimately need a non-off default (sizing
parameters like ``decode_batch_size``, pre-existing on-by-default
surfaces like ``publish_events``) are declared in
``tools/kvlint/knob_allowlist.txt`` — adding a line there is a reviewed,
diff-visible act.

Checked surfaces:

- class-level defaults of any ``*Config`` dataclass
- ``os.environ.get("NAME", default)`` / ``os.getenv`` / ``env.get`` with a
  literal default (non-literal defaults, e.g. ``cfg.x``, defer to the
  dataclass default already checked)
- ``_env_bool("NAME", default)``-style boolean-knob helpers
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tools.kvlint.core import Finding, ModuleUnit, RepoContext

RULE = "knob-default"

#: literal defaults that read as "off"/zero/unset
_OFF_VALUES = {None, False, 0, 0.0, "", "off", "auto", "0", "false", "no"}

#: env-var shape: SCREAMING_SNAKE — keeps plain ``dict.get`` out of scope
_ENV_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")

_ENV_RECEIVERS = {"env", "environ"}
_FALSY_BOOL_STRINGS = {"", "0", "false", "no", "off"}


def _load_allowlist(ctx: RepoContext) -> set[str]:
    cached = ctx.parsed_cache.get("knob_allowlist")
    if cached is not None:
        return cached  # type: ignore[return-value]
    text = ctx.read_repo_file("tools/kvlint/knob_allowlist.txt") or ""
    entries: set[str] = set()
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if line:
            entries.add(line)
    ctx.parsed_cache["knob_allowlist"] = entries
    return entries


def _is_off(value: object) -> bool:
    if isinstance(value, str):
        return value.lower() in _OFF_VALUES
    if isinstance(value, bool):
        return value is False
    return value in (None, 0, 0.0)


def _const(node: ast.AST) -> Optional[ast.Constant]:
    return node if isinstance(node, ast.Constant) else None


def _field_default(node: ast.expr) -> Optional[ast.Constant]:
    """``field(default=<literal>)`` → that literal; None otherwise.
    ``field(default_factory=...)`` builds composites, not toggles — skip."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "field"
    ):
        for kw in node.keywords:
            if kw.arg == "default":
                return _const(kw.value)
    return None


def _env_get_call(node: ast.Call) -> Optional[str]:
    """Env-knob read? Returns the env var name, else None."""
    fn = node.func
    name_arg = node.args[0] if node.args else None
    c = _const(name_arg) if name_arg is not None else None
    if c is None or not isinstance(c.value, str) or not _ENV_NAME_RE.match(c.value):
        return None
    if isinstance(fn, ast.Attribute):
        if fn.attr == "getenv":
            return c.value  # os.getenv("NAME", ...)
        if fn.attr == "get":
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id in _ENV_RECEIVERS:
                return c.value  # env.get / environ.get
            if (
                isinstance(recv, ast.Attribute)
                and recv.attr == "environ"
            ):
                return c.value  # os.environ.get
    return None


def check(unit: ModuleUnit, ctx: RepoContext) -> list[Finding]:
    allow = _load_allowlist(ctx)
    findings: list[Finding] = []

    def flag(line: int, key: str, shown_default: str) -> None:
        if key in allow:
            return
        findings.append(
            Finding(
                rule=RULE,
                path=unit.rel,
                line=line,
                message=(
                    f"knob '{key}' defaults on ({shown_default}); knobs must "
                    "default to off/0/None so no-knobs runs stay bit-identical "
                    "legacy — or declare it in tools/kvlint/knob_allowlist.txt"
                ),
            )
        )

    for node in ast.walk(unit.tree):
        # --- *Config dataclass fields -------------------------------------
        if isinstance(node, ast.ClassDef) and node.name.endswith("Config"):
            for stmt in node.body:
                target: Optional[str] = None
                default: Optional[ast.expr] = None
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    target, default = stmt.target.id, stmt.value
                elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
                    isinstance(stmt.targets[0], ast.Name)
                ):
                    target, default = stmt.targets[0].id, stmt.value
                if target is None or default is None:
                    continue
                c = _const(default) or _field_default(default)
                if c is None:
                    continue  # default_factory / computed: not a toggle
                if not _is_off(c.value):
                    flag(stmt.lineno, f"{node.name}.{target}", repr(c.value))

        # --- env reads -----------------------------------------------------
        elif isinstance(node, ast.Call):
            env_name = _env_get_call(node)
            if env_name is not None and len(node.args) > 1:
                c = _const(node.args[1])
                if c is not None and not _is_off(c.value):
                    flag(node.lineno, f"env:{env_name}", repr(c.value))
                continue
            # boolean-knob helpers: _env_bool("NAME", "1") means on-by-default
            fn = node.func
            helper = (
                fn.id
                if isinstance(fn, ast.Name)
                else fn.attr
                if isinstance(fn, ast.Attribute)
                else ""
            )
            if ("env_bool" in helper or "env_flag" in helper) and len(node.args) > 1:
                name_c = _const(node.args[0])
                dflt_c = _const(node.args[1])
                if (
                    name_c is not None
                    and isinstance(name_c.value, str)
                    and _ENV_NAME_RE.match(name_c.value)
                    and dflt_c is not None
                ):
                    v = dflt_c.value
                    on = (
                        v is True
                        or (
                            isinstance(v, str)
                            and v.strip().lower() not in _FALSY_BOOL_STRINGS
                        )
                    )
                    if on:
                        flag(node.lineno, f"env:{name_c.value}", repr(v))
    return findings
