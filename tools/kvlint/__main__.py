"""CLI: ``python -m tools.kvlint <paths...>``.

Exit status 0 = clean, 1 = findings, 2 = usage error. ``--rule`` limits
the run to one rule (repeatable); ``--list-rules`` prints the registry.
"""

from __future__ import annotations

import argparse
import sys

from tools.kvlint.core import all_rules, lint_paths


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.kvlint",
        description="repo-invariant static analysis (see tools/kvlint/__init__.py)",
    )
    parser.add_argument("targets", nargs="*", help="files or directories to lint")
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE",
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print known rules and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, mod in all_rules().items():
            doc = (mod.__doc__ or "").strip().splitlines()
            print(f"{rule}: {doc[0] if doc else ''}")
        return 0
    if not args.targets:
        parser.print_usage(sys.stderr)
        return 2

    findings = lint_paths(args.targets, rules=args.rules)
    for f in findings:
        print(f.render())
    if findings:
        print(
            f"kvlint: {len(findings)} finding(s). Fix, or suppress a justified "
            "exception with '# kvlint: disable=<rule>' plus a why-comment.",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
