"""Minimal in-process Redis fake covering the hash-ops subset RedisIndex
uses (the reference's tests use miniredis the same way, ``redis_test.go``)."""

from __future__ import annotations

import threading


class FakePipeline:
    def __init__(self, store: "FakeRedis"):
        self._store = store
        self._ops: list[tuple] = []

    def hkeys(self, name):
        self._ops.append(("hkeys", name))
        return self

    def hset(self, name, field, value):
        self._ops.append(("hset", name, field, value))
        return self

    def hdel(self, name, *fields):
        self._ops.append(("hdel", name, fields))
        return self

    def execute(self):
        results = []
        with self._store._lock:
            for op in self._ops:
                if op[0] == "hkeys":
                    results.append(list(self._store._hashes.get(op[1], {}).keys()))
                elif op[0] == "hset":
                    _, name, field, value = op
                    h = self._store._hashes.setdefault(name, {})
                    created = field not in h
                    h[field] = value
                    results.append(int(created))
                elif op[0] == "hdel":
                    _, name, fields = op
                    h = self._store._hashes.get(name, {})
                    removed = sum(1 for f in fields if h.pop(f, None) is not None)
                    if name in self._store._hashes and not h:
                        del self._store._hashes[name]
                    results.append(removed)
        self._ops = []
        return results


class FakeRedis:
    def __init__(self):
        self._hashes: dict[str, dict[str, str]] = {}
        self._lock = threading.RLock()

    def ping(self):
        return True

    def pipeline(self):
        return FakePipeline(self)

    # direct (non-pipelined) variants, for completeness
    def hkeys(self, name):
        with self._lock:
            return list(self._hashes.get(name, {}).keys())

    def keys(self):
        with self._lock:
            return list(self._hashes.keys())

    def scan_iter(self, match=None):
        """SCAN subset used by ``RedisIndex.evict_pod`` (match unused)."""
        for name in self.keys():
            yield name
