"""In-process SLO burn-rate recording over the serving latency stream.

``OBS_SLO`` declares objectives against the same per-request measurements
the PR 5 latency histograms observe (TTFT and per-request mean ITL), e.g.

    OBS_SLO="ttft:0.5:0.99;itl:0.05:0.95"

reads "99% of requests must see TTFT <= 0.5 s, 95% must see mean ITL <=
0.05 s". For each objective and each sliding window (``OBS_SLO_WINDOWS``,
default 60 s and 300 s) the recorder exports

    kvcache_slo_burn_rate{objective, window}

where burn rate = (observed violating fraction) / (1 - target): 1.0 means
the error budget burns exactly at its sustainable rate, N means the
budget is exhausted N x faster — the standard multi-window burn-rate
alerting input, computed in-process so it works without a Prometheus
server (the ``/stats`` ``slo`` block carries the same numbers).

Off by default: with ``OBS_SLO`` unset nothing here is constructed and
the serving path reads no extra clocks.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import get_logger

log = get_logger("obs.slo")

SLO_METRICS = ("ttft", "itl")
DEFAULT_WINDOWS_S = (60.0, 300.0)


@dataclass(frozen=True)
class SLObjective:
    """One objective: ``target`` fraction of requests must see ``metric``
    at or under ``threshold_s``."""

    metric: str  # "ttft" | "itl"
    threshold_s: float
    target: float  # e.g. 0.99

    @property
    def label(self) -> str:
        """The ``objective`` metric-label value (stable, PromQL-friendly)."""
        return f"{self.metric}_le_{self.threshold_s:g}s_p{self.target:g}"


def parse_slo_spec(spec: str) -> list[SLObjective]:
    """``"ttft:0.5:0.99;itl:0.05:0.95"`` → objectives. Raises ValueError
    on malformed specs — a silently-dropped objective would read as a
    perfectly green SLO."""
    out = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(f"bad OBS_SLO segment {part!r} (want metric:threshold_s:target)")
        metric, thr, target = fields
        if metric not in SLO_METRICS:
            raise ValueError(f"bad OBS_SLO metric {metric!r} (want one of {SLO_METRICS})")
        thr_f, target_f = float(thr), float(target)
        if thr_f <= 0 or not (0.0 < target_f < 1.0):
            raise ValueError(f"bad OBS_SLO segment {part!r} (threshold > 0, 0 < target < 1)")
        out.append(SLObjective(metric=metric, threshold_s=thr_f, target=target_f))
    return out


def parse_windows(spec: str) -> tuple[float, ...]:
    """``"60,300"`` → window seconds; empty/unset → the defaults."""
    if not (spec or "").strip():
        return DEFAULT_WINDOWS_S
    out = tuple(float(w) for w in spec.split(",") if w.strip())
    if not out or any(w <= 0 for w in out):
        raise ValueError(f"bad OBS_SLO_WINDOWS {spec!r} (want positive seconds)")
    return out


class SLORecorder:
    """Sliding-window violation accounting for a set of objectives.

    ``observe`` is called once per finished request (the same feed as the
    latency histograms); ``burn_rates`` is scrape-driven (/stats and
    /metrics), so the hot path pays one deque append per objective.
    """

    def __init__(
        self,
        objectives: list[SLObjective],
        windows_s=DEFAULT_WINDOWS_S,
        clock: Callable[[], float] = time.monotonic,
        max_samples_per_objective: int = 65536,
        on_burn: Optional[Callable[[str, str, float], None]] = None,
        burn_threshold: float = 0.0,
        burn_check_interval_s: float = 1.0,
        track_tenants: bool = False,
    ):
        """``on_burn(objective, window, rate)`` (optional, e.g. the
        ``OBS_FLIGHT`` recorder's trigger): fired when any objective's
        burn rate CROSSES ``burn_threshold`` from below — edge-triggered
        per (objective, window), so a sustained burn triggers once until
        it recovers under the threshold. Evaluation is throttled to at
        most once per ``burn_check_interval_s`` (burn rates are
        O(window samples) to compute, which must not ride every request).
        ``burn_threshold <= 0`` or ``on_burn=None`` disables the check —
        the legacy observe path reads no extra state."""
        self.objectives = list(objectives)
        self.windows_s = tuple(windows_s)
        self._clock = clock
        self._mu = threading.Lock()
        #: per objective: deque[(t, violated)] pruned past the max window
        self._events: dict[str, deque] = {  # guarded_by: _mu
            o.label: deque(maxlen=max_samples_per_objective)
            for o in self.objectives
        }
        self._max_samples = max_samples_per_objective
        #: TENANT_QOS per-tenant burn tracking: (tenant, objective label)
        #: -> the same deque[(t, violated)] shape as ``_events``. Only
        #: populated when ``track_tenants`` and the observation carries a
        #: tenant, so the knob-off recorder holds no extra state. Tenant
        #: keys are the serving layer's slice keys (bounded by policy
        #: size), never raw header values.
        self.track_tenants = bool(track_tenants)
        self._tenant_events: dict[tuple[str, str], deque] = {}  # guarded_by: _mu
        self.observed = 0  # guarded_by: _mu
        self.on_burn = on_burn
        self.burn_threshold = float(burn_threshold)
        self._burn_check_interval_s = float(burn_check_interval_s)
        self._next_burn_check = 0.0  # guarded_by: _mu
        #: (objective, window) currently at-or-over the threshold (the
        #: edge detector's state)
        self._burning: set[tuple[str, str]] = set()  # guarded_by: _mu
        self.burn_crossings = 0  # guarded_by: _mu

    def observe(
        self,
        ttft_s: Optional[float],
        itl_s: Optional[float],
        tenant: str = "",
    ) -> None:
        """One finished request's measurements (None = not measurable for
        this request, e.g. single-token generations have no ITL).
        ``tenant`` slices the same observation per tenant when tenant
        tracking is on; "" (always, with TENANT_QOS off) changes
        nothing."""
        now = self._clock()
        values = {"ttft": ttft_s, "itl": itl_s}
        slice_tenant = tenant if self.track_tenants else ""
        check_burn = False
        with self._mu:
            self.observed += 1
            horizon = now - max(self.windows_s)
            for obj in self.objectives:
                v = values[obj.metric]
                if v is None:
                    continue
                ev = self._events[obj.label]
                ev.append((now, v > obj.threshold_s))
                while ev and ev[0][0] < horizon:
                    ev.popleft()
                if slice_tenant:
                    tev = self._tenant_events.get((slice_tenant, obj.label))
                    if tev is None:
                        tev = self._tenant_events[(slice_tenant, obj.label)] = (
                            deque(maxlen=self._max_samples)
                        )
                    tev.append((now, v > obj.threshold_s))
                    while tev and tev[0][0] < horizon:
                        tev.popleft()
            if (
                self.on_burn is not None
                and self.burn_threshold > 0
                and now >= self._next_burn_check
            ):
                self._next_burn_check = now + self._burn_check_interval_s
                check_burn = True
        if check_burn:
            self._check_burn_crossings()

    def burn_rates(self) -> dict[str, dict[str, Optional[float]]]:
        """{objective label: {window label: burn rate | None}} — None when
        the window holds no samples (no traffic is not a green SLO)."""
        now = self._clock()
        out: dict[str, dict[str, Optional[float]]] = {}
        with self._mu:
            for obj in self.objectives:
                ev = list(self._events[obj.label])
                rates: dict[str, Optional[float]] = {}
                for w in self.windows_s:
                    cutoff = now - w
                    total = bad = 0
                    for t, violated in reversed(ev):
                        if t < cutoff:
                            break
                        total += 1
                        bad += violated
                    budget = 1.0 - obj.target
                    rates[f"{w:g}s"] = (
                        round((bad / total) / budget, 4) if total else None
                    )
                out[obj.label] = rates
        return out

    def tenant_burn_rates(self) -> dict[str, dict[str, dict[str, Optional[float]]]]:
        """{tenant: {objective label: {window label: burn rate | None}}}
        over the per-tenant slices (empty until tenant tracking observed
        anything). Same arithmetic as ``burn_rates``, same None-for-empty
        rule."""
        now = self._clock()
        with self._mu:
            slices = {k: list(ev) for k, ev in self._tenant_events.items()}
        out: dict[str, dict[str, dict[str, Optional[float]]]] = {}
        by_label = {o.label: o for o in self.objectives}
        for (tenant, label), ev in sorted(slices.items()):
            obj = by_label.get(label)
            if obj is None:
                continue
            rates: dict[str, Optional[float]] = {}
            for w in self.windows_s:
                cutoff = now - w
                total = bad = 0
                for t, violated in reversed(ev):
                    if t < cutoff:
                        break
                    total += 1
                    bad += violated
                budget = 1.0 - obj.target
                rates[f"{w:g}s"] = (
                    round((bad / total) / budget, 4) if total else None
                )
            out.setdefault(tenant, {})[label] = rates
        return out

    def sync_tenant_gauges(
        self, set_fn: Callable[[str, str, str, float], None]
    ) -> None:
        """Push per-tenant burn rates into labeled gauges
        (``set_fn(tenant, objective, window, rate)``), skipping empty
        windows like ``sync_gauges``."""
        for tenant, objectives in self.tenant_burn_rates().items():
            for objective, windows in objectives.items():
                for window, rate in windows.items():
                    if rate is not None:
                        set_fn(tenant, objective, window, rate)

    def _check_burn_crossings(self) -> None:
        """Edge-triggered burn-threshold detector: fires ``on_burn`` once
        per (objective, window) crossing; a window that recovers below
        the threshold re-arms. Called off the observe path (throttled) so
        the O(samples) burn-rate walk never rides every request."""
        fired: list[tuple[str, str, float]] = []
        rates = self.burn_rates()
        with self._mu:
            for objective, windows in rates.items():
                for window, rate in windows.items():
                    key = (objective, window)
                    if rate is not None and rate >= self.burn_threshold:
                        if key not in self._burning:
                            self._burning.add(key)
                            self.burn_crossings += 1
                            fired.append((objective, window, rate))
                    else:
                        self._burning.discard(key)
            cb = self.on_burn
        for objective, window, rate in fired:
            try:
                cb(objective, window, rate)
            except Exception:
                # The callback (a flight-recorder dump) must never fail
                # the request whose observation tripped it.
                log.exception("on_burn callback failed")

    def sync_gauges(self, set_fn: Callable[[str, str, float], None]) -> None:
        """Push current burn rates into labeled gauges (scrape-driven).
        Windows with no samples are skipped — a gauge stuck at a stale
        value is worse than an absent series."""
        for objective, windows in self.burn_rates().items():
            for window, rate in windows.items():
                if rate is not None:
                    set_fn(objective, window, rate)

    def snapshot(self) -> dict:
        with self._mu:
            observed = self.observed
        return {
            "objectives": [
                {
                    "objective": o.label,
                    "metric": o.metric,
                    "threshold_s": o.threshold_s,
                    "target": o.target,
                }
                for o in self.objectives
            ],
            "windows_s": list(self.windows_s),
            "observed": observed,
            "burn_rates": self.burn_rates(),
        }
