"""Fleet controller: MRC-driven cache-aware autoscaling + live migration.

The ROADMAP item-2 autoscaler: a reconcile loop that reads the fleet's
SLO burn rates (``OBS_SLO``) and its aggregated miss-ratio curve
(``OBS_LIFECYCLE``) and resizes the pod fleet — scaling up only when the
MRC says more cache will actually absorb the burn (and reviving the new
pod warm over the transfer fabric), scaling down instantly by
live-migrating in-flight decode sequences to survivors. Off by default
behind ``FLEET_CONTROLLER``; unset, nothing here is constructed and the
fleet behaves bit-identically to legacy.

- ``fleet``: ``FleetController`` (decide + act + hysteresis),
  ``FleetControllerConfig`` (the ``FLEET_*`` knobs), ``PodSignals`` /
  ``FleetAdapter`` (the environment surface), ``FleetDecision``;
- ``mrc``: per-pod → fleet miss-ratio-curve aggregation (also the
  scorer's fleet-wide ``/debug/mrc``);
- ``inprocess``: the adapter over real in-process ``PodServer``s.
"""

from .fleet import (
    FleetAdapter,
    FleetController,
    FleetControllerConfig,
    FleetDecision,
    PodSignals,
    fleet_burn,
)
from .inprocess import InProcessFleet
from .mrc import aggregate_mrc, hit_rate_at

__all__ = [
    "FleetAdapter",
    "FleetController",
    "FleetControllerConfig",
    "FleetDecision",
    "InProcessFleet",
    "PodSignals",
    "aggregate_mrc",
    "fleet_burn",
    "hit_rate_at",
]
