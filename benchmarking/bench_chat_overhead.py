"""Chat-templating overhead: /score_chat_completions vs /score_completions.

The reference quantifies its chat-preprocessing tax end to end
(`pkg/preprocessing/chat_completions/README.md:118-132`: +10 % TTFT,
+14 % ITL on Qwen2.5-0.5B). Our service is the SCORING side, so the
honest analogue is scoring-request latency through `server/api.py`: the
chat endpoint pays template fetch + Jinja render on top of the shared
tokenize→hash→score path, and this bench measures that delta through the
real HTTP stack (aiohttp test server, real Rust `tokenizers` core with a
corpus-derived WordPiece vocab — no network).

Reports p50/p90/mean per endpoint, the chat delta, and the cold-template
(first-render Jinja compile) cost. Writes a markdown row you can paste
into benchmarking/results/chat_overhead.md and prints one JSON line.

Run: python benchmarking/bench_chat_overhead.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MODEL = "bench/chat-model"

LLAMA3_STYLE_TPL = (
    "{{ bos_token }}{% for message in messages %}"
    "<|start_header_id|>{{ message['role'] }}<|end_header_id|>\n\n"
    "{{ message['content'] }}<|eot_id|>{% endfor %}"
    "{% if add_generation_prompt %}"
    "<|start_header_id|>assistant<|end_header_id|>\n\n{% endif %}"
)

WORDS = (
    "the quick brown fox jumps over a lazy dog while seventeen engineers "
    "benchmark kv cache aware routing on tpu pods measuring latency "
    "percentiles under shared prefix load with chat templates rendered "
    "for every scoring request in the fleet"
).split()


def make_rust_tokenizer():
    """Real Rust `tokenizers` core, WordPiece vocab derived from the
    corpus (offline — same approach as tests/test_tokenizer_offsets.py)."""
    from tokenizers import Tokenizer, models, pre_tokenizers

    vocab = {"[UNK]": 0}
    for w in WORDS + ["<|start_header_id|>", "<|end_header_id|>", "<|eot_id|>",
                      "<|begin_of_text|>", "system", "user", "assistant"]:
        vocab.setdefault(w, len(vocab))
        # Cover mid-word pieces so nothing degenerates to [UNK].
        for i in range(1, len(w)):
            vocab.setdefault("##" + w[i:], len(vocab))
            vocab.setdefault(w[:i], len(vocab))
    tok = Tokenizer(models.WordPiece(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    return tok


class RustCoreTokenizer:
    """Adapter: handmade Rust-core tokenizer behind the service's
    Tokenizer interface (ids + byte offsets, like CachedHFTokenizer)."""

    def __init__(self):
        self._tok = make_rust_tokenizer()

    def encode(self, prompt: str, model_name: str):
        from llm_d_kv_cache_manager_tpu.tokenization.tokenizer import (
            char_offsets_to_byte_offsets,
        )

        enc = self._tok.encode(prompt)
        return list(enc.ids), char_offsets_to_byte_offsets(prompt, enc.offsets)


def build_conversation(rng, n_messages: int, words_per_msg: int):
    msgs = [{"role": "system", "content": "You are a scoring benchmark."}]
    for i in range(n_messages):
        msgs.append(
            {
                "role": "user" if i % 2 == 0 else "assistant",
                "content": " ".join(rng.choice(WORDS, words_per_msg)),
            }
        )
    return msgs


async def timed_post(client, path, payload, reps, lat_ms):
    for _ in range(reps):
        t0 = time.perf_counter()
        resp = await client.post(path, json=payload)
        assert resp.status == 200, (path, resp.status, await resp.text())
        await resp.json()
        lat_ms.append((time.perf_counter() - t0) * 1e3)


def main() -> int:
    from aiohttp.test_utils import TestClient, TestServer

    from llm_d_kv_cache_manager_tpu.server.api import (
        ScoringService,
        ServiceConfig,
    )

    reps = int(os.environ.get("BENCH_CHAT_REPS", "300"))
    n_messages = int(os.environ.get("BENCH_CHAT_MESSAGES", "8"))
    words_per_msg = int(os.environ.get("BENCH_CHAT_WORDS", "40"))

    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    service = ScoringService(
        ServiceConfig(block_size=16, zmq_endpoint=f"tcp://*:{port}"),
        tokenizer=RustCoreTokenizer(),
    )
    service.start()

    rng = np.random.default_rng(7)
    convo = build_conversation(rng, n_messages, words_per_msg)
    # The completions comparator scores the SAME rendered text, so the
    # tokenize+hash+score work is identical and the delta isolates the
    # chat-only stages (request shape + template fetch/render).
    from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
        ChatTemplatingProcessor,
        RenderRequest,
    )

    proc = ChatTemplatingProcessor()
    proc.initialize()
    rendered = proc.render_chat_template(
        RenderRequest(
            conversations=[convo],
            chat_template=LLAMA3_STYLE_TPL,
            template_vars={"bos_token": "<|begin_of_text|>"},
        )
    ).rendered_chats[0]
    proc.finalize()

    completions_payload = {"prompt": rendered, "model": MODEL}
    chat_payload = {
        "messages": convo,
        "model": MODEL,
        "chat_template": LLAMA3_STYLE_TPL,
        "chat_template_kwargs": {"bos_token": "<|begin_of_text|>"},
    }

    out = {}

    async def runner():
        server = TestServer(service.build_app())
        client = TestClient(server)
        await client.start_server()
        try:
            # Cold-template cost: the very first chat render (Jinja
            # compile + template-cache miss).
            cold = []
            await timed_post(
                client, "/score_chat_completions", chat_payload, 1, cold
            )
            out["chat_cold_first_ms"] = round(cold[0], 3)

            # Interleave warm measurement batches to keep drift fair.
            comp, chat = [], []
            half = reps // 2
            await timed_post(client, "/score_completions", completions_payload, 20, [])
            await timed_post(client, "/score_chat_completions", chat_payload, 20, [])
            await timed_post(client, "/score_completions", completions_payload, half, comp)
            await timed_post(client, "/score_chat_completions", chat_payload, half, chat)
            await timed_post(client, "/score_completions", completions_payload, reps - half, comp)
            await timed_post(client, "/score_chat_completions", chat_payload, reps - half, chat)

            for name, lat in (("completions", comp), ("chat", chat)):
                arr = np.asarray(lat)
                out[name] = {
                    "p50_ms": round(float(np.median(arr)), 3),
                    "p90_ms": round(float(np.percentile(arr, 90)), 3),
                    "mean_ms": round(float(np.mean(arr)), 3),
                    "n": len(lat),
                }
        finally:
            await client.close()

    try:
        asyncio.run(runner())
    finally:
        service.shutdown()

    c, ch = out["completions"], out["chat"]
    out["chat_overhead_pct"] = {
        "p50": round(100.0 * (ch["p50_ms"] - c["p50_ms"]) / c["p50_ms"], 1),
        "p90": round(100.0 * (ch["p90_ms"] - c["p90_ms"]) / c["p90_ms"], 1),
        "mean": round(100.0 * (ch["mean_ms"] - c["mean_ms"]) / c["mean_ms"], 1),
    }
    out["config"] = {
        "reps": reps,
        "messages": n_messages,
        "words_per_msg": words_per_msg,
        "rendered_chars": len(rendered),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
