"""Fleet routing built on the KV-block index: blended scorer.

The reference library stops at ``GetPodScores`` — blending with other
scorers happens in the consuming scheduler (its production deployments
combine the kv-cache scorer with prefix-affinity and load scorers; the
EPP sketch in ``examples/kv_cache_aware_scorer`` shows the embedding
point). This module ships that blending as a first-class component,
because round-4 fleet measurements showed pure index routing INVERTING
under pool thrash: when every pod's cache churns, the index truthfully
reports "cold everywhere", and load-tiebreaking then scatters each
prefix group across pods so no warmth ever forms — an index-free sticky
LRU beat it 2× at the tail (benchmarking/results/routing_capacity.md,
round-4 section).

``BlendedRouter`` ranks pods by:

1. **index score** — longest consecutive prefix of KV blocks the pod
   actually holds (real KV events; dominates whenever it exists);
2. **routed-affinity memory** — a per-pod capacity-bounded LRU of the
   block chains this router previously sent there (``PrefixAffinityTracker``),
   giving load-aware FIRST placement and sticky rebuilds when the index
   is cold;
3. **load** — fewest outstanding requests, supplied by the caller.

Measured at a thrash-sized pool: p90 TTFT 2.51 s vs 5.66 s for pure
index routing, and −17 % vs the strongest index-free baseline.

Routing toward warmth has a hard limit this module hit in round 4: when
the warmest pod is overloaded (or a replica joins cold), the best options
used to be "queue behind the hot pod" or "recompute the whole prefill
cold". With an optional ``kvcache/transfer`` cost model the router gains
the third option — MOVE the warmth: ``RoutingDecision.action`` reports
route-to-warm / pull-then-compute / cold-recompute, decided from measured
transfer bytes/s vs prefill tokens/s (see ``transfer/cost_model.py``).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .kvblock.token_processor import ChunkedTokenDatabase, TokenProcessorConfig
from .metrics import collector
from .predictor import PodSignals


class PrefixAffinityTracker:
    """Per-pod capacity-bounded LRU of routed token-block chains.

    Models "which pod did I send this prefix to, and would its cache
    plausibly still hold it" WITHOUT observing KV events: capacity should
    approximate the pod's pool (HBM pages + host-tier slots, in blocks);
    an optional TTL additionally expires stale affinity. This is also the
    strongest index-free comparator (``bench.py``'s ``estimated`` policy).
    """

    def __init__(
        self,
        n_pods: int,
        capacity_blocks: int,
        ttl_s: Optional[float] = None,
        token_processor: Optional[ChunkedTokenDatabase] = None,
    ):
        self.tp = token_processor or ChunkedTokenDatabase(TokenProcessorConfig())
        self.capacity = capacity_blocks
        self.ttl_s = ttl_s
        #: per-pod OrderedDict: block hash -> last-touch time
        self._routed: list[OrderedDict] = [OrderedDict() for _ in range(n_pods)]

    def keys(self, tokens: Sequence[int]) -> list[int]:
        return self.tp.prefix_hashes(tokens)

    def score(self, keys: Sequence[int], pod: int, now: float = 0.0) -> int:
        """Longest consecutive modeled-resident prefix on ``pod``."""
        lru = self._routed[pod]
        n = 0
        for h in keys:
            ts = lru.get(h)
            if ts is None or (self.ttl_s is not None and now - ts > self.ttl_s):
                break
            n += 1
        return n

    def record(self, keys: Sequence[int], pod: int, now: float = 0.0) -> None:
        """Refresh the routed chain in the pod's modeled LRU (insertion
        order = recency), then evict past capacity — mirroring what the
        pod's own page pool will do with the blocks this request touches."""
        lru = self._routed[pod]
        for h in keys:
            lru.pop(h, None)
            lru[h] = now
        while len(lru) > self.capacity:
            lru.popitem(last=False)


@dataclass
class RoutingDecision:
    pod: str
    index_score: int
    affinity_score: int
    #: transfer-aware verdict (kvcache/transfer cost model): "route_warm"
    #: (serve where the prefix lives — the only action without a cost
    #: model), "pull" (land on ``pod`` but fetch the warm prefix from
    #: ``pull_source`` first), or "cold" (land on ``pod``, recompute).
    action: str = "route_warm"
    pull_source: Optional[str] = None
    #: consecutive warm prefix blocks available at ``pull_source``
    pull_blocks: int = 0
    #: modeled TTFT of the chosen arm (ROUTE_PREDICT only; None = the
    #: legacy score-max ranking made this decision)
    predicted_ttft_s: Optional[float] = None


class BlendedRouter:
    """index score → routed-affinity tiebreak → least load.

    ``score_fn(tokens, pods) -> {pod: score}`` is the index read path
    (e.g. ``KVCacheIndexer.score_tokens`` partially applied with the
    model name); ``loads_fn(pods) -> [outstanding]`` supplies load.

    With a ``cost_model`` (``kvcache/transfer.TransferCostModel``) the
    router gains a third axis beyond *where warmth is*: whether to MOVE
    it. When the warmest pod is loaded, the model compares queueing
    behind it against pulling its prefix blocks onto the least-loaded pod
    (measured transfer bytes/s vs prefill tokens/s) against plain cold
    recompute there — the decision rides back on ``RoutingDecision.action``
    and the caller performs the pull (``PodServer.pull_prefix``). Without
    a cost model the behavior is bit-identical to the legacy router.
    """

    def __init__(
        self,
        score_fn: Callable,
        affinity: PrefixAffinityTracker,
        loads_fn: Callable[[Sequence[str]], Sequence[float]],
        cost_model=None,
        auditor=None,
        remote_score_fn: Optional[Callable] = None,
        remote_endpoint_of: Optional[Callable[[str], Optional[str]]] = None,
        predictor=None,
        signals_fn: Optional[Callable] = None,
    ):
        """``auditor`` (optional, an ``obs.RouteAuditor``): records each
        decision's predicted matched-block count + scoreboard keyed by
        request id, so the pod's realized prefix-cache hits can be joined
        back into the predicted-vs-realized / regret / miss-attribution
        metrics. None (default) records nothing — legacy behavior.

        ``remote_score_fn(tokens) -> {holder: blocks}`` (optional, the
        ``REMOTE_TIER`` read path): warmth held by NON-serving remote
        holders — kvstore pods and peers' remote stores, scored through
        the same index on their ``medium="remote"`` entries. With it (and
        a ``cost_model``) the router gains the demoted-warmth arm: when a
        holder has strictly more of the prefix than the warmest serving
        pod and the measured cost model says moving it beats recomputing,
        the decision becomes a pull from the holder onto the best serving
        target — a remote hit beats recompute but loses to a warm local
        hit. ``remote_endpoint_of(holder) -> transfer endpoint`` maps the
        holder's pod identity to its export endpoint (None keeps the pod
        name, which in-process fleets use directly). Both None (default)
        = bit-identical legacy routing.

        ``predictor`` (optional, a ``kvcache.predictor.TTFTPredictor``
        — the ``ROUTE_PREDICT`` knob): replace score-max ranking with
        predicted-TTFT minimization — per candidate pod, queue wait
        (depth x measured prefill rate) + miss-suffix prefill time
        (+ measured pull cost for pull arms), argmin wins.
        ``signals_fn(pods) -> [PodSignals]`` supplies the per-pod queue
        depth / prefill rate / liveness signals (heartbeat state or live
        attribute reads); without it the predictor only sees loads and
        abstains. The predictor ABSTAINS (None) until a prefill rate is
        measured, and whenever every candidate predicts inf — in both
        cases this router's decision is bit-identical to the legacy
        path. None (default) = legacy score-max routing."""
        self.score_fn = score_fn
        self.affinity = affinity
        self.loads_fn = loads_fn
        self.cost_model = cost_model
        self.auditor = auditor
        self.remote_score_fn = remote_score_fn
        self.remote_endpoint_of = remote_endpoint_of
        self.predictor = predictor
        self.signals_fn = signals_fn

    def route(
        self,
        tokens: Sequence[int],
        pods: Sequence[str],
        now: float = 0.0,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> RoutingDecision:
        scores = self.score_fn(tokens, pods)
        keys = self.affinity.keys(tokens)
        loads = list(self.loads_fn(pods))
        aff_scores = [
            self.affinity.score(keys, i, now) for i in range(len(pods))
        ]
        predicted = (
            self._predict(tokens, pods, scores, loads, aff_scores)
            if self.predictor is not None
            else None
        )
        if predicted is not None:
            # Predicted-TTFT minimization (ROUTE_PREDICT): the argmin of
            # the modeled latency replaces score-max ranking entirely —
            # the legacy block below never runs for this decision.
            target, action, pull_source, pull_blocks, predicted_ttft = predicted
            warm_blocks = scores.get(pods[target], 0)
            collector.observe_predicted_ttft(predicted_ttft)
            return self._finish(
                tokens, pods, scores, keys, loads, aff_scores, now,
                target, action, pull_source, pull_blocks, warm_blocks,
                request_id, trace_id, predicted_ttft,
            )
        best = max(
            range(len(pods)),
            key=lambda i: (scores.get(pods[i], 0), aff_scores[i], -loads[i], -i),
        )
        target, action, pull_source, pull_blocks = best, "route_warm", None, 0
        warm_blocks = scores.get(pods[best], 0)
        if self.cost_model is not None and warm_blocks > 0:
            coldest = min(range(len(pods)), key=lambda i: (loads[i], i))
            if coldest != best:
                verdict = self.cost_model.decide(
                    prompt_len=len(tokens),
                    warm_blocks=warm_blocks,
                    warm_load=loads[best],
                    cold_load=loads[coldest],
                )
                if verdict == "pull":
                    target, action = coldest, "pull"
                    pull_source, pull_blocks = pods[best], warm_blocks
                elif verdict == "cold":
                    target, action = coldest, "cold"
        if (
            self.remote_score_fn is not None
            and self.cost_model is not None
            and action != "pull"
        ):
            remote = self.remote_score_fn(tokens)
            if remote:
                # Deterministic best holder: most blocks, name tiebreak.
                holder, rblocks = max(
                    remote.items(), key=lambda kv: (kv[1], kv[0])
                )
                if rblocks > warm_blocks:
                    # The demoted copy holds strictly more of the prefix
                    # than any serving pod. Land on the stickiest/least
                    # loaded target and pull — if the measured cost model
                    # says the move beats both the warm local option and
                    # recompute (remote beats recompute, loses to warm).
                    tgt = max(
                        range(len(pods)),
                        key=lambda i: (aff_scores[i], -loads[i], -i),
                    )
                    verdict = self.cost_model.decide_remote(
                        prompt_len=len(tokens),
                        remote_blocks=rblocks,
                        target_load=loads[tgt],
                        warm_blocks=warm_blocks,
                        warm_load=loads[best],
                    )
                    if verdict == "pull":
                        target, action = tgt, "pull"
                        pull_blocks = rblocks
                        pull_source = (
                            self.remote_endpoint_of(holder)
                            if self.remote_endpoint_of is not None
                            else holder
                        ) or holder
        return self._finish(
            tokens, pods, scores, keys, loads, aff_scores, now,
            target, action, pull_source, pull_blocks, warm_blocks,
            request_id, trace_id, None,
        )

    def _predict(self, tokens, pods, scores, loads, aff_scores):
        """ROUTE_PREDICT arm: ask the predictor for every pod's best
        modeled arm and argmin. Returns ``(target_idx, action,
        pull_source, pull_blocks, predicted_ttft_s)`` or None when the
        model abstains (no measured rate / every arm inf) — the legacy
        ranking then stands, so prediction can never make a decision the
        legacy fleet could not survive."""
        signals = list(self.signals_fn(pods)) if self.signals_fn else []
        by_name = {s.name: s for s in signals}
        sigs = [
            by_name.get(p, PodSignals(name=p, queue_depth=loads[i]))
            for i, p in enumerate(pods)
        ]
        cm = self.cost_model
        # The remote scan is only worth paying when a cost model exists
        # to price the resulting pull arms (same gate as the legacy
        # remote block) — without one every pull arm is inf anyway.
        remote = (
            self.remote_score_fn(tokens)
            if self.remote_score_fn is not None and cm is not None
            else None
        )
        arms = self.predictor.predict_routes(
            sigs,
            len(tokens),
            scores,
            remote_scores=remote,
            remote_endpoint_of=self.remote_endpoint_of,
            transfer_rate=cm.transfer_rate if cm is not None else None,
            block_bytes=cm.config.block_bytes if cm is not None else 0,
            max_pull_blocks=(
                cm.config.max_pull_blocks if cm is not None else None
            ),
        )
        if not arms:
            return None
        candidates = [
            (i, arms[p]) for i, p in enumerate(pods)
            if p in arms and arms[p].ttft_s != float("inf")
        ]
        if not candidates:
            self.predictor.note_abstained()
            return None
        # Argmin with a tie band: candidates whose modeled TTFT is
        # within tie_band (relative) + tie_abs_s of the best are TIES —
        # the model sees no meaningful latency difference there, and
        # scattering a warm prefix group over sub-noise deltas would
        # trade real future hits for nothing. Ties resolve by the legacy
        # ranking axes (warmth, affinity, load, index), so quiet traffic
        # routes exactly as the score-max fleet would.
        cfg = self.predictor.config
        best_ttft = min(c[1].ttft_s for c in candidates)
        threshold = best_ttft * (1.0 + cfg.tie_band) + cfg.tie_abs_s
        ties = [c for c in candidates if c[1].ttft_s <= threshold]
        i, arm = max(
            ties,
            key=lambda c: (
                scores.get(pods[c[0]], 0),
                aff_scores[c[0]],
                -loads[c[0]],
                -c[1].ttft_s,
                -c[0],
            ),
        )
        return i, arm.action, arm.pull_source, arm.pull_blocks, arm.ttft_s

    def _finish(
        self, tokens, pods, scores, keys, loads, aff_scores, now,
        target, action, pull_source, pull_blocks, warm_blocks,
        request_id, trace_id, predicted_ttft,
    ):
        self.affinity.record(keys, target, now)
        # Routing-quality observability: verdict counts let dashboards see
        # the warm/pull/cold mix shift as the fleet warms or thrashes
        # (kvcache_scorer_route_decisions_total{decision=...}). The metric
        # label reports the PLACEMENT QUALITY, not the code path: the
        # default "route_warm" action with a zero index score is a cold
        # placement (cold fleet, or no cost model) and must count as one —
        # otherwise the counter reads 100% warm exactly when nothing is.
        collector.observe_route_decision(
            "cold" if action == "route_warm" and warm_blocks == 0 else action
        )
        if self.auditor is not None and request_id is not None:
            # Predicted = what this router believed the target would serve
            # from cache: the index's claim when it has one, else the
            # affinity model's (index_blocks=0 then marks the prediction
            # as index-free — the `never_stored` discriminator). A pull
            # decision promises the SOURCE's warm chain lands on the
            # target before prefill, so its prediction is pull_blocks —
            # recording the cold target's own score (~0) would drop every
            # pull from the ratio histogram and leave a failed pull
            # (dead peer, cold fallback) with nothing to attribute.
            index_blocks = scores.get(pods[target], 0)
            if action == "pull":
                predicted_blocks = pull_blocks
            elif index_blocks > 0:
                predicted_blocks = index_blocks
            else:
                predicted_blocks = aff_scores[target]
            self.auditor.record_decision(
                request_id,
                chosen_pod=pods[target],
                predicted_blocks=predicted_blocks,
                index_blocks=index_blocks,
                scoreboard=scores,
                decision=(
                    "cold"
                    if action == "route_warm" and warm_blocks == 0
                    else action
                ),
                chain_hashes=keys,
                trace_id=trace_id,
                predicted_ttft_s=predicted_ttft,
            )
        # Decision metadata is DECISION-time state (what drove the pick),
        # captured before record() refreshes the affinity memory.
        return RoutingDecision(
            pod=pods[target],
            index_score=scores.get(pods[target], 0),
            affinity_score=aff_scores[target],
            action=action,
            pull_source=pull_source,
            pull_blocks=pull_blocks,
            predicted_ttft_s=predicted_ttft,
        )


# -- disaggregated prefill/decode placement (ISSUE 9) ------------------------


@dataclass
class PodView:
    """Planner-facing snapshot of one pod, assembled by the caller from
    heartbeat state (role/draining, ``FleetHealth.pod_views``) and serving
    telemetry (queue depth, measured prefill rate — the PR 3-4 heartbeat /
    ``/stats`` carriers). A view is a point-in-time read; the planner
    treats it as truth for one placement and re-plans on failure."""

    name: str
    #: "prefill" | "decode" | "mixed" (mixed serves either tier)
    role: str = "mixed"
    #: the pod's KV-transfer export endpoint (chain handoff source); None
    #: = the pod cannot export, so it can never be a disagg prefill hop
    transfer_endpoint: Optional[str] = None
    draining: bool = False
    #: crashed/expired/unreachable (TTL-expired per FleetHealth, engine
    #: failed, or the caller observed a submit fail)
    dead: bool = False
    #: the pod's transfer plane is suspect: some peer's circuit breaker to
    #: its export endpoint is OPEN — a pull through it would skip to cold
    breaker_open: bool = False
    #: outstanding requests (waiting + prefilling + running) — the decode
    #: tier's ITL-headroom signal and the prefill tier's load tiebreak
    queue_depth: float = 0.0
    #: measured prefill tokens/s (the engine's online EMA); None = unknown
    prefill_rate: Optional[float] = None


@dataclass
class DisaggPlan:
    """A two-hop placement: run ingest on ``prefill_pod`` (stop at first
    token), hand the chain to ``decode_pod`` over the transfer fabric,
    stream tokens there. ``mode == "single"`` is the fallback — serve the
    whole request on ``decode_pod`` exactly as today, so no failure mode
    is worse than the non-disagg fleet."""

    prefill_pod: Optional[str]
    decode_pod: str
    #: "disagg" (two hops) or "single" (legacy one-pod serving)
    mode: str = "disagg"
    #: why the planner fell back / what drove the pick (operator-facing)
    reason: str = ""
    #: the prefill pod's transfer endpoint the decode hop pulls from
    pull_source: Optional[str] = None
    #: index warmth at the prefill pick (observability)
    prefill_score: int = 0


class PlanError(RuntimeError):
    """No healthy pod can serve the request (e.g. every decode-capable pod
    is dead or draining) — the caller surfaces this as an overload-style
    failure rather than silently queueing on a doomed pod."""


class TwoHopPlanner:
    """Placement for disaggregated prefill/decode serving.

    The prefill hop goes where ingest finishes soonest: index warmth
    first (a warm chain skips most of the prefill), then the measured
    prefill rate, then the shortest queue. The decode hop goes where
    streaming has the most ITL headroom: the shallowest queue among
    decode-capable pods. Draining and dead pods are never picked;
    breaker-open pods (pulls from their export endpoint skip to cold)
    are excluded only from the prefill hop — they still serve decode and
    single-pod traffic exactly as a legacy fleet would. ``exclude`` lets
    the caller re-plan around a pod that just failed mid-handoff. When the two picks coincide (mixed pod), or no
    prefill-capable exporter exists, the plan degrades to single-pod
    serving — bit-identical to the legacy fleet's behavior.

    ``score_fn(tokens, pod_names) -> {pod: score}`` is the same index
    read path ``BlendedRouter`` uses (None = warmth-blind placement).
    """

    def __init__(self, score_fn: Optional[Callable] = None):
        self.score_fn = score_fn

    @staticmethod
    def _usable(v: PodView) -> bool:
        # breaker_open is deliberately NOT a liveness exclusion: it only
        # means pulls FROM this pod's export endpoint skip to cold, so it
        # disqualifies the pod as a prefill hop (below), never from decode
        # or single-pod serving — legacy fleets serve fine with open
        # breakers, and "no failure mode worse than today" must hold.
        return not (v.dead or v.draining)

    def plan(
        self,
        tokens: Sequence[int],
        views: Sequence[PodView],
        exclude: Optional[set] = None,
    ) -> DisaggPlan:
        exclude = exclude or set()
        usable = [v for v in views if self._usable(v) and v.name not in exclude]
        if not usable:
            raise PlanError("no healthy pods to place on")
        decode_tier = [v for v in usable if v.role in ("decode", "mixed")]
        if not decode_tier:
            # A prefill-only fleet cannot stream tokens for anyone: this is
            # a deployment error, not a degradable state (docs/operations).
            raise PlanError("no decode-capable pod (fleet is prefill-only)")
        scores = (
            self.score_fn(tokens, [v.name for v in usable])
            if self.score_fn is not None
            else {}
        )
        prefill_tier = [
            v
            for v in usable
            if v.role in ("prefill", "mixed")
            and v.transfer_endpoint
            and not v.breaker_open
        ]
        # Decode pick: most ITL headroom = shallowest queue (deterministic
        # name tiebreak so identical fleets plan identically).
        decode = min(decode_tier, key=lambda v: (v.queue_depth, v.name))
        if not prefill_tier:
            # No exporter to run ingest on: single-pod serve at the warmth
            # (falling back to headroom) among decode-capable pods.
            best = max(
                decode_tier,
                key=lambda v: (scores.get(v.name, 0), -v.queue_depth, v.name),
            )
            return DisaggPlan(
                prefill_pod=None,
                decode_pod=best.name,
                mode="single",
                reason="no prefill-capable exporter",
                prefill_score=scores.get(best.name, 0),
            )
        prefill = max(
            prefill_tier,
            key=lambda v: (
                scores.get(v.name, 0),
                v.prefill_rate or 0.0,
                -v.queue_depth,
                v.name,
            ),
        )
        if prefill.name == decode.name:
            # Both hops land on one (mixed) pod: a handoff to yourself is
            # pure overhead — serve single-pod there, exactly as today.
            return DisaggPlan(
                prefill_pod=None,
                decode_pod=decode.name,
                mode="single",
                reason="prefill and decode picks coincide",
                prefill_score=scores.get(decode.name, 0),
            )
        return DisaggPlan(
            prefill_pod=prefill.name,
            decode_pod=decode.name,
            mode="disagg",
            reason="warmth+rate prefill pick, headroom decode pick",
            pull_source=prefill.transfer_endpoint,
            prefill_score=scores.get(prefill.name, 0),
        )
