#!/usr/bin/env bash
# Fleet smoke — the repo's analogue of the reference's cluster smoke script
# (`tests/kind-vllm-cpu.sh`): stand up the serving fleet + scoring service
# and curl the closed loop (completion → KV events → routing scores).
#
# Modes:
#   tests/fleet_smoke.sh            validate deploy/ manifests, then run the
#                                   process-level closed loop (no containers
#                                   needed; CPU + Pallas interpreter).
#   tests/fleet_smoke.sh --compose  additionally build the image and drive
#                                   the same loop through docker compose
#                                   (deploy/docker-compose.yaml).
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

echo "== [1/3] deploy/ manifest validation =="
python - <<'EOF'
import sys, pathlib
try:
    import yaml
except ImportError:
    sys.exit("pyyaml required (baked into the image / CI deps)")

root = pathlib.Path("deploy")
docs = []
for path in sorted(root.rglob("*.yaml")):
    if path.name == "docker-compose.yaml":
        # compose schema, not k8s — just check it parses and wires the
        # event plane to the scoring service.
        comp = yaml.safe_load(path.read_text())
        svcs = comp["services"]
        assert "scoring" in svcs and any(k != "scoring" for k in svcs), svcs.keys()
        pod = next(v for k, v in svcs.items() if k != "scoring")
        assert "scoring" in pod["environment"]["ZMQ_ENDPOINT"]
        continue
    for doc in yaml.safe_load_all(path.read_text()):
        if doc:
            docs.append((path, doc))

kinds = {}
for path, doc in docs:
    assert "kind" in doc and "apiVersion" in doc, f"{path}: not a k8s object"
    kinds.setdefault(doc["kind"], []).append((path, doc))

# kustomization resource refs must exist. (The values.env tunables-surface
# contract — parity keys, overlay key subsets, generator options — is
# pinned once, in tests/test_deploy_config.py, run as step 1b below.)
for path, doc in kinds.pop("Kustomization", []):
    for res in doc.get("resources", []):
        ref = path.parent / res
        assert ref.exists() or ref.with_suffix(".yaml").exists(), f"{path}: missing {res}"

# the event-plane service must target a port the scoring container exposes
scoring = next(d for _, d in kinds["Deployment"] if d["metadata"]["name"] == "kv-cache-scoring")
ports = {p["name"]: p["containerPort"]
         for p in scoring["spec"]["template"]["spec"]["containers"][0]["ports"]}
assert "zmq-events" in ports and "http" in ports, ports
events_svc = next(d for _, d in kinds["Service"] if d["metadata"]["name"] == "kv-cache-scoring-events")
assert events_svc["spec"]["ports"][0]["targetPort"] in (ports["zmq-events"], "zmq-events")

# the TPU fleet must publish to the events service and mount shared config
sts = next(d for _, d in kinds["StatefulSet"] if d["metadata"]["name"] == "tpu-serving")
container = sts["spec"]["template"]["spec"]["containers"][0]
env_text = str(container)
assert "kv-cache-scoring-events" in env_text, "fleet does not point at the event plane"
print(f"ok: {len(docs)} k8s objects across {len(set(p for p, _ in docs))} files")
EOF

echo "== [1a/3] kustomize build + schema/cross-ref validation =="
# Rendered-output validation (kustomize_lite implements the exact feature
# subset deploy/ uses; no kustomize/kubeconform binary in this image):
# generators resolve, namespaces/selectors/serviceName/configMapRefs all
# cross-check post-render — the drift class a python-yaml lint can't see.
python tests/kustomize_lite.py deploy deploy/overlays/*/

echo "== [1b/3] values.env tunables-surface contract =="
JAX_PLATFORMS=cpu python -m pytest tests/test_deploy_config.py -q

echo "== [2/3] process-level closed loop (fleet_demo) =="
JAX_PLATFORMS=cpu python examples/fleet_demo.py

if [[ "${1:-}" == "--compose" ]]; then
    echo "== [3/3] docker compose closed loop =="
    docker build -t kv-cache-manager-tpu:latest .
    docker compose -f deploy/docker-compose.yaml up -d --wait
    trap 'docker compose -f deploy/docker-compose.yaml down -v' EXIT
    # pod server healthy?
    for i in $(seq 1 120); do
        curl -fsS http://127.0.0.1:8000/healthz >/dev/null 2>&1 && break
        sleep 1
    done
    curl -fsS http://127.0.0.1:8000/healthz
    # serve one completion, then confirm the scoring service saw its events
    PROMPT="the quick brown fox jumps over the lazy dog; pack my box with xx"
    IDS=$(python -c "print([ord(c) for c in '$PROMPT'[:64]])")
    curl -fsS -X POST http://127.0.0.1:8000/v1/completions \
        -H 'Content-Type: application/json' \
        -d "{\"prompt_token_ids\": $IDS, \"max_tokens\": 4}"
    for i in $(seq 1 60); do
        # `|| echo 0`: a transient curl failure must retry, not trip set -e.
        SCORE=$(curl -fsS -X POST http://127.0.0.1:8080/score_completions \
            -H 'Content-Type: application/json' \
            -d "{\"prompt\": \"${PROMPT:0:64}\", \"model\": \"tiny-llama\"}" \
            | python -c "import json,sys; print(int(json.load(sys.stdin)['scores'].get('tpu-pod-A', 0)))" \
            || echo 0)
        # Guard: the score must be an integer before the arithmetic compare,
        # or set -e turns a malformed response into a bash syntax error.
        [[ "$SCORE" =~ ^[0-9]+$ ]] || SCORE=0
        [[ "$SCORE" -ge 4 ]] && break
        sleep 1
    done
    [[ "$SCORE" -ge 4 ]] || { echo "scores never warmed (got $SCORE)"; exit 1; }
    echo "compose loop ok: tpu-pod-A score=$SCORE"
else
    echo "== [3/3] docker compose loop skipped (pass --compose to run) =="
fi
echo "FLEET SMOKE PASSED"
