from .keys import Key, PodEntry, DeviceTier, DEFAULT_TIER, tier_for_medium
from .token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
    DEFAULT_BLOCK_SIZE,
    hash_block,
    root_hash,
)

__all__ = [
    "Key",
    "PodEntry",
    "DeviceTier",
    "DEFAULT_TIER",
    "tier_for_medium",
    "ChunkedTokenDatabase",
    "TokenProcessorConfig",
    "DEFAULT_BLOCK_SIZE",
    "hash_block",
    "root_hash",
]
