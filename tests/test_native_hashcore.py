"""Native C++ hash kernel ↔ pure-Python parity.

The pure-Python implementation in ``token_processor.py`` is the audited
oracle (byte-level CBOR goldens in test_token_processor.py); the native
kernel must match it exactly on every input shape.
"""

import random

import pytest

from llm_d_kv_cache_manager_tpu.native import build as native_build
from llm_d_kv_cache_manager_tpu.native import hashcore
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import token_processor as tp


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    try:
        native_build.build(verbose=False)
    except Exception as e:  # no compiler on this machine → pure-Python still gates
        pytest.skip(f"native build unavailable: {e}")
    # reset the module's load cache so a fresh .so is picked up
    hashcore._lib = None
    hashcore._load_attempted = False
    assert hashcore.available()


def _py_chain(parent, tokens, block_size):
    out, prefix = [], parent
    n = (len(tokens) // block_size) * block_size
    for i in range(0, n, block_size):
        prefix = tp.hash_block(prefix, tokens[i : i + block_size])
        out.append(prefix)
    return out


class TestNativeParity:
    @pytest.mark.parametrize("seed", ["", "42", "sémillon", "a" * 300])
    def test_root_hash(self, seed):
        assert hashcore.root_hash(seed) == tp.root_hash(seed)

    @pytest.mark.parametrize("n,bs", [(0, 16), (15, 16), (16, 16), (17, 16), (160, 16), (48, 4), (1000, 16), (256, 256)])
    def test_chain(self, n, bs):
        rng = random.Random(n * 31 + bs)
        tokens = [rng.randrange(0, 2**32) for _ in range(n)]
        root = tp.root_hash("")
        assert hashcore.chain_hashes(root, tokens, bs) == _py_chain(root, tokens, bs)

    def test_token_processor_uses_native(self):
        db = tp.ChunkedTokenDatabase(tp.TokenProcessorConfig(use_native=True))
        dbp = tp.ChunkedTokenDatabase(tp.TokenProcessorConfig(use_native=False))
        assert db._native is not None
        assert dbp._native is None
        toks = list(range(777))
        assert db.prefix_hashes(toks) == dbp.prefix_hashes(toks)

    def test_boundary_token_values(self):
        root = tp.root_hash("")
        for v in (0, 23, 24, 255, 256, 65535, 65536, 2**32 - 1):
            toks = [v] * 16
            assert hashcore.chain_hashes(root, toks, 16) == _py_chain(root, toks, 16)
