"""Default in-memory index: two-level LRU.

Parity with reference ``pkg/kvcache/kvblock/in_memory.go``: an LRU of
key → pod-LRU, bounded by key count and pods-per-key. Lookup terminates at a
present-but-empty key (broken prefix chain, ``in_memory.go:110-114``); add
uses an atomic get-or-insert so concurrent adders share one pod cache
(``:155-183``); evict drops the key once its pod set empties (``:216-235``).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from ...utils import get_logger
from ...utils.lru import LRUCache
from .index import Index, InMemoryIndexConfig
from .keys import Key, PodEntry

log = get_logger("kvcache.kvblock.in_memory")


class _PodCache:
    """Per-key LRU of pod entries."""

    __slots__ = ("cache", "mu")

    def __init__(self, capacity: int):
        self.cache: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.mu = threading.Lock()


class InMemoryIndex(Index):
    def __init__(self, config: Optional[InMemoryIndexConfig] = None):
        self.config = config or InMemoryIndexConfig()
        self._data: LRUCache[Key, _PodCache] = LRUCache(self.config.size)

    def lookup(
        self, keys: Sequence[Key], pod_filter: Optional[set[str]] = None
    ) -> dict[Key, list[str]]:
        if not keys:
            raise ValueError("no keys provided for lookup")

        pods_per_key: dict[Key, list[str]] = {}
        for key in keys:
            pod_cache = self._data.get(key)
            if pod_cache is None:
                log.trace("key not found in index", key=str(key))
                continue
            entries = pod_cache.cache.keys()
            if not entries:
                # prefix chain breaks here: stop scanning further keys
                log.trace("no pods found for key, cutting search", key=str(key))
                return pods_per_key
            if not pod_filter:
                pods_per_key[key] = [e.pod_identifier for e in entries]
            else:
                filtered = [
                    e.pod_identifier for e in entries if e.pod_identifier in pod_filter
                ]
                # Key recorded only when pods survive the filter; a
                # filtered-to-empty key does NOT break the scan (only an
                # inherently empty pod cache does, in_memory.go:111-131).
                if filtered:
                    pods_per_key[key] = filtered
        return pods_per_key

    def add(self, keys: Sequence[Key], entries: Sequence[PodEntry]) -> None:
        if not keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")

        for key in keys:
            # fast path avoids allocating a throwaway _PodCache per hot-key add
            pod_cache = self._data.get(key)
            if pod_cache is None:
                pod_cache, _existed = self._data.get_or_put(
                    key, _PodCache(self.config.pod_cache_size)
                )
            with pod_cache.mu:
                for entry in entries:
                    pod_cache.cache.put(entry, None)
            log.trace("added pods to key", key=str(key), pods=[str(e) for e in entries])

    def evict(self, key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")

        pod_cache = self._data.get(key)
        if pod_cache is None:
            log.trace("key not found in index, nothing to evict", key=str(key))
            return

        with pod_cache.mu:
            for entry in entries:
                pod_cache.cache.remove(entry)
            is_empty = len(pod_cache.cache) == 0

        if is_empty:
            # Re-check under the pod lock; worst case an empty cache lingers
            # until LRU-evicted (same tolerance as the reference).
            current = self._data.get(key)
            if current is not None:
                with current.mu:
                    if len(current.cache) == 0:
                        self._data.remove(key)
                        log.trace("evicted key from index as no pods remain", key=str(key))

    def size_info(self) -> dict:
        pods: set[str] = set()
        blocks = 0
        # items() snapshots without promoting (the evict_pod rule): a
        # metrics scrape must not perturb key recency.
        for _key, pod_cache in self._data.items():
            blocks += 1
            with pod_cache.mu:
                pods.update(e.pod_identifier for e in pod_cache.cache.keys())
        return {"blocks": blocks, "pods": len(pods)}

    def pod_names(self) -> list[str]:
        pods: set[str] = set()
        for _key, pod_cache in self._data.items():
            with pod_cache.mu:
                pods.update(e.pod_identifier for e in pod_cache.cache.keys())
        return sorted(pods)

    def evict_pod(self, pod_identifier: str) -> int:
        removed = 0
        # items() snapshots without promoting, so a sweep does not disturb
        # key recency; keys added concurrently simply miss this pass (the
        # pod is alive again, its entries belong).
        for key, pod_cache in self._data.items():
            with pod_cache.mu:
                stale = [
                    e
                    for e in pod_cache.cache.keys()
                    if e.pod_identifier == pod_identifier
                ]
                for e in stale:
                    pod_cache.cache.remove(e)
                removed += len(stale)
                is_empty = len(pod_cache.cache) == 0
            if is_empty:
                current = self._data.get(key)
                if current is not None:
                    with current.mu:
                        if len(current.cache) == 0:
                            self._data.remove(key)
        if removed:
            log.debug("swept pod from index", pod=pod_identifier, entries=removed)
        return removed
