"""ctypes binding for the C++ two-level LRU block index.

Build: ``python -m llm_d_kv_cache_manager_tpu.native.build``. Loading is
lazy and optional — ``available()`` gates the native index backend, and the
pure-Python ``InMemoryIndex`` remains the default.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

_LIB_NAME = "liblruindex.so"
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_u64p = ctypes.POINTER(ctypes.c_uint64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    path = os.path.join(os.path.dirname(__file__), _LIB_NAME)
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.lruidx_create.restype = ctypes.c_void_p
        lib.lruidx_create.argtypes = [ctypes.c_uint64, ctypes.c_uint32]
        lib.lruidx_destroy.restype = None
        lib.lruidx_destroy.argtypes = [ctypes.c_void_p]
        lib.lruidx_add.restype = None
        lib.lruidx_add.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, _u64p, ctypes.c_uint64,
            _u32p, _u8p, ctypes.c_uint64,
        ]
        lib.lruidx_evict.restype = None
        lib.lruidx_evict.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            _u32p, _u8p, ctypes.c_uint64,
        ]
        lib.lruidx_lookup.restype = ctypes.c_uint64
        lib.lruidx_lookup.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, _u64p, ctypes.c_uint64,
            _u32p, ctypes.c_uint64, _u32p, _u8p, _u32p,
        ]
        lib.lruidx_score.restype = ctypes.c_uint64
        lib.lruidx_score.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, _u64p, ctypes.c_uint64,
            _u32p, ctypes.c_uint64, _u32p, _u32p, _u64p,
        ]
        lib.lruidx_size.restype = ctypes.c_uint64
        lib.lruidx_size.argtypes = [ctypes.c_void_p]
        try:  # PR-3 symbol: absent in pre-self-healing builds of the .so
            lib.lruidx_evict_pod.restype = ctypes.c_uint64
            lib.lruidx_evict_pod.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        except AttributeError:
            pass
        try:  # PR-11 symbol: shared-lock read-side lookup (no LRU promote)
            lib.lruidx_lookup_ro.restype = ctypes.c_uint64
            lib.lruidx_lookup_ro.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, _u64p, ctypes.c_uint64,
                _u32p, ctypes.c_uint64, _u32p, _u8p, _u32p,
            ]
        except AttributeError:
            pass
        try:  # PR-11 symbol: exact distinct-pod occupancy walk
            lib.lruidx_distinct_pods.restype = ctypes.c_uint64
            lib.lruidx_distinct_pods.argtypes = [
                ctypes.c_void_p, _u32p, ctypes.c_uint64,
            ]
        except AttributeError:
            pass
        try:  # PR-11 symbol: one-call cross-shard fused scoring
            lib.lruidx_score_sharded.restype = ctypes.c_uint64
            lib.lruidx_score_sharded.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint64,
                ctypes.c_uint32, _u64p, _u32p, ctypes.c_uint64,
                _u32p, ctypes.c_uint64, _u32p, _u32p, _u64p,
            ]
        except AttributeError:
            pass
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


class NativeLru:
    """Thin RAII wrapper over the C handle (integer-id API; interning is the
    caller's concern)."""

    def __init__(self, max_keys: int, pods_per_key: int):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "liblruindex.so not built — run "
                "`python -m llm_d_kv_cache_manager_tpu.native.build`"
            )
        self._lib = lib
        # Out-buffer sizing must track the C++ per-key cap exactly — a
        # smaller buffer would let lruidx_lookup write past the allocation.
        self.pods_per_key = max(1, pods_per_key)
        self._h = lib.lruidx_create(max_keys, pods_per_key)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.lruidx_destroy(h)

    def add(self, model: int, hashes, pod_ids, tiers) -> None:
        n_keys, n_entries = len(hashes), len(pod_ids)
        self._lib.lruidx_add(
            self._h, model,
            (ctypes.c_uint64 * n_keys)(*hashes), n_keys,
            (ctypes.c_uint32 * n_entries)(*pod_ids),
            (ctypes.c_uint8 * n_entries)(*tiers), n_entries,
        )

    def evict(self, model: int, block_hash: int, pod_ids, tiers) -> None:
        n = len(pod_ids)
        self._lib.lruidx_evict(
            self._h, model, block_hash,
            (ctypes.c_uint32 * n)(*pod_ids),
            (ctypes.c_uint8 * n)(*tiers), n,
        )

    def lookup(self, model: int, hashes, filter_ids):
        """Returns (n_processed, [per-key list of (pod_id, tier)])."""
        n_keys = len(hashes)
        n_filter = len(filter_ids)
        cap = n_keys * self.pods_per_key
        out_pods = (ctypes.c_uint32 * cap)()
        out_tiers = (ctypes.c_uint8 * cap)()
        out_counts = (ctypes.c_uint32 * n_keys)()
        processed = self._lib.lruidx_lookup(
            self._h, model,
            (ctypes.c_uint64 * n_keys)(*hashes), n_keys,
            (ctypes.c_uint32 * max(1, n_filter))(*(filter_ids or [0])),
            n_filter, out_pods, out_tiers, out_counts,
        )
        result = []
        r = 0
        for i in range(processed):
            c = out_counts[i]
            result.append([(out_pods[r + j], out_tiers[r + j]) for j in range(c)])
            r += c
        return processed, result

    @property
    def has_lookup_ro(self) -> bool:
        return hasattr(self._lib, "lruidx_lookup_ro")

    def lookup_ro(self, model: int, hashes, filter_ids):
        """Read-side lookup: same outputs and early-stop semantics as
        ``lookup``, but under the C++ shared lock with NO recency
        promotion — safe (and concurrent) against in-flight applies.
        Raises when the loaded library predates the symbol."""
        if not self.has_lookup_ro:
            raise RuntimeError(
                "liblruindex.so predates lruidx_lookup_ro — rebuild with "
                "`python -m llm_d_kv_cache_manager_tpu.native.build`"
            )
        n_keys = len(hashes)
        n_filter = len(filter_ids)
        cap = n_keys * self.pods_per_key
        out_pods = (ctypes.c_uint32 * cap)()
        out_tiers = (ctypes.c_uint8 * cap)()
        out_counts = (ctypes.c_uint32 * n_keys)()
        processed = self._lib.lruidx_lookup_ro(
            self._h, model,
            (ctypes.c_uint64 * n_keys)(*hashes), n_keys,
            (ctypes.c_uint32 * max(1, n_filter))(*(filter_ids or [0])),
            n_filter, out_pods, out_tiers, out_counts,
        )
        result = []
        r = 0
        for i in range(processed):
            c = out_counts[i]
            result.append([(out_pods[r + j], out_tiers[r + j]) for j in range(c)])
            r += c
        return processed, result

    def score(self, model: int, hashes, filter_ids):
        """Fused longest-prefix scoring.

        Returns ([(pod_id, score)], hits) where hits = number of keys with a
        filter-surviving pod (the plain lookup path's hit metric)."""
        n_keys = len(hashes)
        n_filter = len(filter_ids)
        cap = self.pods_per_key
        out_pods = (ctypes.c_uint32 * cap)()
        out_scores = (ctypes.c_uint32 * cap)()
        out_hits = (ctypes.c_uint64 * 1)()
        n = self._lib.lruidx_score(
            self._h, model,
            (ctypes.c_uint64 * n_keys)(*hashes), n_keys,
            (ctypes.c_uint32 * max(1, n_filter))(*(filter_ids or [0])),
            n_filter, out_pods, out_scores, out_hits,
        )
        return [(out_pods[i], out_scores[i]) for i in range(n)], int(out_hits[0])

    def evict_pod(self, pod_id: int) -> int:
        """Remove every entry of ``pod_id``; returns entries removed. Raises
        when the loaded library predates the symbol (rebuild required)."""
        if not hasattr(self._lib, "lruidx_evict_pod"):
            raise RuntimeError(
                "liblruindex.so predates lruidx_evict_pod — rebuild with "
                "`python -m llm_d_kv_cache_manager_tpu.native.build`"
            )
        return int(self._lib.lruidx_evict_pod(self._h, pod_id))

    def size(self) -> int:
        return self._lib.lruidx_size(self._h)

    def distinct_pods(self, cap: int):
        """Exact distinct pod ids currently holding >= 1 entry (shared-lock
        O(entries) walk — scrape-driven callers only). Returns None when
        the loaded library predates the symbol (caller falls back to the
        ever-interned approximation)."""
        if not hasattr(self._lib, "lruidx_distinct_pods"):
            return None
        cap = max(int(cap), 1)
        out = (ctypes.c_uint32 * cap)()
        n = int(self._lib.lruidx_distinct_pods(self._h, out, cap))
        return [out[i] for i in range(min(n, cap))]


def score_sharded_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "lruidx_score_sharded")


def score_sharded(lrus, model: int, hashes, owners, filter_ids):
    """One-call fused longest-prefix scoring over a chain whose keys are
    partitioned across ``lrus`` (``owners[i]`` indexes key i's shard):
    every shard is shared-locked inside the call (concurrent with
    applies), no LRU promotion, one GIL release round trip total. Pod ids
    MUST be interned in one table shared by all shards. Returns
    ``([(pod_id, score)], hits)`` like ``NativeLru.score``."""
    lib = _load()
    if lib is None or not hasattr(lib, "lruidx_score_sharded"):
        raise RuntimeError(
            "liblruindex.so predates lruidx_score_sharded — rebuild with "
            "`python -m llm_d_kv_cache_manager_tpu.native.build`"
        )
    n_keys = len(hashes)
    n_filter = len(filter_ids)
    handles = (ctypes.c_void_p * len(lrus))(*[lru._h for lru in lrus])
    cap = max(lru.pods_per_key for lru in lrus)
    out_pods = (ctypes.c_uint32 * cap)()
    out_scores = (ctypes.c_uint32 * cap)()
    out_hits = (ctypes.c_uint64 * 1)()
    n = lib.lruidx_score_sharded(
        handles, len(lrus), model,
        (ctypes.c_uint64 * n_keys)(*hashes),
        (ctypes.c_uint32 * n_keys)(*owners), n_keys,
        (ctypes.c_uint32 * max(1, n_filter))(*(filter_ids or [0])),
        n_filter, out_pods, out_scores, out_hits,
    )
    return [(out_pods[i], out_scores[i]) for i in range(n)], int(out_hits[0])
