"""Disaggregated prefill/decode serving over the KV-transfer fabric.

Composes the pieces PRs 1-7 built — chunked prefill, verified cross-pod
KV transfer with async-pull overlap, admission control + graceful drain,
and end-to-end tracing — into the deployment mode DistServe (OSDI '24)
and Splitwise (ISCA '24) showed removes prefill/decode interference
beyond what chunking alone delivers: dedicated prefill pods run ingest
at full batch width and stop at the first token; dedicated decode pods
pull the finished chain over the transfer fabric and stream tokens.

Everything is off by default: a fleet of ``POD_ROLE=mixed`` pods (the
default) behaves — and speaks on every wire — bit-identically to the
legacy single-tier fleet.
"""

from ..router import DisaggPlan, PlanError, PodView, TwoHopPlanner
from .coordinator import (
    DisaggConfig,
    DisaggCoordinator,
    DisaggResult,
    views_from_pods,
)

__all__ = [
    "DisaggConfig",
    "DisaggCoordinator",
    "DisaggPlan",
    "DisaggResult",
    "PlanError",
    "PodView",
    "TwoHopPlanner",
    "views_from_pods",
]
