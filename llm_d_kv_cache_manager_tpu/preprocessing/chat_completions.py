"""OpenAI chat-completions → flattened prompt, matching engine templating.

Functional parity with the reference's three-language bridge
(``pkg/preprocessing/chat_completions``: Go → embedded-CPython C shim
(``cgo_functions.c``) → Python ``render_jinja_template_wrapper.py``). Our
control plane is already Python, so the CPython-embedding layer collapses to
in-process calls while keeping the same surface:

- ``render_chat_template(request)`` → rendered prompt(s) via
  ``transformers.utils.chat_template_utils.render_jinja_template`` —
  the same function serving engines use, so the flattened prompt (and hence
  the block-hash chain) lines up;
- ``fetch_chat_template(model)`` → template + special-token kwargs from
  ``AutoTokenizer`` (reference ``render_jinja_template_wrapper.py:130-188``),
  with a thread-locked cache keyed ``model:revision:token``;
- ``initialize()/finalize()/clear_caches()`` for API parity with the
  reference's interpreter lifecycle (here they only manage the caches).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from ..utils import get_logger

log = get_logger("preprocessing.chat_completions")


@dataclass
class RenderRequest:
    conversations: list[list[dict[str, str]]]
    chat_template: str
    tools: Optional[list] = None
    documents: Optional[list] = None
    add_generation_prompt: bool = True
    continue_final_message: bool = False
    # special-token kwargs collected at fetch time (bos/eos etc.)
    template_vars: dict[str, Any] = field(default_factory=dict)


@dataclass
class RenderResponse:
    rendered_chats: list[str]


@dataclass
class FetchTemplateRequest:
    model: str
    revision: Optional[str] = None
    token: Optional[str] = None
    chat_template: Optional[str] = None  # explicit override


_SPECIAL_TOKEN_ATTRS = (
    "bos_token",
    "eos_token",
    "pad_token",
    "unk_token",
    "sep_token",
    "cls_token",
    "mask_token",
)


class ChatTemplatingProcessor:
    def __init__(self):
        self._cache_lock = threading.Lock()
        self._template_cache: dict[str, tuple[str, dict[str, Any]]] = {}  # guarded_by: _cache_lock
        self._initialized = False

    # -- lifecycle (parity with the reference's interpreter management) -----
    def initialize(self) -> None:
        self._initialized = True

    def finalize(self) -> None:
        self._initialized = False
        self.clear_caches()

    def clear_caches(self) -> None:
        with self._cache_lock:
            self._template_cache.clear()

    # -- rendering ----------------------------------------------------------
    def render_chat_template(self, request: RenderRequest) -> RenderResponse:
        from transformers.utils.chat_template_utils import render_jinja_template

        rendered = []
        for conversation in request.conversations:
            out = render_jinja_template(
                conversations=[conversation],
                chat_template=request.chat_template,
                tools=request.tools,
                documents=request.documents,
                add_generation_prompt=request.add_generation_prompt,
                continue_final_message=request.continue_final_message,
                **request.template_vars,
            )
            # Depending on version the helper returns str or (list, indices).
            if isinstance(out, tuple):
                out = out[0]
            if isinstance(out, list):
                rendered.extend(out)
            else:
                rendered.append(out)
        return RenderResponse(rendered_chats=rendered)

    # -- template fetching --------------------------------------------------
    def fetch_chat_template(self, request: FetchTemplateRequest) -> tuple[str, dict[str, Any]]:
        """Return (template, special-token kwargs) for a model, cached."""
        if request.chat_template:
            return request.chat_template, {}

        cache_key = f"{request.model}:{request.revision}:{request.token}"
        with self._cache_lock:
            hit = self._template_cache.get(cache_key)
        if hit is not None:
            return hit

        from transformers import AutoTokenizer

        kwargs: dict[str, Any] = {"trust_remote_code": True}
        if request.revision:
            kwargs["revision"] = request.revision
        if request.token:
            kwargs["token"] = request.token
        tokenizer = AutoTokenizer.from_pretrained(request.model, **kwargs)
        template = getattr(tokenizer, "chat_template", None)
        if not template:
            raise ValueError(f"model {request.model!r} has no chat template")

        template_vars = {}
        for attr in _SPECIAL_TOKEN_ATTRS:
            val = getattr(tokenizer, attr, None)
            if val is not None:
                template_vars[attr] = str(val)

        with self._cache_lock:
            self._template_cache[cache_key] = (template, template_vars)
        return template, template_vars
