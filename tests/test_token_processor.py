"""CBOR canonical encoding + chained block hashing parity tests.

Golden bytes are hand-derived from RFC 8949 so the encoder is checked
independently of its own implementation. The chain semantics mirror reference
``pkg/kvcache/kvblock/token_processor.go`` (block size 16, no partial blocks,
low-8-bytes-big-endian sha256 over CBOR [parent, chunk, None]).
"""

import hashlib

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor import dumps_canonical
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
    Key,
    hash_block,
    root_hash,
)


class TestCanonicalCBOR:
    @pytest.mark.parametrize(
        "obj,expected",
        [
            (0, b"\x00"),
            (23, b"\x17"),
            (24, b"\x18\x18"),
            (255, b"\x18\xff"),
            (256, b"\x19\x01\x00"),
            (65535, b"\x19\xff\xff"),
            (65536, b"\x1a\x00\x01\x00\x00"),
            (4294967295, b"\x1a\xff\xff\xff\xff"),
            (4294967296, b"\x1b\x00\x00\x00\x01\x00\x00\x00\x00"),
            (2**64 - 1, b"\x1b" + b"\xff" * 8),
            (-1, b"\x20"),
            (-25, b"\x38\x18"),
            (None, b"\xf6"),
            (True, b"\xf5"),
            (False, b"\xf4"),
            ("", b"\x60"),
            ("a", b"\x61a"),
            ("hello", b"\x65hello"),
            # 2-byte UTF-8
            ("ü", b"\x62\xc3\xbc"),
            (b"\x01\x02", b"\x42\x01\x02"),
            ([], b"\x80"),
            ([1, 2, 3], b"\x83\x01\x02\x03"),
            ([1, [2, 3]], b"\x82\x01\x82\x02\x03"),
            ([1, "x", None], b"\x83\x01\x61x\xf6"),
        ],
    )
    def test_golden_bytes(self, obj, expected):
        assert dumps_canonical(obj) == expected

    def test_uint64_overflow_rejected(self):
        with pytest.raises(OverflowError):
            dumps_canonical(2**64)

    def test_canonical_map_key_order(self):
        # Keys sorted by encoded bytes: int 1 (0x01) < text "a" (0x61 0x61).
        assert dumps_canonical({"a": 2, 1: 1}) == b"\xa2\x01\x01\x61a\x02"

    def test_numpy_ints_match_python_ints(self):
        np = pytest.importorskip("numpy")
        assert dumps_canonical([np.uint32(7), np.int64(300)]) == dumps_canonical([7, 300])


def _manual_hash(payload_bytes: bytes) -> int:
    return int.from_bytes(hashlib.sha256(payload_bytes).digest()[24:32], "big")


class TestHashChain:
    def test_root_hash_empty_seed(self):
        # CBOR of "" is 0x60; root = low 8 bytes (BE) of sha256(0x60).
        assert root_hash("") == _manual_hash(b"\x60")

    def test_root_hash_seed_string(self):
        assert root_hash("42") == _manual_hash(b"\x62\x34\x32")

    def test_single_block_hash_manual(self):
        # parent=0, tokens [1..16], extra None:
        # 0x83 array(3) | 0x00 | 0x90 array(16) | 0x01..0x10 | 0xf6
        payload = b"\x83\x00\x90" + bytes(range(1, 17)) + b"\xf6"
        assert hash_block(0, list(range(1, 17))) == _manual_hash(payload)

    def test_chain_links(self):
        db = ChunkedTokenDatabase()
        tokens = list(range(100, 148))  # 3 full blocks of 16
        hashes = db.prefix_hashes(tokens)
        assert len(hashes) == 3
        parent = db.init_hash
        for i, chunk_start in enumerate(range(0, 48, 16)):
            chunk = tokens[chunk_start : chunk_start + 16]
            parent = hash_block(parent, chunk)
            assert hashes[i] == parent

    def test_no_partial_blocks(self):
        db = ChunkedTokenDatabase()
        assert db.prefix_hashes(list(range(15))) == []
        assert len(db.prefix_hashes(list(range(17)))) == 1
        assert len(db.prefix_hashes(list(range(32)))) == 2

    def test_block_size_config(self):
        db4 = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        assert len(db4.prefix_hashes(list(range(10)))) == 2

    def test_seed_changes_all_hashes(self):
        a = ChunkedTokenDatabase(TokenProcessorConfig(hash_seed=""))
        b = ChunkedTokenDatabase(TokenProcessorConfig(hash_seed="other"))
        toks = list(range(16))
        assert a.prefix_hashes(toks) != b.prefix_hashes(toks)

    def test_keys_carry_model_name(self):
        db = ChunkedTokenDatabase()
        keys = db.tokens_to_kv_block_keys(list(range(32)), "meta-llama/Llama-3-8B")
        assert all(isinstance(k, Key) for k in keys)
        assert all(k.model_name == "meta-llama/Llama-3-8B" for k in keys)
        assert keys[0].chunk_hash == db.prefix_hashes(list(range(32)))[0]

    def test_prefix_property(self):
        # Two prompts sharing the first 32 tokens share the first 2 keys.
        db = ChunkedTokenDatabase()
        a = db.prefix_hashes(list(range(48)))
        b = db.prefix_hashes(list(range(32)) + [999] * 16)
        assert a[:2] == b[:2]
        assert a[2] != b[2]

    def test_hashes_fit_uint64(self):
        db = ChunkedTokenDatabase()
        for h in db.prefix_hashes(list(range(160))):
            assert 0 <= h < 2**64
