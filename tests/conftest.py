"""Test bootstrap.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding code is
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py runs on the one real chip).

The container's ``sitecustomize`` imports jax and registers the axon
TPU-tunnel PJRT plugin before conftest runs, with ``JAX_PLATFORMS=axon``
baked into jax's config — so env vars set here are too late, and letting
backend init reach the tunnel can hang every test run if the tunnel is
wedged. ``jax.config.update`` before the first backend initialization pins
the platform to CPU in-process and the tunnel is never touched.
"""

import os
import sys

# Must precede the first jax backend initialization (not merely jax import).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_d_kv_cache_manager_tpu.tokenization import Tokenizer  # noqa: E402


class CharTokenizer(Tokenizer):
    """Shared offline test tokenizer: token id = ord(char), byte offsets."""

    def encode(self, prompt, model_name):
        return [ord(c) for c in prompt], [(i, i + 1) for i in range(len(prompt))]


def free_tcp_port() -> int:
    """An ephemeral TCP port — fixed test ports collide when suites run
    concurrently (two pytest processes, or pytest alongside a dev server)."""
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pytest_configure(config):
    # Lock-order/race harness: LOCKTRACE=1 routes every lock created from
    # here on through utils.locktrace's TracingLock, so the concurrency
    # hammer and chaos suites run under cycle + guarded-attribute checking
    # (CI runs them that way; plain local runs are untouched).
    from llm_d_kv_cache_manager_tpu.utils import locktrace

    if locktrace.enabled():
        locktrace.activate()
    config.addinivalue_line(
        "markers",
        "network: needs a real HF tokenizer (network or populated HF cache); "
        "skips cleanly offline",
    )
    config.addinivalue_line(
        "markers",
        "slow: heavy fuzz matrices / multi-config sweeps — excluded from the "
        "fast pre-commit loop (`pytest -m 'not slow'`); CI's full job runs "
        "everything",
    )


#: Heavy suites (fuzz matrices, multi-config sweeps, cross-engine numerics
#: oracles) auto-marked ``slow`` — kept as one table instead of markers
#: scattered over seven files. Measured on the dev rig: the full suite is
#: ~12.5 min; `pytest -m "not slow"` keeps the per-commit loop under 5.
#: Coverage rationale: everything here is either randomized re-coverage of
#: paths the fast tests pin directly, or parity oracles that only move when
#: the model/ops layer changes.
_SLOW_CLASSES = {
    ("test_chunked_prefill.py", "TestChunkedInterference"),
    ("test_engine.py", "TestDecodePathParityFuzz"),
    ("test_engine.py", "TestMoEServing"),
    ("test_engine.py", "TestGemmaServing"),
    ("test_engine.py", "TestHostDramOffloadTier"),
    ("test_engine.py", "TestTensorParallelServing"),
    ("test_parallel.py", "TestMoEExpertParallel"),
    ("test_parallel.py", "TestShardedTraining"),
    ("test_parallel.py", "TestSharding"),
    ("test_parallel.py", "TestTrainForwardMatchesServing"),
    ("test_llama_model.py", "TestHFNumericsParity"),
    ("test_llama_model.py", "TestMixtralMoE"),
    ("test_llama_model.py", "TestPrefillDecodeConsistency"),
    ("test_gmm.py", "TestExpertParallelWithKernel"),
    ("test_gmm.py", "TestRoutedDispatchWithKernel"),
    ("test_ring_attention.py", "TestRingAttention"),
    ("test_ring_attention.py", "TestSpEngine"),
    ("test_checkpoint.py", "TestQuantizedCheckpoint"),
    ("test_checkpoint.py", "TestCheckpoint"),
}


#: per-test wall-clock cap (seconds) applied when pytest-timeout is
#: installed (CI installs it; local runs without it are unchanged). A
#: deadlocked drain/abort test then fails fast with a stack dump instead of
#: eating the whole tier-1 budget. Generous: the slowest legitimate tests
#: (fuzz matrices, multi-config sweeps) finish well under it.
_PER_TEST_TIMEOUT_S = 300


def pytest_unconfigure(config):
    from llm_d_kv_cache_manager_tpu.utils import locktrace

    if locktrace.enabled():
        locktrace.deactivate()


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _locktrace_gate():
    """Fail any test on lock-order cycles / unguarded mutations recorded
    while it ran (LOCKTRACE=1 only; zero-cost no-op otherwise). A test that
    intentionally seeds a violation consumes it and calls ``reset()``
    before returning, so it passes this gate clean."""
    yield
    from llm_d_kv_cache_manager_tpu.utils import locktrace

    if locktrace.enabled():
        try:
            locktrace.assert_clean()
        finally:
            locktrace.reset()


def pytest_collection_modifyitems(config, items):
    import pytest

    have_timeout = config.pluginmanager.hasplugin("timeout")
    for item in items:
        if have_timeout and item.get_closest_marker("timeout") is None:
            item.add_marker(pytest.mark.timeout(_PER_TEST_TIMEOUT_S))
        cls = getattr(item, "cls", None)
        if cls is None:
            continue
        key = (os.path.basename(str(item.fspath)), cls.__name__)
        if key in _SLOW_CLASSES:
            item.add_marker(pytest.mark.slow)
