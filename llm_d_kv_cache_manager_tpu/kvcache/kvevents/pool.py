"""Event-ingestion worker pool: sharded, per-pod ordered.

Parity with reference ``pkg/kvcache/kvevents/pool.go``: incoming messages
are sharded by FNV-1a(pod id) onto per-worker FIFO queues so events for one
pod are always applied in order (``pool.go:125-137``); workers decode the
msgpack batch and apply Add/Evict to the block index. Poison pills are
dropped, not retried (``:174-180``).

TPU retarget: the pod entry tier comes from the event's ``medium`` field
({tpu_hbm, host_dram}) rather than the reference's hardcoded ``"gpu"``
(``pool.go:247``).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Optional, Sequence

from ...utils import get_logger
from ..kvblock import DeviceTier, Index, Key, PodEntry, tier_for_medium
from .events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    decode_event_batch,
)

log = get_logger("kvcache.kvevents.pool")

DEFAULT_CONCURRENCY = 4


def fnv1a_32(data: bytes) -> int:
    """FNV-1a 32-bit (matches Go ``hash/fnv.New32a``)."""
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


@dataclass
class Message:
    """One raw event message from the transport
    (reference ``zmq_subscriber.go`` Message)."""

    topic: str
    pod_identifier: str
    model_name: str
    payload: bytes
    seq: int = 0


@dataclass
class KVEventsPoolConfig:
    concurrency: int = DEFAULT_CONCURRENCY
    # Transport config is attached by the subscriber layer (zmq_subscriber).


class KVEventsPool:
    """Sharded ordered worker pool applying KV events to the index."""

    def __init__(self, index: Index, config: Optional[KVEventsPoolConfig] = None):
        self.config = config or KVEventsPoolConfig()
        if self.config.concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        self.index = index
        self._queues: list["queue.Queue[Optional[Message]]"] = [
            queue.Queue() for _ in range(self.config.concurrency)
        ]
        self._threads: list[threading.Thread] = []
        self._running = False
        self._mu = threading.Lock()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        with self._mu:
            if self._running:
                return
            self._running = True
            for i in range(self.config.concurrency):
                t = threading.Thread(
                    target=self._worker, args=(i,), name=f"kvevents-worker-{i}", daemon=True
                )
                t.start()
                self._threads.append(t)

    def shutdown(self) -> None:
        with self._mu:
            if not self._running:
                return
            self._running = False
            for q in self._queues:
                q.put(None)
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=5)

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until all queued *and in-flight* events have been applied."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(q.unfinished_tasks == 0 for q in self._queues):
                return True
            time.sleep(0.002)
        return False

    # -- ingestion ----------------------------------------------------------
    def add_task(self, msg: Message) -> None:
        """Shard by pod id so per-pod ordering holds."""
        shard = fnv1a_32(msg.pod_identifier.encode("utf-8")) % self.config.concurrency
        self._queues[shard].put(msg)

    def _worker(self, shard: int) -> None:
        q = self._queues[shard]
        while True:
            msg = q.get()
            if msg is None:
                q.task_done()
                return
            try:
                self._process_event(msg)
            except Exception:
                # Poison pill or backend failure on one message must not kill
                # the worker; drop and continue (reference pool.go:174-180).
                log.exception("failed to process event message; dropping")
            finally:
                q.task_done()

    def _process_event(self, msg: Message) -> None:
        batch = decode_event_batch(msg.payload)
        if batch is None:
            log.debug("failed to unmarshal event batch, dropping message", topic=msg.topic)
            return

        for ev in batch.events:
            if isinstance(ev, BlockStored):
                keys = [Key(msg.model_name, h) for h in ev.block_hashes]
                entries = [PodEntry(msg.pod_identifier, tier_for_medium(ev.medium))]
                try:
                    self.index.add(keys, entries)
                except Exception:
                    log.exception("failed to add event to index", pod=msg.pod_identifier)
            elif isinstance(ev, BlockRemoved):
                if ev.medium is None:
                    # No medium (incl. legacy events) = the pod no longer
                    # holds the block at all: clear every tier, else an entry
                    # stored with an explicit medium would never match the
                    # eviction and stale locality would persist forever.
                    entries = [PodEntry(msg.pod_identifier, t) for t in DeviceTier]
                else:
                    entries = [PodEntry(msg.pod_identifier, tier_for_medium(ev.medium))]
                for h in ev.block_hashes:
                    try:
                        self.index.evict(Key(msg.model_name, h), entries)
                    except Exception:
                        log.exception("failed to evict from index", pod=msg.pod_identifier)
            elif isinstance(ev, AllBlocksCleared):
                # No-op, as in the reference (pool.go:300-301): the event
                # carries no hash list, and the index ages entries out.
                continue
