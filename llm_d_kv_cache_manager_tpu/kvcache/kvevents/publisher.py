"""ZMQ PUB publisher for KV events.

Counterpart of the subscriber: used by the in-tree JAX serving engine's
block manager to announce block stores/evictions, and by demos/tests to
simulate a fleet (reference ``examples/kv_events/offline/publisher.go``).
Publishers **connect** to the subscriber's bound endpoint; each message is
3 frames ``[topic, seq (8B big-endian), msgpack payload]`` with a
monotonically increasing per-publisher sequence number.
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ...utils import get_logger
from .events import Event, EventBatch

log = get_logger("kvcache.kvevents.publisher")


@dataclass
class ZMQPublisherConfig:
    endpoint: str = "tcp://localhost:5557"
    pod_identifier: str = "local-pod"
    model_name: str = "unknown-model"
    # Rank of this publisher in a data-parallel fleet, tagged onto batches.
    data_parallel_rank: Optional[int] = None


#: bounded send retries: the index tolerates lost batches (LRU staleness
#: model), so after these attempts the batch is DROPPED — a transient
#: socket error must never raise into the engine loop and kill serving.
_SEND_ATTEMPTS = 3
_SEND_BACKOFF_S = 0.05


class ZMQPublisher:
    def __init__(self, config: ZMQPublisherConfig):
        import zmq

        self.config = config
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.connect(config.endpoint)
        self._mu = threading.Lock()
        self._seq = 0  # guarded_by: _mu
        self._closed = False  # guarded_by: _mu
        self.dropped_batches = 0  # guarded_by: _mu
        self.topic = f"kv@{config.pod_identifier}@{config.model_name}"

    def publish(self, events: list[Event], ts: Optional[float] = None) -> int:
        """Publish one EventBatch; returns the sequence number used (-1
        when the publisher is closed or the batch was dropped after
        bounded retries — the subscriber's seq gaps flag the loss)."""
        import zmq

        batch = EventBatch(
            # Wall clock on purpose: ts crosses the wire, compared across hosts.
            ts=ts if ts is not None else time.time(),  # kvlint: disable=monotonic-time
            events=events,
            data_parallel_rank=self.config.data_parallel_rank,
        )
        payload = batch.to_payload()
        with self._mu:
            if self._closed:
                log.warning("publish after close; dropping batch")
                self.dropped_batches += 1
                return -1
            # The seq is consumed HERE, before any send attempt: a dropped
            # batch therefore leaves a hole in the stream and the next
            # successful publish exposes it — subscribers detect the gap
            # and trigger resync instead of silently desyncing.
            seq = self._seq
            self._seq += 1
            frames = [self.topic.encode("utf-8"), struct.pack(">Q", seq), payload]
            # Send/backoff UNDER _mu on purpose: PUB sockets are not
            # thread-safe, and releasing the lock mid-retry would let a
            # later seq overtake this one on the wire — subscribers would
            # read the reorder as a gap and trigger spurious resyncs.
            # Worst case is ~0.15s (bounded retries); publish is called
            # off the engine's hot path.
            for attempt in range(_SEND_ATTEMPTS):
                try:
                    self._sock.send_multipart(frames)  # kvlint: disable=lock-discipline
                    return seq
                except zmq.ZMQError as e:
                    if attempt + 1 == _SEND_ATTEMPTS:
                        # Give up: the engine loop must keep serving; the
                        # dropped-batch counter rides on heartbeats and the
                        # skipped seq flags the gap to subscribers.
                        self.dropped_batches += 1
                        log.warning(
                            "dropping event batch after bounded retries",
                            pod=self.config.pod_identifier,
                            model=self.config.model_name,
                            error=repr(e),
                            attempts=_SEND_ATTEMPTS,
                            seq=seq,
                            dropped_total=self.dropped_batches,
                        )
                        return -1
                    time.sleep(_SEND_BACKOFF_S * (2**attempt))  # kvlint: disable=lock-discipline
        return -1  # unreachable; keeps the contract explicit

    def close(self) -> None:
        """Idempotent: double-close must not hit an already-closed socket."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            self._sock.close(linger=100)
