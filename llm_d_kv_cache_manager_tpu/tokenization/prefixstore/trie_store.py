"""Alternative prefix store: character trie (no eviction).

Parity with reference ``pkg/tokenization/prefixstore/trie_store.go``: a
per-model character trie where each node records the tokens that become
fully contained once the prefix reaches that character (token ``[, high]``
byte offset ≤ the node's byte position). Lookup walks the prompt until the
first unseen character, collecting newly-contained tokens and the covered
ratio. Not the default: unbounded growth and slower than the LRU store
(reference ``docs/architecture.md:159-160``).

Design deviations from the reference (both correctness fixes):

- nodes store *all* newly-contained token ids at their position rather than
  only the last one — the reference drops intermediate tokens when several
  (e.g. zero-width specials) become contained at the same character;
- each insert stamps its path with a generation, and lookups stop at the
  first generation change — the reference happily splices token indexes
  from different tokenizations that overwrote each other's shared-prefix
  nodes, returning corrupted sequences with full overlap ratio.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from .indexer import Config, Indexer, Offset


class _Node:
    __slots__ = ("children", "new_tokens", "last_index", "gen")

    def __init__(self):
        self.children: dict[str, _Node] = {}
        # token ids newly contained at this node, and the index of the last
        # contained token in the full tokenization (-1 = none).
        self.new_tokens: list[int] = []
        self.last_index: int = -1
        # generation of the insert that last wrote this node. Every insert
        # rewrites a contiguous path from the root, so along any root path
        # generations are non-increasing; mixing nodes from different
        # generations would splice token indexes from different
        # tokenizations, so lookups stop at the first generation change.
        self.gen: int = 0


class ContainedTokenStore(Indexer):
    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config()
        self._tries: dict[str, _Node] = {}
        self._gen = 0
        self._mu = threading.RLock()

    def _trie(self, model_name: str, create: bool) -> Optional[_Node]:
        trie = self._tries.get(model_name)
        if trie is None and create:
            trie = _Node()
            self._tries[model_name] = trie
        return trie

    def add_tokenization(
        self,
        model_name: str,
        prompt: str,
        tokens: Sequence[int],
        offsets: Sequence[Offset],
    ) -> None:
        if not prompt or not tokens:
            return
        if len(tokens) != len(offsets):
            raise ValueError("tokens and offsets must be parallel")

        with self._mu:
            self._gen += 1
            gen = self._gen
            node = self._trie(model_name, create=True)
            # Tokens contained before any character (zero-width specials at
            # position 0) attach to the root.
            k = -1
            root_new = []
            while k + 1 < len(tokens) and offsets[k + 1][1] <= 0:
                k += 1
                root_new.append(int(tokens[k]))
            node.new_tokens = root_new
            node.last_index = k
            node.gen = gen

            byte_pos = 0
            for ch in prompt:
                byte_pos += len(ch.encode("utf-8"))
                new_here: list[int] = []
                while k + 1 < len(tokens) and offsets[k + 1][1] <= byte_pos:
                    k += 1
                    new_here.append(int(tokens[k]))
                child = node.children.get(ch)
                if child is None:
                    child = _Node()
                    node.children[ch] = child
                node = child
                node.new_tokens = new_here
                node.last_index = k
                node.gen = gen

    def find_longest_contained_tokens(
        self, prompt: str, model_name: str
    ) -> tuple[list[int], float]:
        with self._mu:
            node = self._trie(model_name, create=False)
            if node is None or not prompt:
                return [], 0.0

            contained: list[int] = []
            expected_gen = node.gen  # root carries the latest insert's gen
            contained.extend(node.new_tokens)

            matched_chars = 0
            for ch in prompt:
                child = node.children.get(ch)
                if child is None or child.gen != expected_gen:
                    # gen change = this subpath was written by a different
                    # (older) tokenization than the nodes already collected;
                    # splicing them would corrupt the sequence.
                    break
                node = child
                matched_chars += 1
                contained.extend(node.new_tokens)
            return contained, matched_chars / len(prompt)
