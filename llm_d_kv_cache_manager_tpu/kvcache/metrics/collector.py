"""Prometheus metrics for the KV-block index.

Metric names match the reference collectors
(``pkg/kvcache/metrics/collector.go:29-54``):

- ``kvcache_index_admissions_total``
- ``kvcache_index_evictions_total``
- ``kvcache_index_lookup_requests_total``
- ``kvcache_index_lookup_hits_total``  (defined-but-never-incremented in the
  reference — a noted gap; here it counts per-key hits returned by lookups)
- ``kvcache_index_lookup_latency_seconds`` histogram

A periodic "metrics beat" log thread mirrors ``StartMetricsLogging``
(``collector.go:75-130``). Falls back to inert counters when
``prometheus_client`` is unavailable so the library never hard-depends on it.
"""

from __future__ import annotations

import threading
from typing import Optional

from ...obs.lifecycle import COLD_DISTANCE_CLAMP, REUSE_DISTANCE_BUCKETS
from ...utils import get_logger

log = get_logger("kvcache.metrics")

try:
    import prometheus_client as _prom
except ImportError:  # pragma: no cover
    _prom = None


class _NullMetric:
    def inc(self, *_a, **_k):
        pass

    def observe(self, *_a, **_k):
        pass

    def set(self, *_a, **_k):
        pass

    def labels(self, *_a, **_k):
        return self


_registered = False
_lock = threading.Lock()

admissions = _NullMetric()
evictions = _NullMetric()
lookup_requests = _NullMetric()
lookup_hits = _NullMetric()
lookup_latency = _NullMetric()
# Fleet self-healing (PR 3): seq gaps, snapshot resyncs, dead-pod sweeps,
# publisher-reported drops, transfer circuit-breaker transitions.
fleet_gaps = _NullMetric()
fleet_resyncs = _NullMetric()
fleet_pods_swept = _NullMetric()
fleet_publisher_drops = _NullMetric()
breaker_opens = _NullMetric()
breaker_closes = _NullMetric()
# Request-lifecycle robustness (PR 4): pods that said a PodDrained goodbye
# (evicted without a TTL wait), scoring requests degraded to an empty
# scoreboard because the index backend failed (routing falls back to cold
# placement instead of erroring the request).
fleet_pods_drained = _NullMetric()
scorer_errors = _NullMetric()
# Observability (PR 5): routing-decision counter (labeled by the blended
# router's verdict), scorer score latency, and index-occupancy gauges so
# dashboards can correlate routing quality with index fill.
route_decisions = _NullMetric()
score_latency = _NullMetric()
index_blocks = _NullMetric()
index_pods = _NullMetric()
# Routing-quality observability (PR 10): event-plane staleness (publish →
# index-visibility lag per pod/event type, events-behind per pod), the
# predicted-vs-realized audit loop (hit ratio, per-decision regret, miss
# attribution), and the scoreboard-size gauge. Series appear only when the
# OBS_AUDIT/OBS_METRICS surfaces feed them — a knobs-off process never
# touches a label.
index_staleness = _NullMetric()
index_events_behind = _NullMetric()
scoreboard_size = _NullMetric()
route_pvr = _NullMetric()
route_regret = _NullMetric()
route_miss = _NullMetric()
# Predicted-TTFT routing (ISSUE 14): the latency model's per-decision
# prediction and its realized/predicted honesty ratio from the audit
# join. Series appear only when ROUTE_PREDICT feeds them — a knobs-off
# process never observes either.
route_predicted_ttft = _NullMetric()
route_ttft_ratio = _NullMetric()
# Sharded control plane (PR 11): per-shard index occupancy and stale-ring
# misroute forwards. Series appear only when SCORER_SHARDS partitions the
# index — a knobs-off process never touches a shard label (the staleness /
# events-behind families above likewise grow a ``shard`` label that stays
# "" until the sharded plane feeds them).
shard_blocks = _NullMetric()
shard_pods = _NullMetric()
shard_misroutes = _NullMetric()
# KV-capacity observability plane (ISSUE 15): block tier transitions +
# per-tier residency from the lifecycle ledger, and the sampled
# reuse-distance histogram behind the MRC. Series appear only when
# OBS_LIFECYCLE attaches the ledger/estimator — a knobs-off process never
# touches a label.
block_transitions = _NullMetric()
block_residency = _NullMetric()
reuse_distance = _NullMetric()
# KV-block integrity plane (ISSUE 19): content-digest checks at tier
# transitions, quarantines, scrubber coverage, and fleet BadBlock
# revocations. Series appear only when KV_INTEGRITY feeds them — a
# knobs-off process never touches a label.
integrity_checks = _NullMetric()
integrity_quarantined = _NullMetric()
integrity_scrub_pages = _NullMetric()
integrity_bad_blocks = _NullMetric()
# Fleet observability federation (ISSUE 20): the derived fleet health
# rollup and the federator's own scrape accounting. Series appear only
# when OBS_FED scrapes feed them — a knobs-off process never sets the
# gauge or observes a scrape.
fleet_health_score = _NullMetric()
fleet_scrape_seconds = _NullMetric()
fleet_scrape_errors = _NullMetric()
fleet_pods_skipped = _NullMetric()

# Internal shadow counters so the metrics beat can log without scraping.
_shadow = {
    "admissions": 0,
    "evictions": 0,
    "lookup_requests": 0,
    "lookup_hits": 0,
    "fleet_gaps": 0,
    "fleet_resyncs": 0,
    "fleet_pods_swept": 0,
    "fleet_publisher_drops": 0,
    "breaker_opens": 0,
    "breaker_closes": 0,
    "fleet_pods_drained": 0,
    "scorer_errors": 0,
}
_shadow_lock = threading.Lock()


def bump(name: str, amount: int = 1) -> None:
    with _shadow_lock:
        _shadow[name] = _shadow.get(name, 0) + amount


def snapshot() -> dict:
    with _shadow_lock:
        return dict(_shadow)


def register(registry=None) -> None:
    """Idempotently create and register the collectors."""
    global _registered, admissions, evictions, lookup_requests, lookup_hits, lookup_latency
    global fleet_gaps, fleet_resyncs, fleet_pods_swept, fleet_publisher_drops
    global breaker_opens, breaker_closes, fleet_pods_drained, scorer_errors
    global route_decisions, score_latency, index_blocks, index_pods
    global index_staleness, index_events_behind, scoreboard_size
    global route_pvr, route_regret, route_miss
    global route_predicted_ttft, route_ttft_ratio
    global shard_blocks, shard_pods, shard_misroutes
    global block_transitions, block_residency, reuse_distance
    global integrity_checks, integrity_quarantined
    global integrity_scrub_pages, integrity_bad_blocks
    global fleet_health_score, fleet_scrape_seconds
    global fleet_scrape_errors, fleet_pods_skipped
    with _lock:
        if _registered:
            return
        if _prom is None:
            _registered = True
            return
        registry = registry or _prom.REGISTRY
        admissions = _prom.Counter(
            "kvcache_index_admissions_total",
            "Total number of KV-block admissions into the index",
            registry=registry,
        )
        evictions = _prom.Counter(
            "kvcache_index_evictions_total",
            "Total number of KV-block evictions from the index",
            registry=registry,
        )
        lookup_requests = _prom.Counter(
            "kvcache_index_lookup_requests_total",
            "Total number of index lookup requests",
            registry=registry,
        )
        lookup_hits = _prom.Counter(
            "kvcache_index_lookup_hits_total",
            "Total number of per-key hits returned by index lookups",
            registry=registry,
        )
        lookup_latency = _prom.Histogram(
            "kvcache_index_lookup_latency_seconds",
            "Latency of index lookups in seconds",
            registry=registry,
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
        )
        fleet_gaps = _prom.Counter(
            "kvcache_fleet_event_gaps_total",
            "Sequence gaps detected in pod event streams",
            registry=registry,
        )
        fleet_resyncs = _prom.Counter(
            "kvcache_fleet_resyncs_total",
            "IndexSnapshot resyncs applied (replace-all-for-pod)",
            registry=registry,
        )
        fleet_pods_swept = _prom.Counter(
            "kvcache_fleet_pods_swept_total",
            "Pods swept from the index after TTL expiry",
            registry=registry,
        )
        fleet_publisher_drops = _prom.Counter(
            "kvcache_fleet_publisher_drops_total",
            "Event batches publishers reported dropping (via heartbeats)",
            registry=registry,
        )
        breaker_opens = _prom.Counter(
            "kvcache_transfer_breaker_opens_total",
            "Transfer circuit-breaker open transitions",
            registry=registry,
        )
        breaker_closes = _prom.Counter(
            "kvcache_transfer_breaker_closes_total",
            "Transfer circuit-breaker close transitions (half-open probe ok)",
            registry=registry,
        )
        fleet_pods_drained = _prom.Counter(
            "kvcache_fleet_pods_drained_total",
            "Pods evicted immediately after a PodDrained goodbye",
            registry=registry,
        )
        scorer_errors = _prom.Counter(
            "kvcache_scorer_errors_total",
            "Scoring requests degraded to an empty scoreboard because the "
            "index backend failed",
            registry=registry,
        )
        route_decisions = _prom.Counter(
            "kvcache_scorer_route_decisions_total",
            "Blended-router routing decisions by verdict "
            "(route_warm / pull / cold)",
            ["decision"],
            registry=registry,
        )
        score_latency = _prom.Histogram(
            "kvcache_scorer_score_seconds",
            "Wall time of one scoring request (tokenize + hash + index "
            "lookup + score), as served by the scoring API",
            registry=registry,
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
        )
        index_blocks = _prom.Gauge(
            "kvcache_index_blocks",
            "Block keys currently tracked by the KV-block index "
            "(refreshed on /stats and /metrics scrapes)",
            registry=registry,
        )
        index_pods = _prom.Gauge(
            "kvcache_index_pods",
            "Distinct pods currently holding at least one index entry "
            "(refreshed on /stats and /metrics scrapes)",
            registry=registry,
        )
        index_staleness = _prom.Histogram(
            "kvcache_index_staleness_seconds",
            "Event-plane lag: publish timestamp to index application, per "
            "pod and event type (OBS_AUDIT); the shard label is \"\" on a "
            "single index and the owning scorer shard under SCORER_SHARDS",
            ["pod", "event", "shard"],
            registry=registry,
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
        )
        index_events_behind = _prom.Gauge(
            "kvcache_index_events_behind",
            "Events received from a pod's publisher but not yet applied "
            "to the index (subscriber seq high-water minus worker "
            "high-water; refreshed on /stats and /metrics scrapes); the "
            "shard label is \"\" on a single index and the ingest lane's "
            "shard under SCORER_SHARDS",
            ["pod", "shard"],
            registry=registry,
        )
        scoreboard_size = _prom.Gauge(
            "kvcache_scorer_scoreboard_size",
            "Pods in the most recent scoring response's scoreboard "
            "(OBS_METRICS)",
            registry=registry,
        )
        route_pvr = _prom.Histogram(
            "kvcache_route_predicted_vs_realized_blocks",
            "Realized prefix-cache hit blocks over the scorer's predicted "
            "matched blocks, per audited request (1.0 = the prediction "
            "held exactly; OBS_AUDIT)",
            registry=registry,
            buckets=(0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0, 1.25,
                     1.5, 2.0),
        )
        route_regret = _prom.Histogram(
            "kvcache_route_regret_blocks",
            "Per-decision counterfactual regret: best scoreboard entry "
            "minus the chosen pod's score, in blocks (0 = the warmest pod "
            "was picked), labeled by routing decision (OBS_AUDIT)",
            ["decision"],
            registry=registry,
            buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                     256.0),
        )
        route_miss = _prom.Counter(
            "kvcache_route_miss_attributed_total",
            "Audited requests whose realized hits fell short of the "
            "prediction, by attributed cause (stale_index / evicted_on_pod "
            "/ never_stored / dead_pod_reroute / quarantined; OBS_AUDIT)",
            ["cause"],
            registry=registry,
        )
        route_predicted_ttft = _prom.Histogram(
            "kvcache_route_predicted_ttft_seconds",
            "Modeled TTFT of the chosen routing arm (queue wait + miss "
            "prefill + pull cost, corrector-adjusted) per predicted-"
            "routing decision (ROUTE_PREDICT)",
            registry=registry,
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     5.0, 10.0, 30.0, 60.0),
        )
        route_ttft_ratio = _prom.Histogram(
            "kvcache_route_ttft_realized_over_predicted",
            "Realized TTFT over the routing model's predicted TTFT per "
            "audited request (1.0 = the latency model told the truth; "
            "ROUTE_PREDICT + OBS_AUDIT join)",
            registry=registry,
            buckets=(0.1, 0.25, 0.5, 0.8, 0.9, 1.0, 1.1, 1.25, 1.5, 2.0,
                     4.0, 10.0),
        )
        shard_blocks = _prom.Gauge(
            "kvcache_index_shard_blocks",
            "Block keys tracked by one scorer shard's sub-index "
            "(SCORER_SHARDS; refreshed on /stats and /metrics scrapes)",
            ["shard"],
            registry=registry,
        )
        shard_pods = _prom.Gauge(
            "kvcache_index_shard_pods",
            "Distinct pods holding at least one entry on one scorer "
            "shard's sub-index (SCORER_SHARDS; refreshed on /stats and "
            "/metrics scrapes)",
            ["shard"],
            registry=registry,
        )
        shard_misroutes = _prom.Counter(
            "kvcache_shard_misroute_total",
            "Event ops that landed on a stale-ring shard and were "
            "forwarded once to the current owner (SCORER_SHARDS resize "
            "in flight), labeled by the shard that observed the misroute",
            ["shard"],
            registry=registry,
        )
        block_transitions = _prom.Counter(
            "kvcache_block_tier_transitions_total",
            "KV-block tier transitions recorded by the lifecycle ledger "
            "(OBS_LIFECYCLE): from/to in {none, tpu_hbm, host_dram, "
            "remote}, reason = allocate/import/spill/restore/prefetch/"
            "demote/demote_failed/evict (pod hooks) or stored/removed/"
            "drained/resync/ttl_swept (scorer event feed)",
            ["from", "to", "reason"],
            registry=registry,
        )
        block_residency = _prom.Histogram(
            "kvcache_block_tier_residency_seconds",
            "How long a KV block stayed resident in a tier before "
            "leaving it (observed at departure; OBS_LIFECYCLE)",
            ["tier"],
            registry=registry,
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0, 600.0, 1800.0, 3600.0),
        )
        reuse_distance = _prom.Histogram(
            "kvcache_reuse_distance_blocks",
            "Sampled LRU stack distance of prefix-block lookups, in "
            "blocks (OBS_LIFECYCLE): P[distance < C] is the modeled hit "
            "rate of a C-block tier — the MRC behind /debug/mrc; cold "
            "first-ever accesses land in +Inf",
            registry=registry,
            buckets=tuple(float(b) for b in REUSE_DISTANCE_BUCKETS),
        )
        integrity_checks = _prom.Counter(
            "kvcache_integrity_checks_total",
            "Content-digest verifications at KV tier transitions "
            "(KV_INTEGRITY), by transition path (restore / prefetch / "
            "import / remote_accept / remote_serve / export / scrub) and "
            "outcome (ok / corrupt / unverified — no recorded digest)",
            ["path", "outcome"],
            registry=registry,
        )
        integrity_quarantined = _prom.Counter(
            "kvcache_integrity_quarantined_total",
            "KV block copies quarantined after a failed content-digest "
            "check (KV_INTEGRITY), by the tier holding the bad copy "
            "(host_dram / remote / wire)",
            ["tier"],
            registry=registry,
        )
        integrity_scrub_pages = _prom.Counter(
            "kvcache_integrity_scrub_pages_total",
            "Resident host-tier pages verified by the background "
            "integrity scrubber (KV_INTEGRITY + INTEGRITY_SCRUB_INTERVAL_S)",
            registry=registry,
        )
        integrity_bad_blocks = _prom.Counter(
            "kvcache_integrity_bad_blocks_total",
            "Block hashes revoked fleet-wide by BadBlock events as seen "
            "by this process (published locally or applied by the scorer "
            "index; KV_INTEGRITY)",
            registry=registry,
        )
        fleet_health_score = _prom.Gauge(
            "kvcache_fleet_health_score",
            "Derived fleet health rollup in [0, 1] from the last "
            "federated scrape (OBS_FED): mean per-pod score — "
            "unreachable/expired pods score 0, draining caps at 0.5, "
            "burning SLOs / open breakers / near-full HBM / quarantines "
            "deduct (see obs/federation.py); refreshed per scrape",
            registry=registry,
        )
        fleet_scrape_seconds = _prom.Histogram(
            "kvcache_fleet_scrape_seconds",
            "Wall time of one federated fleet scrape-and-join across "
            "all registered pods (OBS_FED)",
            registry=registry,
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
        )
        fleet_scrape_errors = _prom.Counter(
            "kvcache_fleet_scrape_errors_total",
            "Registered pods whose /stats fetch failed during a "
            "federated scrape (OBS_FED) — expired pods are skipped, "
            "not errored",
            registry=registry,
        )
        fleet_pods_skipped = _prom.Counter(
            "kvcache_fleet_scrape_pods_skipped_total",
            "Registered pods skipped outright by a federated scrape "
            "because FleetHealth reported them expired/swept/drained "
            "(OBS_FED) — the dead-pod-costs-one-skip guarantee",
            registry=registry,
        )
        _registered = True


def observe_score_latency(seconds: float, trace_id: Optional[str] = None) -> None:
    """One scoring request's wall time. Under OBS_EXEMPLARS the caller
    passes the observing request's trace_id, which rides as an
    OpenMetrics exemplar on the bucket it lands in — a tail bucket then
    resolves directly to ``/debug/traces?trace=<id>``. Exemplars render
    only in the OpenMetrics exposition (the classic text format drops
    them), so the scorer switches formats under the same knob."""
    if trace_id:
        score_latency.observe(seconds, exemplar={"trace_id": trace_id})
    else:
        score_latency.observe(seconds)


def observe_fleet_scrape(
    scrape_s: float,
    errors: int = 0,
    skipped: int = 0,
    health: Optional[float] = None,
) -> None:
    """Mirror one federated fleet scrape into the OBS_FED families
    (scrape-driven, like the occupancy gauges): join wall time, per-scrape
    fetch errors and dead-pod skips, and the derived health rollup."""
    bump("fleet_scrapes")
    fleet_scrape_seconds.observe(scrape_s)
    if errors:
        fleet_scrape_errors.inc(errors)
    if skipped:
        fleet_pods_skipped.inc(skipped)
    if health is not None:
        fleet_health_score.set(health)


def observe_route_decision(action: str) -> None:
    """One blended-router verdict (route_warm / pull / cold)."""
    bump(f"route_decisions_{action}")
    route_decisions.labels(decision=action).inc()


def observe_staleness(pod: str, event: str, lag_s: float, shard: str = "") -> None:
    """One event's publish→index-application lag (OBS_AUDIT). ``shard``
    is "" on a single index; the sharded plane labels each observation
    with the applying shard."""
    bump("staleness_events")
    index_staleness.labels(pod=pod, event=event, shard=shard).observe(lag_s)


def set_events_behind(pod: str, behind: int, shard: str = "") -> None:
    index_events_behind.labels(pod=pod, shard=shard).set(behind)


def set_shard_index_size(shard: str, blocks: int, pods: int) -> None:
    """Refresh one scorer shard's occupancy gauges (scrape-driven)."""
    shard_blocks.labels(shard=shard).set(blocks)
    shard_pods.labels(shard=shard).set(pods)


def observe_shard_misroute(shard: str, n: int = 1) -> None:
    """Stale-ring misroute forwards observed by ``shard`` (SCORER_SHARDS)."""
    bump("shard_misroutes", n)
    shard_misroutes.labels(shard=shard).inc(n)


def set_scoreboard_size(n: int) -> None:
    scoreboard_size.set(n)


def observe_predicted_vs_realized(ratio: float) -> None:
    """Realized/predicted blocks for one audited request (OBS_AUDIT)."""
    bump("route_audits_joined")
    route_pvr.observe(ratio)


def observe_route_regret(decision: str, regret_blocks: int) -> None:
    route_regret.labels(decision=decision).observe(regret_blocks)


def observe_predicted_ttft(seconds: float) -> None:
    """One predicted-routing decision's modeled TTFT (ROUTE_PREDICT)."""
    bump("route_predictions")
    route_predicted_ttft.observe(seconds)


def observe_ttft_ratio(ratio: float) -> None:
    """Realized/predicted TTFT for one audited predicted-routing
    decision (ROUTE_PREDICT + OBS_AUDIT join)."""
    bump("route_ttft_joins")
    route_ttft_ratio.observe(ratio)


def observe_miss_cause(cause: str) -> None:
    bump(f"route_miss_{cause}")
    route_miss.labels(cause=cause).inc()


def observe_integrity_check(path: str, outcome: str) -> None:
    """One content-digest verification at a tier transition (KV_INTEGRITY)."""
    bump(f"integrity_checks_{outcome}")
    integrity_checks.labels(path=path, outcome=outcome).inc()


def observe_quarantine(tier: str) -> None:
    """One block copy quarantined after a corrupt digest (KV_INTEGRITY)."""
    bump("integrity_quarantined")
    integrity_quarantined.labels(tier=tier).inc()


def observe_scrub_pages(n: int) -> None:
    """Host-tier pages the background scrubber verified (KV_INTEGRITY)."""
    if n:
        bump("integrity_scrub_pages", n)
        integrity_scrub_pages.inc(n)


def observe_bad_blocks(n: int) -> None:
    """Block hashes revoked by BadBlock events (KV_INTEGRITY)."""
    if n:
        bump("integrity_bad_blocks", n)
        integrity_bad_blocks.inc(n)


def observe_tier_transition(frm: str, to: str, reason: str) -> None:
    """One lifecycle-ledger tier transition (OBS_LIFECYCLE). Keyword
    form avoided: ``from`` is a Python keyword, so the label rides
    positionally via labels(frm, to, reason)."""
    bump("block_transitions")
    block_transitions.labels(frm, to, reason).inc()


def observe_tier_residency(tier: str, seconds: float) -> None:
    block_residency.labels(tier=tier).observe(seconds)


def observe_reuse_distance(distance_blocks: float) -> None:
    """One sampled reuse distance (inf = cold first-ever access). Cold
    accesses are clamped to a finite over-the-top value so they land in
    the +Inf bucket without poisoning the ``_sum`` series with inf."""
    bump("reuse_distances")
    reuse_distance.observe(min(distance_blocks, COLD_DISTANCE_CLAMP))


def set_index_size(blocks: int, pods: int) -> None:
    """Refresh the index-occupancy gauges (scrape-driven, not event-driven:
    walking the index is O(keys), so only /stats and /metrics pay it)."""
    index_blocks.set(blocks)
    index_pods.set(pods)
    with _shadow_lock:
        _shadow["index_blocks"] = blocks
        _shadow["index_pods"] = pods


_beat_thread: Optional[threading.Thread] = None
_beat_stop = threading.Event()


def start_metrics_logging(interval_seconds: float) -> None:
    """Spawn the non-blocking metrics-beat logger (idempotent)."""
    global _beat_thread
    with _lock:
        if _beat_thread is not None and _beat_thread.is_alive():
            return
        _beat_stop.clear()

        def beat():
            while not _beat_stop.wait(interval_seconds):
                log.info("metrics beat", **snapshot())

        _beat_thread = threading.Thread(target=beat, name="kvcache-metrics-beat", daemon=True)
        _beat_thread.start()


def stop_metrics_logging(timeout: float = 2.0) -> None:
    """Stop the metrics beat and JOIN the thread. Without the join (the
    pre-PR-5 bug) a stop/start pair in one process raced: ``start`` saw the
    old thread still alive, returned early, and the beat never restarted —
    and the half-dead thread leaked past interpreter teardown checks."""
    global _beat_thread
    _beat_stop.set()
    with _lock:
        thread, _beat_thread = _beat_thread, None
    if thread is not None and thread.is_alive():
        thread.join(timeout=timeout)
