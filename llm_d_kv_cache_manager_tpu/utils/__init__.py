from .logging import DEBUG, TRACE, RateLimitedWarn, get_logger, log_context

__all__ = ["get_logger", "log_context", "RateLimitedWarn", "DEBUG", "TRACE"]
