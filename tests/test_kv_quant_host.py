"""KV capacity tiers suite (ISSUE 6 acceptance).

int8 paged-KV quantization + the first-class host-DRAM tier with prefetch:

- **Quantize→dequantize bounds**: per-element error <= scale/2, zeros
  exact, scale geometry pinned (per-page, per-(layer, kv_head)).
- **Spill→bring-back parity**: greedy outputs through a quantized host
  tier match the fp, no-eviction baseline — with the ``kv_quant`` knob on
  AND off (off = bit-identical mechanism already pinned by
  ``test_engine``; on = the int8 round trip must not change tokens).
- **Quantized transfer**: the wire's optional quant triple round-trips,
  legacy response bytes are unchanged when the knob is off, quantized
  imports reproduce cold-prefill outputs, and tampered payloads (token
  flip, truncated scales) are rejected before anything registers.
- **Prefetch-vs-blocking equivalence**: the ahead-of-scheduler bring-back
  stage produces identical outputs to allocate-time restores, also when
  the KV-event plane runs through a delaying ``ChaosLink``; after release
  the index converges to engine ground truth — including the
  ``medium="host_dram"`` ``BlockStored`` emitted on spill, pinned down to
  the ``PodEntry`` tier.
- **Observability**: ``kvcache_host_*`` metric families (OBS_METRICS
  surface), per-path hit accounting, the ``/stats`` host block gated on
  the tier knob, and the ``pod.host_bringback`` span.
"""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from chaos import ChaosLink, engine_truth, index_view_of_pod
from llm_d_kv_cache_manager_tpu.kvcache import (
    KVCacheIndexer,
    KVCacheIndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    Key,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.keys import DeviceTier
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    KVEventsPool,
    KVEventsPoolConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.transfer import protocol
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA, quant
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    Engine,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import (
    PodServer,
    PodServerConfig,
    _ServingMetrics,
)

PS = 4
MODEL = "tiny-llama"


def _engine_config(
    total_pages=64,
    host_pages=0,
    kv_quant=None,
    host_prefetch=False,
    host_tier_policy="always",
):
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(
            total_pages=total_pages, page_size=PS, host_pages=host_pages
        ),
        scheduler=SchedulerConfig(max_prefill_batch=4),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
        kv_quant=kv_quant,
        host_prefetch=host_prefetch,
        host_tier_policy=host_tier_policy,
    )


def _engine(**kw):
    return Engine(_engine_config(**kw))


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _page(seed, shape=(3, PS, 2, 8), dtype=np.float32):
    return (
        np.random.default_rng(seed).standard_normal(shape).astype(dtype) * 3.7
    )


class TestKVPageQuantization:
    def test_round_trip_error_bounded(self):
        x = _page(0)
        q, scale = quant.quantize_kv_page(x)
        assert q.dtype == np.int8
        d = quant.dequantize_kv_page(q, scale, np.float32)
        # Symmetric rounding: per-element error is bounded by scale/2,
        # broadcast over the (layer, head) the element belongs to.
        assert (np.abs(d - x) <= scale / 2 + 1e-6).all()

    def test_bf16_pages_supported(self):
        import jax.numpy as jnp

        bf16 = np.dtype(jnp.bfloat16.dtype.name)
        x = _page(1).astype(bf16)
        q, scale = quant.quantize_kv_page(x)
        d = quant.dequantize_kv_page(q, scale, bf16)
        assert d.dtype == bf16 and d.shape == x.shape
        assert (
            np.abs(d.astype(np.float32) - x.astype(np.float32))
            <= scale / 2 + 0.05  # bf16 storage rounding on top of quant
        ).all()

    def test_zeros_round_trip_exactly(self):
        q, scale = quant.quantize_kv_page(np.zeros((2, PS, 1, 4), np.float32))
        assert (q == 0).all()
        assert (quant.dequantize_kv_page(q, scale, np.float32) == 0).all()

    def test_scale_geometry_per_layer_per_head(self):
        shape = (3, PS, 2, 8)
        assert quant.kv_scale_shape(shape) == (3, 1, 2, 1)
        q, scale = quant.quantize_kv_page(_page(2, shape))
        assert scale.shape == (3, 1, 2, 1) and scale.dtype == np.float32
        # An outlier in one (layer, head) must not coarsen the others.
        x = np.ones(shape, np.float32)
        x[0, :, 0, :] = 1000.0
        _, s2 = quant.quantize_kv_page(x)
        assert s2[0, 0, 0, 0] > 100 * s2[0, 0, 1, 0]

    def test_unknown_kv_quant_mode_rejected(self):
        with pytest.raises(ValueError, match="kv_quant"):
            _engine(kv_quant="fp4")


class TestQuantizedSpillBringBack:
    def _run(self, **kw):
        prompts = [_prompt(70 + i, 16) for i in range(3)]
        eng = _engine(**kw)
        outs = []
        for p in prompts + [prompts[0]]:
            s = eng.add_request(p, SamplingParams(max_new_tokens=5))
            eng.run_until_complete()
            outs.append(s.output_tokens)
        return eng, s, outs

    def test_greedy_parity_vs_fp_baseline_knob_on_and_off(self):
        # Baseline: pool big enough that nothing ever spills.
        _, _, ref = self._run(total_pages=64)
        # Tier on, full-width spills (knob off): bit-identical mechanism.
        _, s_fp, fp = self._run(total_pages=12, host_pages=32)
        # Tier on, int8 spills: the quantized round trip through host DRAM
        # must still produce the same greedy tokens.
        eng, s_q, qt = self._run(total_pages=12, host_pages=32, kv_quant="int8")
        assert fp == ref and qt == ref
        assert s_fp.num_cached_prompt > 0 and s_q.num_cached_prompt > 0
        assert eng.block_manager.host_stats["spilled"] > 0
        assert eng.block_manager.host_stats["restored"] > 0

    def test_quantized_host_pool_halves_slot_bytes(self):
        fp = _engine(total_pages=12, host_pages=8)
        q8 = _engine(total_pages=12, host_pages=8, kv_quant="int8")
        assert q8._host_k.dtype == np.int8
        # int8 payload + f32 per-(layer, head) scales is well under half
        # the bf16/fp32 slot bytes for any realistic head_dim.
        fp_bytes = fp._host_k.nbytes
        q_bytes = q8._host_k.nbytes + q8._host_k_scale.nbytes
        assert q_bytes <= fp_bytes // 2 + q8._host_k_scale.nbytes


class TestQuantizedTransferWire:
    def _warm_engine(self, prompt, **kw):
        eng = _engine(**kw)
        eng.add_request(prompt, SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        return eng

    def test_legacy_response_bytes_unchanged_when_off(self):
        import msgpack

        prompt = _prompt(80, 24)
        eng = self._warm_engine(prompt)
        hashes = eng.block_manager.token_db.prefix_hashes(prompt)
        blocks = eng.export_kv_blocks(hashes)
        assert blocks and all(b.quant is None for b in blocks)
        legacy = msgpack.packb(
            [
                "Blocks",
                True,
                [
                    [
                        b.block_hash,
                        b.parent_block_hash,
                        list(b.token_ids),
                        b.block_size,
                        b.dtype,
                        list(b.shape),
                        b.k_data,
                        b.v_data,
                    ]
                    for b in blocks
                ],
            ],
            use_bin_type=True,
        )
        assert protocol.encode_response(blocks, True) == legacy

    def test_quant_triple_rides_the_wire(self):
        prompt = _prompt(81, 24)
        eng = self._warm_engine(prompt, kv_quant="int8")
        hashes = eng.block_manager.token_db.prefix_hashes(prompt)
        blocks = eng.export_kv_blocks(hashes)
        assert blocks and all(b.quant == "int8" for b in blocks)
        # int8 payload: one byte per element of the logical page shape.
        assert len(blocks[0].k_data) == int(np.prod(blocks[0].shape))
        assert len(blocks[0].k_scale) == (
            int(np.prod(quant.kv_scale_shape(tuple(blocks[0].shape)))) * 4
        )
        dec, complete, err = protocol.decode_response(
            protocol.encode_response(blocks, True)
        )
        assert err is None and complete
        assert [(b.block_hash, b.quant, b.k_scale) for b in dec] == [
            (b.block_hash, b.quant, b.k_scale) for b in blocks
        ]

    def test_quantized_import_matches_cold_prefill(self):
        prompt = _prompt(82, 24)
        src = self._warm_engine(prompt, kv_quant="int8")
        hashes = src.block_manager.token_db.prefix_hashes(prompt)
        wire = protocol.decode_response(
            protocol.encode_response(src.export_kv_blocks(hashes), True)
        )[0]
        # Import into an UNQUANTIZED engine: dequantized before the pool.
        tgt = _engine()
        assert tgt.import_kv_blocks(wire) == len(wire)
        s_warm = tgt.add_request(prompt, SamplingParams(max_new_tokens=4))
        tgt.run_until_complete()
        cold = _engine()
        s_cold = cold.add_request(prompt, SamplingParams(max_new_tokens=4))
        cold.run_until_complete()
        assert s_warm.output_tokens == s_cold.output_tokens
        assert s_warm.num_cached_prompt > 0

    def test_tampered_tokens_rejected(self):
        prompt = _prompt(83, 24)
        src = self._warm_engine(prompt, kv_quant="int8")
        hashes = src.block_manager.token_db.prefix_hashes(prompt)
        blocks = src.export_kv_blocks(hashes)
        blocks[0].token_ids = list(blocks[0].token_ids)
        blocks[0].token_ids[0] ^= 1
        tgt = _engine()
        assert tgt.import_kv_blocks(blocks) == 0
        assert tgt.transfer_stats["import_rejected"] == 1

    def test_truncated_scale_rejected_as_geometry(self):
        prompt = _prompt(84, 24)
        src = self._warm_engine(prompt, kv_quant="int8")
        hashes = src.block_manager.token_db.prefix_hashes(prompt)
        blocks = src.export_kv_blocks(hashes)
        blocks[0].k_scale = blocks[0].k_scale[:-4]
        tgt = _engine()
        assert tgt.import_kv_blocks(blocks) == 0
        assert tgt.transfer_stats["import_rejected"] == 1

    def test_host_tier_sourced_export_is_importable(self):
        # Spill the first prompt's pages to the (int8) host tier, then
        # export its chain: blocks served FROM host DRAM must import and
        # reproduce the cold output like HBM-sourced ones.
        prompts = [_prompt(85 + i, 16) for i in range(3)]
        src = _engine(total_pages=12, host_pages=32, kv_quant="int8")
        for p in prompts:
            src.add_request(p, SamplingParams(max_new_tokens=4))
            src.run_until_complete()
        hashes = src.block_manager.token_db.prefix_hashes(prompts[0])
        chain = src.block_manager.lookup_chain(hashes)
        assert any(tier == "host_dram" for _, _, tier, _ in chain)
        blocks = src.export_kv_blocks(hashes)
        assert blocks
        tgt = _engine()
        assert tgt.import_kv_blocks(blocks) == len(blocks)
        s_warm = tgt.add_request(prompts[0], SamplingParams(max_new_tokens=4))
        tgt.run_until_complete()
        cold = _engine()
        s_cold = cold.add_request(prompts[0], SamplingParams(max_new_tokens=4))
        cold.run_until_complete()
        assert s_warm.output_tokens == s_cold.output_tokens


class TestHostPrefetch:
    def _workload(self, eng):
        """Thrash-then-repeat: fill past the HBM pool so early prompts
        spill, then repeat them — the repeats are host-tier hits."""
        prompts = [_prompt(90 + i, 16) for i in range(4)]
        outs = []
        for p in prompts + prompts[:2]:
            s = eng.add_request(p, SamplingParams(max_new_tokens=5))
            eng.run_until_complete()
            outs.append(s.output_tokens)
        return outs

    def test_prefetch_equivalent_to_blocking_allocate(self):
        ref = self._workload(_engine(total_pages=64))
        blocking = self._workload(
            _engine(total_pages=12, host_pages=32, kv_quant="int8")
        )
        eng = _engine(
            total_pages=12, host_pages=32, kv_quant="int8", host_prefetch=True
        )
        prefetched = self._workload(eng)
        assert blocking == ref and prefetched == ref
        assert eng.host_prefetch_stats["pages"] > 0
        assert eng.block_manager.host_stats["prefetched"] > 0
        # Every prefetched page is also counted as restored (same mover).
        hs = eng.block_manager.host_stats
        assert hs["restored"] >= hs["prefetched"]

    def test_prefetch_respects_cost_model_decline(self):
        eng = _engine(
            total_pages=12,
            host_pages=32,
            host_prefetch=True,
            host_tier_policy="auto",
        )
        prompts = [_prompt(95 + i, 16) for i in range(3)]
        for p in prompts:
            # Pin the EMAs so restoring always loses to recompute: the
            # prefetch stage must decline exactly like blocking allocate.
            eng._prefill_rate = 1e9
            eng._restore_rate = 1e-3
            eng.add_request(p, SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        eng._prefill_rate = 1e9
        eng._restore_rate = 1e-3
        s = eng.add_request(prompts[0], SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        assert eng.host_prefetch_stats["pages"] == 0
        assert s.num_cached_prompt == 0  # declined: honest recompute

    def test_prefetch_hash_memo_survives_waiting(self):
        eng = _engine(total_pages=32, host_pages=8, host_prefetch=True)
        seq = eng.add_request(_prompt(99, 16), SamplingParams(max_new_tokens=2))
        eng.step()
        # Memo either unset (no host pages yet: stage short-circuits) or
        # the exact chain allocate computes.
        if seq.prefetch_hashes is not None:
            assert seq.prefetch_hashes == (
                eng.block_manager.token_db.prefix_hashes(seq.prompt_tokens)
            )


class TestHostTierIndexConvergence:
    """The scorer's tier-aware view must match engine ground truth across
    spills — pinned through the real event wire, with delayed delivery."""

    def _plane(self):
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            )
        )
        pool = KVEventsPool(
            indexer.kv_block_index, KVEventsPoolConfig(concurrency=2)
        )
        pool.start()
        return indexer, pool

    def _pod(self, pool, pod_id, **engine_kw):
        link = ChaosLink(pool, pod_id, MODEL)
        server = PodServer(
            PodServerConfig(
                model_name=MODEL,
                pod_identifier=pod_id,
                publish_events=False,
                engine=_engine_config(**engine_kw),
            ),
            publisher=link,
        )
        server.start()
        return server, link

    def test_spill_stored_host_dram_and_index_converges(self):
        indexer, pool = self._plane()
        server, link = self._pod(
            pool, "tier-pod-0", total_pages=12, host_pages=32, kv_quant="int8"
        )
        try:
            for i in range(3):
                server.generate(
                    _prompt(100 + i, 16),
                    SamplingParams(max_new_tokens=3),
                    timeout=120,
                )
            assert pool.drain(timeout=10)
            digest = server.engine.block_manager.block_digest()
            assert digest["host_dram"]  # spills actually happened
            # Index view == engine truth over every hash the link carried:
            # without the BlockStored(host_dram) on spill, spilled blocks
            # would vanish from the index while the engine still holds
            # them — exactly the divergence this pins.
            truth = engine_truth(server)
            view = index_view_of_pod(
                indexer.kv_block_index, MODEL, link.seen_hashes, "tier-pod-0"
            )
            assert view == truth
            # And the tier is recorded, not just membership.
            h = int(digest["host_dram"][0])
            entries = indexer.kv_block_index._data.get(Key(MODEL, h)).cache.keys()
            tiers = {e.device_tier for e in entries}
            assert tiers == {DeviceTier.HOST_DRAM}
        finally:
            server.shutdown()
            pool.shutdown()

    def test_prefetch_equivalence_under_delayed_events(self):
        # The chaos delay link holds the event stream while requests flow:
        # prefetch-on and prefetch-off pods must produce identical outputs
        # regardless, and after release both converge to ground truth.
        outs = {}
        for flag in (False, True):
            indexer, pool = self._plane()
            server, link = self._pod(
                pool,
                f"tier-pod-{int(flag)}",
                total_pages=12,
                host_pages=32,
                kv_quant="int8",
                host_prefetch=flag,
            )
            try:
                link.delay_next(1000)  # hold everything
                prompts = [_prompt(110 + i, 16) for i in range(3)]
                res = []
                for p in prompts + [prompts[0]]:
                    s = server.generate(
                        p, SamplingParams(max_new_tokens=3), timeout=120
                    )
                    res.append(s.output_tokens)
                outs[flag] = res
                link.release_held()
                assert pool.drain(timeout=10)
                truth = engine_truth(server)
                view = index_view_of_pod(
                    indexer.kv_block_index,
                    MODEL,
                    link.seen_hashes,
                    server.config.pod_identifier,
                )
                assert view == truth
            finally:
                server.shutdown()
                pool.shutdown()
        assert outs[False] == outs[True]


class TestHostObservability:
    def test_host_metric_names_and_types(self):
        pytest.importorskip("prometheus_client")
        m = _ServingMetrics(obs=True)
        text = m.exposition().decode()
        types = {}
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                _, _, name, typ = line.split(" ")
                types[name] = typ
        assert types.get("kvcache_host_pages") == "gauge"
        assert types.get("kvcache_host_hits_total") == "counter"
        assert types.get("kvcache_host_prefetch_seconds") == "histogram"
        # And the families stay off the default exposition surface.
        off = _ServingMetrics(obs=False).exposition().decode()
        assert "kvcache_host_" not in off

    def test_sync_host_stats_splits_paths(self):
        pytest.importorskip("prometheus_client")
        m = _ServingMetrics(obs=True)
        m.sync_host_stats({"restored": 5, "prefetched": 3}, host_cached=7)
        m.sync_host_stats({"restored": 5, "prefetched": 3}, host_cached=7)
        text = m.exposition().decode()
        assert 'kvcache_host_hits_total{path="prefetch"} 3.0' in text
        assert 'kvcache_host_hits_total{path="allocate"} 2.0' in text
        assert "kvcache_host_pages 7.0" in text

    def _run_app(self, server, scenario):
        server.start()

        async def runner():
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                await scenario(client)
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            server.shutdown()

    def test_stats_host_block_gated_on_tier_knob(self):
        async def with_tier(c):
            resp = await c.get("/stats")
            stats = await resp.json()
            assert stats["host"]["host_pages"] == 8
            assert stats["host"]["kv_quant"] == "int8"
            assert "prefetch" in stats["host"]

        async def without_tier(c):
            resp = await c.get("/stats")
            assert "host" not in await resp.json()

        self._run_app(
            PodServer(
                PodServerConfig(
                    model_name=MODEL,
                    pod_identifier="host-stats-pod",
                    publish_events=False,
                    engine=_engine_config(host_pages=8, kv_quant="int8"),
                )
            ),
            with_tier,
        )
        self._run_app(
            PodServer(
                PodServerConfig(
                    model_name=MODEL,
                    pod_identifier="host-stats-pod-2",
                    publish_events=False,
                    engine=_engine_config(),
                )
            ),
            without_tier,
        )

    def test_bringback_span_recorded(self):
        server = PodServer(
            PodServerConfig(
                model_name=MODEL,
                pod_identifier="span-pod",
                publish_events=False,
                obs_tracing=True,
                engine=_engine_config(
                    total_pages=12, host_pages=32, host_prefetch=True
                ),
            )
        )
        server.start()
        try:
            prompts = [_prompt(120 + i, 16) for i in range(3)]
            for p in prompts + [prompts[0]]:
                server.generate(p, SamplingParams(max_new_tokens=3), timeout=120)
            spans = [
                s
                for trace in server.tracer.traces(limit=1000)
                for s in trace["spans"]
                if s["name"] == "pod.host_bringback"
            ]
            assert spans, "prefetch ran but no bringback span recorded"
            assert spans[0]["attrs"]["pages"] > 0
        finally:
            server.shutdown()
