"""kvlint static-analysis + locktrace runtime-harness tests.

Per-checker fixture snippets that MUST flag and MUST pass, suppression
semantics, the committed-tree gate (the whole package lints clean — the
same invariant CI enforces), and the locktrace regression suite including
a synthetic ABBA lock-order inversion the harness must detect.
"""

from __future__ import annotations

import textwrap
import threading
from pathlib import Path

import pytest

from llm_d_kv_cache_manager_tpu.utils import locktrace
from tools.kvlint.core import REPO_ROOT, lint_paths


def _mini_repo(tmp_path: Path, **files: str) -> Path:
    """Lay out a throwaway repo root with the given rel-path -> source."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    return tmp_path


def _lint(root: Path, rel: str, rule: str):
    return lint_paths([str(root / rel)], rules=[rule], repo_root=root)


# ---------------------------------------------------------------------------
# monotonic-time
# ---------------------------------------------------------------------------


class TestMonotonicTime:
    def test_flags_wall_clock_deadline(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                "pkg/mod.py": """
                import time
                def wait(timeout):
                    deadline = time.time() + timeout
                    return deadline
                """
            },
        )
        findings = _lint(root, "pkg/mod.py", "monotonic-time")
        assert len(findings) == 1
        assert "time.monotonic" in findings[0].message

    def test_monotonic_passes(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                "pkg/mod.py": """
                import time
                def wait(timeout):
                    return time.monotonic() + timeout
                """
            },
        )
        assert _lint(root, "pkg/mod.py", "monotonic-time") == []

    def test_line_suppression(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                "pkg/mod.py": """
                import time
                def stamp():
                    # wall clock crosses the wire here
                    return time.time()  # kvlint: disable=monotonic-time
                """
            },
        )
        assert _lint(root, "pkg/mod.py", "monotonic-time") == []

    def test_file_suppression_requires_explicit_form(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                "pkg/mod.py": """
                # this module is all wire timestamps
                # kvlint: disable-file=monotonic-time
                import time
                def a():
                    return time.time()
                def b():
                    return time.time()
                """
            },
        )
        assert _lint(root, "pkg/mod.py", "monotonic-time") == []

    def test_standalone_comment_covers_next_line_only(self, tmp_path):
        # The flake8 noqa-above-the-line habit must not silently become a
        # file-wide suppression: only the next line is covered.
        root = _mini_repo(
            tmp_path,
            **{
                "pkg/mod.py": """
                import time
                def a():
                    # wall clock crosses the wire  # kvlint: disable=monotonic-time
                    return time.time()
                def b():
                    return time.time()
                """
            },
        )
        findings = _lint(root, "pkg/mod.py", "monotonic-time")
        assert len(findings) == 1
        assert findings[0].line == 7  # only b()'s call still flagged

    def test_suppressing_one_rule_keeps_others(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                "pkg/mod.py": """
                import time
                def stamp():
                    return time.time()  # kvlint: disable=lock-discipline
                """
            },
        )
        assert len(_lint(root, "pkg/mod.py", "monotonic-time")) == 1


# ---------------------------------------------------------------------------
# knob-default
# ---------------------------------------------------------------------------

_ALLOWLIST = "tools/kvlint/knob_allowlist.txt"


class TestKnobDefault:
    def test_flags_on_by_default_config_field(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "",
                "pkg/cfg.py": """
                class FooConfig:
                    fancy_mode: bool = True
                    safe_mode: bool = False
                """,
            },
        )
        findings = _lint(root, "pkg/cfg.py", "knob-default")
        assert len(findings) == 1
        assert "FooConfig.fancy_mode" in findings[0].message

    def test_allowlist_entry_passes(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "FooConfig.fancy_mode  # sizing, reviewed\n",
                "pkg/cfg.py": """
                class FooConfig:
                    fancy_mode: bool = True
                """,
            },
        )
        assert _lint(root, "pkg/cfg.py", "knob-default") == []

    def test_off_values_pass(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "",
                "pkg/cfg.py": """
                from typing import Optional
                class FooConfig:
                    a: int = 0
                    b: float = 0.0
                    c: Optional[str] = None
                    d: bool = False
                    e: str = ""
                    f: str = "off"
                    g: str = "auto"
                """,
            },
        )
        assert _lint(root, "pkg/cfg.py", "knob-default") == []

    def test_field_default_literal_checked(self, tmp_path):
        # field(default=True) is the same knob as `= True` — must not slip
        # through the Constant-only fast path.
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "",
                "pkg/cfg.py": """
                from dataclasses import dataclass, field
                @dataclass
                class FooConfig:
                    sneaky_on: bool = field(default=True)
                    composite: list = field(default_factory=list)
                """,
            },
        )
        findings = _lint(root, "pkg/cfg.py", "knob-default")
        assert len(findings) == 1
        assert "FooConfig.sneaky_on" in findings[0].message

    def test_mistyped_target_is_usage_error(self, tmp_path):
        with pytest.raises(SystemExit):
            lint_paths([str(tmp_path / "no_such_dir")], repo_root=tmp_path)

    def test_flags_env_literal_default(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "",
                "pkg/env.py": """
                import os
                FANCY = os.environ.get("FANCY_MODE", "1")
                PAGES = int(os.environ.get("PAGES", 0))
                """,
            },
        )
        findings = _lint(root, "pkg/env.py", "knob-default")
        assert len(findings) == 1
        assert "env:FANCY_MODE" in findings[0].message

    def test_env_bool_helper_on_default_flagged(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "",
                "pkg/env.py": """
                def _env_bool(name, default):
                    import os
                    return os.environ.get(name, default) not in ("0", "")
                PUBLISH = _env_bool("PUBLISH_STUFF", "1")
                QUIET = _env_bool("QUIET_STUFF", "0")
                """,
            },
        )
        findings = _lint(root, "pkg/env.py", "knob-default")
        assert len(findings) == 1
        assert "env:PUBLISH_STUFF" in findings[0].message

    def test_non_literal_default_defers_to_config(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _ALLOWLIST: "",
                "pkg/env.py": """
                import os
                def load(cfg):
                    cfg.depth = int(os.environ.get("DEPTH", cfg.depth))
                """,
            },
        )
        assert _lint(root, "pkg/env.py", "knob-default") == []


# ---------------------------------------------------------------------------
# wire-append-only
# ---------------------------------------------------------------------------

_MANIFEST = "tools/kvlint/wire_manifest.json"
_WIRE_MOD = "kvcache/transfer/protocol.py"


def _wire_repo(tmp_path: Path, body: str, manifest: str) -> Path:
    return _mini_repo(
        tmp_path, **{_MANIFEST: manifest, _WIRE_MOD: body}
    )


_WIRE_OK = """
import msgpack

def encode_request(name, hashes, extra=None):
    arr = ["Tag", name, hashes]
    if extra is not None:
        arr.append(extra)
    return msgpack.packb(arr)
"""

_WIRE_MANIFEST_OK = """
{"kvcache/transfer/protocol.py":
  {"encode_request": {"arr": ["'Tag'", "name", "hashes", "extra"]}}}
"""


class TestWireAppendOnly:
    def test_matching_manifest_passes(self, tmp_path):
        root = _wire_repo(tmp_path, _WIRE_OK, _WIRE_MANIFEST_OK)
        assert _lint(root, _WIRE_MOD, "wire-append-only") == []

    def test_reorder_flagged(self, tmp_path):
        reordered = _WIRE_OK.replace(
            '["Tag", name, hashes]', '["Tag", hashes, name]'
        )
        root = _wire_repo(tmp_path, reordered, _WIRE_MANIFEST_OK)
        findings = _lint(root, _WIRE_MOD, "wire-append-only")
        assert len(findings) == 1
        assert "reorders" in findings[0].message

    def test_positional_insertion_flagged(self, tmp_path):
        inserted = _WIRE_OK.replace(
            '["Tag", name, hashes]', '["Tag", name, "NEW", hashes]'
        )
        root = _wire_repo(tmp_path, inserted, _WIRE_MANIFEST_OK)
        findings = _lint(root, _WIRE_MOD, "wire-append-only")
        assert len(findings) == 1
        assert "reorders" in findings[0].message

    def test_new_trailing_field_requires_manifest_update(self, tmp_path):
        grown = _WIRE_OK + (
            "\n\ndef encode_request2(name, hashes, extra=None, trace=None):\n"
            "    arr = ['Tag', name, hashes]\n"
            "    if extra is not None:\n"
            "        arr.append(extra)\n"
            "    if trace is not None:\n"
            "        arr.append(trace)\n"
            "    return msgpack.packb(arr)\n"
        )
        manifest = _WIRE_MANIFEST_OK.replace(
            '"encode_request":',
            '"encode_request2": {"arr": ["\'Tag\'", "name", "hashes", '
            '"extra"]}, "encode_request":',
        )
        root = _wire_repo(tmp_path, grown, manifest)
        findings = _lint(root, _WIRE_MOD, "wire-append-only")
        assert len(findings) == 1
        assert "grew trailing" in findings[0].message
        assert "['trace']" in findings[0].message

    def test_unknown_builder_flagged(self, tmp_path):
        root = _wire_repo(
            tmp_path, _WIRE_OK, '{"kvcache/transfer/protocol.py": {}}'
        )
        findings = _lint(root, _WIRE_MOD, "wire-append-only")
        assert len(findings) == 1
        assert "not in" in findings[0].message

    def test_removed_field_flagged(self, tmp_path):
        shrunk = _WIRE_OK.replace('["Tag", name, hashes]', '["Tag", name]')
        # manifest still pins hashes at position 2
        manifest = _WIRE_MANIFEST_OK.replace(', "extra"', "")
        root = _wire_repo(tmp_path, shrunk, manifest)
        findings = _lint(root, _WIRE_MOD, "wire-append-only")
        assert len(findings) == 1

    def test_method_builders_extracted(self, tmp_path):
        body = """
        class Beat:
            def to_tagged_union(self):
                arr = ["Beat", self.n]
                if self.draining:
                    arr.append(True)
                return arr
        """
        manifest = (
            '{"kvcache/transfer/protocol.py": {"Beat.to_tagged_union":'
            ' {"arr": ["\'Beat\'", "self.n", "True"]}}}'
        )
        root = _wire_repo(tmp_path, textwrap.dedent(body), manifest)
        assert _lint(root, _WIRE_MOD, "wire-append-only") == []


# ---------------------------------------------------------------------------
# metric-pin
# ---------------------------------------------------------------------------

_METRIC_MOD = "kvcache/metrics/collector.py"
_DOCS = "docs/observability.md"


class TestMetricPin:
    def test_uncatalogued_name_flagged(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _DOCS: "| `kvcache_known_total` | counter | — | known |\n",
                _METRIC_MOD: 'NAME = "kvcache_mystery_total"\n',
            },
        )
        findings = _lint(root, _METRIC_MOD, "metric-pin")
        assert len(findings) == 1
        assert "kvcache_mystery_total" in findings[0].message

    def test_catalogued_name_passes(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _DOCS: "| `kvcache_known_total` | counter | — | known |\n",
                _METRIC_MOD: 'NAME = "kvcache_known_total"\n',
            },
        )
        assert _lint(root, _METRIC_MOD, "metric-pin") == []

    def test_stale_catalog_row_flagged_in_full_run(self, tmp_path):
        root = _mini_repo(
            tmp_path,
            **{
                _DOCS: (
                    "| `kvcache_known_total` | counter | — | known |\n"
                    "| `kvcache_gone_total` | counter | — | removed |\n"
                ),
                _METRIC_MOD: 'NAME = "kvcache_known_total"\n',
                # the reverse check only runs when every metric module is
                # in scope this invocation
                "server/serve.py": "x = 1\n",
                "llm_d_kv_cache_manager_tpu/obs/__init__.py": "",
            },
        )
        findings = lint_paths(
            [str(root)], rules=["metric-pin"], repo_root=root
        )
        assert len(findings) == 1
        assert "kvcache_gone_total" in findings[0].message


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class TestLockDiscipline:
    def _repo(self, tmp_path, body):
        return _mini_repo(tmp_path, **{"pkg/mod.py": body})

    def test_unguarded_write_flagged(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._views = 0  # guarded_by: _lock
                def bump(self):
                    self._views += 1
            """,
        )
        findings = _lint(root, "pkg/mod.py", "lock-discipline")
        assert len(findings) == 1
        assert "_views" in findings[0].message

    def test_guarded_write_passes(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._views = 0  # guarded_by: _lock
                def bump(self):
                    with self._lock:
                        self._views += 1
            """,
        )
        assert _lint(root, "pkg/mod.py", "lock-discipline") == []

    def test_wrong_lock_flagged(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._other_lock = threading.Lock()
                    self._views = 0  # guarded_by: _lock
                def bump(self):
                    with self._other_lock:
                        self._views += 1
            """,
        )
        assert len(_lint(root, "pkg/mod.py", "lock-discipline")) == 1

    def test_holds_annotation_trusted(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._views = 0  # guarded_by: _lock
                def _bump_locked(self):  # kvlint: holds=_lock
                    self._views += 1
                def bump(self):
                    with self._lock:
                        self._bump_locked()
            """,
        )
        assert _lint(root, "pkg/mod.py", "lock-discipline") == []

    def test_condition_alias(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._work = threading.Condition(self._mu)
                    self._q = []  # guarded_by: _mu|_work
                def put(self, x):
                    with self._work:
                        self._q.append(x)
                def snap(self):
                    with self._mu:
                        return list(self._q)
            """,
        )
        assert _lint(root, "pkg/mod.py", "lock-discipline") == []

    def test_sleep_under_lock_flagged(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading, time
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                def nap(self):
                    with self._lock:
                        time.sleep(1)
            """,
        )
        findings = _lint(root, "pkg/mod.py", "lock-discipline")
        assert len(findings) == 1
        assert "time.sleep" in findings[0].message

    def test_zmq_recv_under_lock_flagged(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.sock = None
                def pull(self):
                    with self._lock:
                        return self.sock.recv_multipart()
            """,
        )
        assert len(_lint(root, "pkg/mod.py", "lock-discipline")) == 1

    def test_jax_dispatch_under_lock_flagged(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            import jax
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                def ship(self, x):
                    with self._lock:
                        return jax.device_put(x)
            """,
        )
        findings = _lint(root, "pkg/mod.py", "lock-discipline")
        assert len(findings) == 1
        assert "dispatch" in findings[0].message

    def test_nested_with_on_held_lock_keeps_outer_hold(self, tmp_path):
        # Re-entering an already-held RLock inside a holds= method must not
        # clear the hold for the code after the inner block.
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._views = 0  # guarded_by: _lock
                def helper(self):  # kvlint: holds=_lock
                    with self._lock:
                        self._views += 1
                    self._views += 1  # still under the caller's hold
            """,
        )
        assert _lint(root, "pkg/mod.py", "lock-discipline") == []

    def test_init_exempt(self, tmp_path):
        root = self._repo(
            tmp_path,
            """
            import threading
            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._views = 0  # guarded_by: _lock
                    self._views = 1
            """,
        )
        assert _lint(root, "pkg/mod.py", "lock-discipline") == []


# ---------------------------------------------------------------------------
# kernel-abi
# ---------------------------------------------------------------------------

_ABI_MANIFEST = "tools/kvlint/kernel_abi.json"
_KERNEL_MOD = "ops/paged_attention.py"

_KERNEL_OK = """
def paged_attention(q, k_pages, v_pages, bt, sl, k_scale=None, fresh_k=None):
    q_blocked = q.reshape(1, 2, 2, 8)
    inputs = [bt, sl, q_blocked, k_pages, v_pages]
    if k_scale is not None:
        inputs.append(k_scale)
    if fresh_k is not None:
        inputs.append(fresh_k.reshape(1, 2, 1, 8))
    grid_spec = PrefetchScalarGridSpec(num_scalar_prefetch=2, grid=(1,))
    return inputs, grid_spec
"""

_ABI_OK = """
{"ops/paged_attention.py":
  {"paged_attention": {
    "num_scalar_prefetch": 2,
    "operands": ["bt", "sl", "q_blocked", "k_pages", "v_pages",
                 "k_scale", "fresh_k"]}}}
"""


def _abi_repo(tmp_path: Path, body: str, manifest: str) -> Path:
    return _mini_repo(
        tmp_path, **{_ABI_MANIFEST: manifest, _KERNEL_MOD: body}
    )


class TestKernelAbi:
    def test_matching_pin_passes(self, tmp_path):
        root = _abi_repo(tmp_path, _KERNEL_OK, _ABI_OK)
        assert _lint(root, _KERNEL_MOD, "kernel-abi") == []

    def test_variant_tail_reorder_flagged(self, tmp_path):
        # Fresh operands appended before the scales: compiles fine, reads
        # scales as fresh K inside the kernel — exactly what the pin is for.
        swapped = _KERNEL_OK.replace(
            """    if k_scale is not None:
        inputs.append(k_scale)
    if fresh_k is not None:
        inputs.append(fresh_k.reshape(1, 2, 1, 8))""",
            """    if fresh_k is not None:
        inputs.append(fresh_k.reshape(1, 2, 1, 8))
    if k_scale is not None:
        inputs.append(k_scale)""",
        )
        root = _abi_repo(tmp_path, swapped, _ABI_OK)
        findings = _lint(root, _KERNEL_MOD, "kernel-abi")
        assert len(findings) == 1
        assert "operand order" in findings[0].message

    def test_seed_list_reorder_flagged(self, tmp_path):
        root = _abi_repo(
            tmp_path,
            _KERNEL_OK.replace(
                "[bt, sl, q_blocked, k_pages, v_pages]",
                "[bt, sl, q_blocked, v_pages, k_pages]",
            ),
            _ABI_OK,
        )
        assert len(_lint(root, _KERNEL_MOD, "kernel-abi")) == 1

    def test_unpinned_new_operand_flagged(self, tmp_path):
        grown = _KERNEL_OK.replace(
            "grid_spec = PrefetchScalarGridSpec",
            "inputs.append(bt)\n    grid_spec = PrefetchScalarGridSpec",
        )
        root = _abi_repo(tmp_path, grown, _ABI_OK)
        findings = _lint(root, _KERNEL_MOD, "kernel-abi")
        assert len(findings) == 1
        assert "update" in findings[0].message

    def test_prefetch_count_change_flagged(self, tmp_path):
        root = _abi_repo(
            tmp_path,
            _KERNEL_OK.replace("num_scalar_prefetch=2", "num_scalar_prefetch=3"),
            _ABI_OK,
        )
        findings = _lint(root, _KERNEL_MOD, "kernel-abi")
        assert len(findings) == 1
        assert "num_scalar_prefetch" in findings[0].message

    def test_pinned_function_removed_flagged(self, tmp_path):
        root = _abi_repo(
            tmp_path,
            _KERNEL_OK.replace("def paged_attention", "def renamed_attention"),
            _ABI_OK,
        )
        findings = _lint(root, _KERNEL_MOD, "kernel-abi")
        assert len(findings) == 1
        assert "no longer exists" in findings[0].message

    def test_committed_manifest_pins_the_real_kernel(self):
        import json

        manifest = json.loads(
            (REPO_ROOT / "tools/kvlint/kernel_abi.json").read_text()
        )
        pin = manifest["llm_d_kv_cache_manager_tpu/ops/paged_attention.py"][
            "paged_attention"
        ]
        # The scalar-prefetch operands lead in BOTH kernel variants, and
        # the quantized scales sit between the pages and the fresh tail.
        assert pin["num_scalar_prefetch"] == 2
        assert pin["operands"][:2] == ["block_tables", "seq_lens"]
        ops = pin["operands"]
        assert ops.index("k_scale") > ops.index("v_pages")
        assert ops.index("v_scale") < ops.index("fresh_k")


# ---------------------------------------------------------------------------
# committed tree stays clean (the CI gate invariant)
# ---------------------------------------------------------------------------


class TestCommittedTree:
    def test_package_lints_clean(self):
        findings = lint_paths(
            [str(REPO_ROOT / "llm_d_kv_cache_manager_tpu")],
            repo_root=REPO_ROOT,
        )
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_wire_manifest_covers_all_builders(self):
        # Both wire modules must have at least their known builders pinned;
        # an empty manifest section would make the rule vacuous.
        import json

        manifest = json.loads(
            (REPO_ROOT / "tools/kvlint/wire_manifest.json").read_text()
        )
        assert set(manifest) == {
            "kvcache/transfer/protocol.py",
            "kvcache/kvevents/events.py",
        }
        assert "encode_request" in manifest["kvcache/transfer/protocol.py"]
        assert (
            "EventBatch.to_payload" in manifest["kvcache/kvevents/events.py"]
        )


# ---------------------------------------------------------------------------
# locktrace runtime harness
# ---------------------------------------------------------------------------


@pytest.fixture
def traced():
    """Activate lock tracing for one test; restore the session's state
    after (a LOCKTRACE=1 run keeps tracing on for the remaining tests)."""
    locktrace.activate()
    try:
        yield
    finally:
        locktrace.reset()
        if not locktrace.enabled():
            locktrace.deactivate()


class TestLockTrace:
    def test_abba_inversion_detected(self, traced):
        """Seeded ABBA regression: two locks taken in opposite orders by
        two threads — no deadlock occurs (the threads run sequentially),
        but the harness must flag the order inversion."""
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def forward():
            with lock_a:
                with lock_b:
                    pass

        def backward():
            with lock_b:
                with lock_a:
                    pass

        for fn in (forward, backward):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        violations = locktrace.violations()
        assert len(violations) == 1
        assert violations[0].kind == "lock-order-cycle"
        assert "ABBA" in violations[0].message
        with pytest.raises(AssertionError):
            locktrace.assert_clean()
        locktrace.reset()
        locktrace.assert_clean()  # consumed: the autouse gate stays green

    def test_consistent_order_is_clean(self, traced):
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def nested():
            with lock_a:
                with lock_b:
                    pass

        for _ in range(2):
            t = threading.Thread(target=nested)
            t.start()
            t.join()
        locktrace.assert_clean()

    def test_rlock_reentrancy_not_a_cycle(self, traced):
        rl = threading.RLock()
        with rl:
            with rl:
                pass
        locktrace.assert_clean()

    def test_same_class_plain_lock_nesting_flagged(self, traced):
        """Two NON-reentrant locks born at the same allocation site (one
        lock class, two instances) nested inside each other: same instance
        would self-deadlock, two instances are an unordered pair — either
        way a violation."""

        def make():
            return threading.Lock()  # one allocation site = one lock class

        a, b = make(), make()
        with a:
            with b:
                pass
        assert [v.kind for v in locktrace.violations()] == [
            "lock-order-cycle"
        ]
        locktrace.reset()

    def test_guarded_attr_unguarded_mutation_detected(self, traced):
        class Obj:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0  # guarded_by: _lock

        obj = Obj()
        locktrace.guard_attrs(obj, obj._lock, "state")
        with obj._lock:
            obj.state = 1  # guarded: fine

        def rogue():
            obj.state = 2  # unguarded cross-thread write

        t = threading.Thread(target=rogue)
        t.start()
        t.join()
        violations = locktrace.violations()
        assert [v.kind for v in violations] == ["unguarded-mutation"]
        assert "state" in violations[0].message
        locktrace.reset()

    def test_guard_is_per_instance_not_per_lock_class(self, traced):
        """Two locks born at the same allocation site must not alias each
        other's holds: holding instance A's lock does not satisfy a guard
        on instance B's state."""

        class Obj:
            def __init__(self):
                self._lock = threading.Lock()  # one site, many instances
                self.state = 0

        a, b = Obj(), Obj()
        locktrace.guard_attrs(b, b._lock, "state")

        def rogue():
            with a._lock:  # the WRONG instance's lock
                b.state = 1

        t = threading.Thread(target=rogue)
        t.start()
        t.join()
        assert [v.kind for v in locktrace.violations()] == [
            "unguarded-mutation"
        ]
        locktrace.reset()

    def test_condition_event_queue_survive_tracing(self, traced):
        # The harness must not break stdlib primitives built on locks.
        import queue

        cond = threading.Condition()
        with cond:
            cond.notify_all()
        ev = threading.Event()
        ev.set()
        assert ev.is_set()
        q: "queue.Queue[int]" = queue.Queue()
        q.put(7)
        assert q.get() == 7
        locktrace.assert_clean()

    def test_index_hammer_under_tracing(self, traced):
        """The PR-3 concurrency hammer shape, run under the harness: the
        in-memory index's two-level locking must produce no order cycles."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
            Key,
            PodEntry,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )

        index = InMemoryIndex()
        errors: list = []

        def worker(tid: int):
            try:
                for i in range(25):
                    key = Key("m", i % 7)
                    pod = f"pod{tid % 3}"
                    op = (tid + i) % 4
                    if op == 0:
                        index.add([key], [PodEntry(pod, None)])
                    elif op == 1:
                        index.lookup([key], set())
                    elif op == 2:
                        index.evict(key, [PodEntry(pod, None)])
                    else:
                        index.evict_pod(pod)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        locktrace.assert_clean()
