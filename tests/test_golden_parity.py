"""Cross-engine golden hash parity against the reference's vLLM-produced vectors.

The reference ships four literal uint64 block hashes for an embedded prompt
(`/root/reference/examples/testdata/data.go:28-33`), minted by vLLM's
``sha256_cbor_64bit`` prefix hashing over the bert-base-uncased tokenization
of `tests/golden/bert_prompt.txt` (block size 256 — every reference consumer
of the fixture overrides the default 16 to 256, `examples/kv_cache_index/
main.go:97`, `examples/kv_events/offline/main.go:49,172` — hash seed "",
special tokens added — `pkg/tokenization/tokenizer.go:110-123`). These are the one
externally-produced truth available for the hash chain: a test against them
fails if our chain ever diverges from vLLM's actual output, not just from
itself.

The token ids require the bert vocab, which this image cannot fetch (zero
egress, no HF cache); `tests/golden/mint_bert_ids.py` mints the fixture on
any networked machine. Tests that need the ids skip loudly when the fixture
is absent; fixture-integrity and contract tests always run.
"""

import hashlib
import json
import pathlib

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"
IDS_FIXTURE = GOLDEN_DIR / "bert_prompt_ids.json"

# /root/reference/examples/testdata/data.go:28-33 — verbatim.
GOLDEN_HASHES = [
    17765219867688349152,
    10822023734066583577,
    15079747349478396262,
    6796279860526008575,
]

# sha256 of the vendored prompt bytes; guards against fixture drift (the
# hashes are only meaningful for this exact byte sequence).
PROMPT_SHA256 = "9ba9de631aba3ed098e227ecea4267cee3f9d29195dc15cff5f754905fa256c9"


def _load_ids():
    if not IDS_FIXTURE.exists():
        pytest.skip(
            "tests/golden/bert_prompt_ids.json absent — this image has no "
            "network/HF cache to tokenize with bert-base-uncased; run "
            "`python tests/golden/mint_bert_ids.py` on a networked machine "
            "to enable the cross-engine assertion"
        )
    data = json.loads(IDS_FIXTURE.read_text())
    prompt = (GOLDEN_DIR / "bert_prompt.txt").read_bytes()
    assert data["prompt_sha256"] == hashlib.sha256(prompt).hexdigest(), (
        "ids fixture was minted for a different prompt"
    )
    assert data["model"] == "bert-base-uncased" and data["add_special_tokens"]
    return data["ids"]


class TestFixtureIntegrity:
    """Runs regardless of the ids fixture."""

    def test_vendored_prompt_matches_reference_bytes(self):
        prompt = (GOLDEN_DIR / "bert_prompt.txt").read_bytes()
        assert hashlib.sha256(prompt).hexdigest() == PROMPT_SHA256
        # the fixture is 3548 bytes of 5-paragraph Lorem Ipsum
        assert len(prompt) == 3548

    def test_golden_hashes_are_uint64(self):
        for h in GOLDEN_HASHES:
            assert 0 <= h < 2**64

    def test_mint_script_compiles(self):
        src = (GOLDEN_DIR / "mint_bert_ids.py").read_text()
        compile(src, "mint_bert_ids.py", "exec")


class TestCrossEngineGolden:
    """The cross-engine assertion proper (needs the minted ids fixture)."""

    def _db(self, use_native: bool) -> ChunkedTokenDatabase:
        # Fixture provenance config: block size 256 (the reference overrides
        # its default 16 everywhere PromptHashes is consumed —
        # examples/kv_cache_index/main.go:97, offline/main.go:49,172),
        # seed "" (token_processor.go:48).
        return ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=256, hash_seed="", use_native=use_native)
        )

    def test_python_chain_matches_vllm_golden(self):
        ids = _load_ids()
        hashes = self._db(use_native=False).prefix_hashes(ids)
        # ~1k-token prompt → exactly 4 complete 256-token blocks.
        assert hashes == GOLDEN_HASHES

    def test_native_chain_matches_vllm_golden(self):
        ids = _load_ids()
        db = self._db(use_native=True)
        if db._native is None:
            pytest.skip("native hashcore unavailable")
        assert db.prefix_hashes(ids) == GOLDEN_HASHES
