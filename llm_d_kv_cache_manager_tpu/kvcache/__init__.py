from . import kvblock  # noqa: F401
from .indexer import KVCacheIndexer, KVCacheIndexerConfig
from .scorer import (
    KVBlockScorer,
    KVBlockScorerConfig,
    LongestPrefixScorer,
    ScoringStrategy,
    new_scorer,
)

__all__ = [
    "kvblock",
    "KVCacheIndexer",
    "KVCacheIndexerConfig",
    "KVBlockScorer",
    "KVBlockScorerConfig",
    "LongestPrefixScorer",
    "ScoringStrategy",
    "new_scorer",
]
