"""Thread-safe LRU containers used across the index and token stores."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Generic, Hashable, Iterator, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class LRUCache(Generic[K, V]):
    """Bounded LRU map. Get/contains refresh recency; eviction drops the
    least-recently-used entry. All operations hold an internal lock."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"LRU capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.RLock()
        self._data: OrderedDict[K, V] = OrderedDict()  # guarded_by: _lock

    def get(self, key: K) -> Optional[V]:
        with self._lock:
            if key not in self._data:
                return None
            self._data.move_to_end(key)
            return self._data[key]

    def put(self, key: K, value: V) -> None:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
            self._data[key] = value
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def get_or_put(self, key: K, value: V) -> tuple[V, bool]:
        """Atomic double-checked insert: returns (current_value, existed)."""
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                return self._data[key], True
            self._data[key] = value
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
            return value, False

    def remove(self, key: K) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def __contains__(self, key: K) -> bool:
        with self._lock:
            return key in self._data

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def keys(self) -> list[K]:
        """Snapshot of keys, least-recently-used first."""
        with self._lock:
            return list(self._data.keys())

    def items(self) -> list[tuple[K, V]]:
        with self._lock:
            return list(self._data.items())

    def __iter__(self) -> Iterator[K]:
        return iter(self.keys())
