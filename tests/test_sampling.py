"""Sampling ops: filtered distributions and speculative verification.

`spec_sample` implements deterministic-draft speculative sampling (accept
draft with prob P(draft); residual sample on rejection) — the invariants
below are what make the emitted stream an exact sample of the target
distribution, so they are pinned as pure-function tests.
"""

import numpy as np
import jax
import jax.numpy as jnp

from llm_d_kv_cache_manager_tpu.ops.sampling import sample_tokens, spec_sample

V = 16


def _logits(rng, b, s):
    return jnp.asarray(rng.standard_normal((b, s, V)) * 3.0, jnp.float32)


class TestSpecSample:
    def test_greedy_lanes_match_argmax_semantics(self):
        rng = np.random.default_rng(0)
        logits = _logits(rng, 2, 4)
        argmax = np.asarray(jnp.argmax(logits, -1))
        drafts = jnp.asarray(argmax.copy())
        drafts = drafts.at[0, 2].set((argmax[0, 2] + 1) % V)  # one mismatch
        accept, replacement, free = spec_sample(
            logits, drafts,
            jnp.zeros((2,)), jnp.zeros((2,), jnp.int32), jnp.ones((2,)),
            jax.random.PRNGKey(0),
        )
        accept = np.asarray(accept)
        assert accept[1].all() and accept[0, [0, 1, 3]].all()
        assert not accept[0, 2]
        np.testing.assert_array_equal(np.asarray(replacement), argmax)
        np.testing.assert_array_equal(np.asarray(free), argmax)

    def test_replacement_never_equals_draft_for_sampled_lanes(self):
        rng = np.random.default_rng(1)
        logits = _logits(rng, 3, 5)
        drafts = jnp.asarray(rng.integers(0, V, (3, 5)), jnp.int32)
        for seed in range(5):
            _, replacement, _ = spec_sample(
                logits, drafts,
                jnp.full((3,), 1.0), jnp.zeros((3,), jnp.int32), jnp.ones((3,)),
                jax.random.PRNGKey(seed),
            )
            assert not np.any(np.asarray(replacement) == np.asarray(drafts))

    def test_topk1_collapses_to_argmax(self):
        # A point-mass distribution: accept iff draft == argmax; free is
        # argmax; so temperature>0 behaves exactly like greedy.
        rng = np.random.default_rng(2)
        logits = _logits(rng, 2, 4)
        argmax = np.asarray(jnp.argmax(logits, -1))
        drafts = jnp.asarray(argmax)
        accept, _, free = spec_sample(
            logits, drafts,
            jnp.full((2,), 0.8), jnp.ones((2,), jnp.int32), jnp.ones((2,)),
            jax.random.PRNGKey(3),
        )
        assert np.asarray(accept).all()
        np.testing.assert_array_equal(np.asarray(free), argmax)

    def test_acceptance_rate_tracks_draft_probability(self):
        # Statistical: with temperature 1 and a known distribution, the
        # measured acceptance over many keys approaches P(draft).
        logits = jnp.log(
            jnp.asarray([[[0.7, 0.2, 0.1] + [1e-9] * (V - 3)]], jnp.float32)
        )
        drafts = jnp.zeros((1, 1), jnp.int32)  # P(draft) = 0.7
        hits = 0
        n = 400
        for seed in range(n):
            accept, _, _ = spec_sample(
                logits, drafts,
                jnp.ones((1,)), jnp.zeros((1,), jnp.int32), jnp.ones((1,)),
                jax.random.PRNGKey(seed),
            )
            hits += int(np.asarray(accept)[0, 0])
        assert 0.6 < hits / n < 0.8  # ~±4 sigma band around 0.7

    def test_free_samples_stay_in_topk_support(self):
        rng = np.random.default_rng(4)
        logits = _logits(rng, 2, 3)
        top2 = np.asarray(jnp.argsort(logits, -1))[:, :, -2:]
        drafts = jnp.zeros((2, 3), jnp.int32)
        for seed in range(5):
            _, _, free = spec_sample(
                logits, drafts,
                jnp.full((2,), 1.0), jnp.full((2,), 2, jnp.int32), jnp.ones((2,)),
                jax.random.PRNGKey(seed),
            )
            f = np.asarray(free)
            for bi in range(2):
                for si in range(3):
                    assert f[bi, si] in top2[bi, si]


class TestSampleTokensStillIntact:
    def test_greedy_and_sampled(self):
        rng = np.random.default_rng(5)
        logits = jnp.asarray(rng.standard_normal((4, V)) * 3, jnp.float32)
        toks = sample_tokens(
            logits,
            jnp.asarray([0.0, 0.0, 1.0, 1.0]),
            jnp.asarray([0, 0, 2, 0], jnp.int32),
            jnp.asarray([1.0, 1.0, 1.0, 0.9]),
            jax.random.PRNGKey(0),
        )
        toks = np.asarray(toks)
        argmax = np.asarray(jnp.argmax(logits, -1))
        assert toks[0] == argmax[0] and toks[1] == argmax[1]
        assert all(0 <= t < V for t in toks)
