"""Flash-prefill kernel block-size sweep (op level, fenced timings).

Times `flash_prefill_paged` directly at serving shapes across
(q_block, key_block) configurations, against the XLA-scan oracle's time.
Timing discipline per the tunnel's quirks: chain outputs into the next
call's query and fence with a device→host fetch.

Run on the chip: ``python benchmarking/bench_flash_prefill_blocks.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    from llm_d_kv_cache_manager_tpu.ops.attention import prefill_with_paged_context
    from llm_d_kv_cache_manager_tpu.ops.flash_prefill import flash_prefill_paged

    on_tpu = jax.default_backend() == "tpu"
    # 1.4B-bench attention geometry; one layer's attention op.
    b, s, n_q, n_kv, d, ps = 4, 2048, 24, 8, 128, 16
    max_ctx_pages = 128  # 2048 tokens of warm context
    reps = 8 if on_tpu else 1

    rng = np.random.default_rng(0)
    total_pages = b * max_ctx_pages + 1
    dtype = jnp.bfloat16
    q = jnp.asarray(rng.standard_normal((b, s, n_q, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, n_kv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, n_kv, d)), dtype)
    k_pages = jnp.asarray(rng.standard_normal((total_pages, ps, n_kv, d)), dtype)
    v_pages = jnp.asarray(rng.standard_normal((total_pages, ps, n_kv, d)), dtype)
    bt = jnp.asarray(
        (rng.permutation(total_pages - 1)[: b * max_ctx_pages] + 1).reshape(
            b, max_ctx_pages
        ),
        jnp.int32,
    )
    cl = jnp.asarray([2048, 2048, 1024, 0], jnp.int32)
    nv = jnp.full((b,), s, jnp.int32)
    positions = cl[:, None] + jnp.arange(s)[None, :]
    valid = jnp.ones((b, s), bool)

    def time_fn(fn):
        y = fn(q)
        np.asarray(y[0, 0, 0, :1])  # compile + fence
        qq = q
        t0 = time.perf_counter()
        for _ in range(reps):
            y = fn(qq)
            # chain: perturb the query with the output (same shape)
            qq = (qq + y.astype(qq.dtype) * 1e-3).astype(qq.dtype)
        np.asarray(y[0, 0, 0, :1])
        return (time.perf_counter() - t0) / reps * 1e3

    # jit the oracle with every array as a traced ARGUMENT (un-jitted it
    # dispatches eagerly op-by-op; closing over the arrays would bake them
    # in as constants and let XLA fold the q-independent gather/concat out
    # of the timed region — asymmetric vs the Pallas path's jit).
    xla_jit = jax.jit(
        lambda qq, k, v, kp, vp, bt, cl, pos, val: prefill_with_paged_context(
            qq, k, v, kp, vp, bt, cl, positions=pos, valid=val
        )
    )
    xla_ms = time_fn(
        lambda qq: xla_jit(qq, k, v, k_pages, v_pages, bt, cl, positions, valid)
    )
    print(json.dumps({"impl": "xla_scan", "ms": round(xla_ms, 2)}), flush=True)

    for qb in (128, 256, 512):
        for kb in (256, 512, 1024):
            try:
                ms = time_fn(
                    lambda qq, qb=qb, kb=kb: flash_prefill_paged(
                        qq, k, v, k_pages, v_pages, bt, cl, nv,
                        q_block=qb, key_block=kb,
                    )
                )
            except Exception as e:  # VMEM overflow etc.
                print(json.dumps({"q_block": qb, "key_block": kb,
                                  "error": type(e).__name__}), flush=True)
                continue
            print(
                json.dumps(
                    {
                        "impl": "pallas",
                        "q_block": qb,
                        "key_block": kb,
                        "ms": round(ms, 2),
                        "speedup_vs_xla": round(xla_ms / ms, 2),
                    }
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
