"""Model numerics tests.

- Cross-check against transformers' torch Llama (random-init, no network):
  the strongest validation of RMSNorm/RoPE/GQA/SwiGLU wiring.
- Prefill↔decode consistency on the paged KV cache: prefilling n tokens
  must give the same next-token logits as prefilling n-1 and decoding one.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from llm_d_kv_cache_manager_tpu.models import (
    TINY_LLAMA,
    LlamaConfig,
    decode_step,
    init_kv_pages,
    init_params,
    prefill,
)

PAGE_SIZE = 4


def _alloc(cfg, batch, max_tokens):
    """Trivial sequential page allocation for tests."""
    pages_per_seq = max_tokens // PAGE_SIZE
    total = batch * pages_per_seq + 1
    k_pages, v_pages = init_kv_pages(cfg, total, PAGE_SIZE)
    block_tables = np.arange(batch * pages_per_seq).reshape(batch, pages_per_seq) + 1
    return k_pages, v_pages, jnp.asarray(block_tables, jnp.int32)


def _prefill_args(block_tables, batch, seq):
    pos = np.tile(np.arange(seq), (batch, 1))
    page_ids = np.take_along_axis(
        np.asarray(block_tables), pos // PAGE_SIZE, axis=1
    )
    slot_ids = pos % PAGE_SIZE
    valid = np.ones((batch, seq), bool)
    return (
        jnp.asarray(pos, jnp.int32),
        jnp.asarray(valid),
        jnp.asarray(page_ids, jnp.int32),
        jnp.asarray(slot_ids, jnp.int32),
    )


def _zero_ctx(batch):
    return jnp.zeros((batch, 1), jnp.int32), jnp.zeros((batch,), jnp.int32)


class TestHFNumericsParity:
    def test_logits_match_transformers(self):
        torch = pytest.importorskip("torch")
        from transformers import LlamaConfig as HFLlamaConfig
        from transformers import LlamaForCausalLM

        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_state_dict,
        )

        hf_cfg = HFLlamaConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            rope_theta=10000.0,
            rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        torch.manual_seed(0)
        hf_model = LlamaForCausalLM(hf_cfg).eval()

        cfg = config_from_hf(hf_cfg)
        cfg = LlamaConfig(**{**cfg.__dict__, "dtype": jnp.float32})
        params = load_hf_state_dict(hf_model.state_dict(), cfg)

        batch, seq = 2, 12
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 128, (batch, seq))

        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()  # [b, s, vocab]

        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits[:, -1], rtol=2e-4, atol=2e-4
        )

    def test_qwen_style_bias_loads(self):
        torch = pytest.importorskip("torch")
        from transformers import Qwen2Config, Qwen2ForCausalLM

        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_state_dict,
        )

        hf_cfg = Qwen2Config(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            rope_theta=10000.0,
            rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        torch.manual_seed(1)
        hf_model = Qwen2ForCausalLM(hf_cfg).eval()
        cfg = config_from_hf(hf_cfg)
        assert cfg.qkv_bias
        cfg = LlamaConfig(**{**cfg.__dict__, "dtype": jnp.float32})
        params = load_hf_state_dict(hf_model.state_dict(), cfg)

        batch, seq = 1, 8
        rng = np.random.default_rng(2)
        tokens = rng.integers(0, 128, (batch, seq))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits[:, -1], rtol=2e-4, atol=2e-4
        )

    def test_gemma_matches_transformers(self):
        """Gemma family: gated-GELU FFN, (1+w) RMSNorm, sqrt(d)-scaled tied
        embeddings, decoupled head_dim — prefill AND decode logits must match
        HF GemmaForCausalLM."""
        torch = pytest.importorskip("torch")
        from transformers import GemmaConfig, GemmaForCausalLM

        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_state_dict,
        )

        hf_cfg = GemmaConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=4,
            head_dim=24,
            rope_theta=10000.0,
            rms_norm_eps=1e-6,
            tie_word_embeddings=True,
            hidden_activation="gelu_pytorch_tanh",
        )
        torch.manual_seed(5)
        hf_model = GemmaForCausalLM(hf_cfg).eval()
        cfg = config_from_hf(hf_cfg)
        assert cfg.norm_offset == 1.0 and cfg.scale_embeddings
        assert cfg.hidden_act == "gelu_tanh" and cfg.tie_word_embeddings
        cfg = LlamaConfig(**{**cfg.__dict__, "dtype": jnp.float32})
        params = load_hf_state_dict(hf_model.state_dict(), cfg)

        batch, seq = 2, 12
        rng = np.random.default_rng(6)
        tokens = rng.integers(0, 128, (batch, seq))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq + PAGE_SIZE)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        logits, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits[:, -1], rtol=2e-4, atol=2e-4
        )

        nxt = rng.integers(0, 128, (batch, 1))
        with torch.no_grad():
            hf_logits2 = hf_model(
                torch.tensor(np.concatenate([tokens, nxt], axis=1))
            ).logits.numpy()
        dec_logits, _, _ = decode_step(
            params, cfg,
            jnp.asarray(nxt[:, 0], jnp.int32),
            jnp.full((batch,), seq, jnp.int32),
            k_pages, v_pages, block_tables,
            jnp.full((batch,), seq + 1, jnp.int32),
            page_size=PAGE_SIZE, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(dec_logits), hf_logits2[:, -1], rtol=2e-4, atol=2e-4
        )

    def test_qwen3_moe_matches_transformers(self):
        """Qwen3-MoE: qk-norm + 128-expert-style routed FFN with decoupled
        expert width and norm_topk_prob gating — prefill logits must match
        HF Qwen3MoeForCausalLM (tiny random model, both gating modes)."""
        torch = pytest.importorskip("torch")
        try:
            from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM
        except ImportError:
            pytest.skip("transformers has no Qwen3Moe")
        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_state_dict,
        )

        for norm_topk in (True, False):
            hf_cfg = Qwen3MoeConfig(
                vocab_size=128,
                hidden_size=64,
                intermediate_size=128,
                moe_intermediate_size=48,
                num_hidden_layers=2,
                num_attention_heads=4,
                num_key_value_heads=2,
                head_dim=24,
                num_experts=4,
                num_experts_per_tok=2,
                norm_topk_prob=norm_topk,
                rope_theta=10000.0,
                rms_norm_eps=1e-6,
                tie_word_embeddings=False,
            )
            torch.manual_seed(7)
            hf_model = Qwen3MoeForCausalLM(hf_cfg).eval()
            cfg = config_from_hf(hf_cfg)
            assert cfg.qk_norm and cfg.n_experts == 4
            assert cfg.moe_inter == 48 and cfg.norm_topk_prob is norm_topk
            cfg = LlamaConfig(**{**cfg.__dict__, "dtype": jnp.float32})
            params = load_hf_state_dict(hf_model.state_dict(), cfg)

            batch, seq = 2, 12
            rng = np.random.default_rng(8)
            tokens = rng.integers(0, 128, (batch, seq))
            with torch.no_grad():
                hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()
            k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
            pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
            logits, _, _ = prefill(
                params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
                k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
            )
            np.testing.assert_allclose(
                np.asarray(logits), hf_logits[:, -1], rtol=3e-4, atol=3e-4
            )

    def test_qwen3_moe_mixed_dense_rejected(self):
        pytest.importorskip("torch")
        try:
            from transformers import Qwen3MoeConfig
        except ImportError:
            pytest.skip("transformers has no Qwen3Moe")
        from llm_d_kv_cache_manager_tpu.models.hf_loader import config_from_hf

        cfg = Qwen3MoeConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
            num_experts=2, mlp_only_layers=[0],
        )
        with pytest.raises(NotImplementedError, match="dense/sparse"):
            config_from_hf(cfg)

    def test_gemma2_rejected_loudly(self):
        """Gemma2/3 layer schemas differ; loading them as Gemma-1 must raise
        instead of silently producing wrong logits."""
        pytest.importorskip("torch")
        try:
            from transformers import Gemma2Config
        except ImportError:
            pytest.skip("transformers has no Gemma2Config")
        from llm_d_kv_cache_manager_tpu.models.hf_loader import config_from_hf

        with pytest.raises(NotImplementedError, match="Gemma2"):
            config_from_hf(Gemma2Config(vocab_size=64, hidden_size=32,
                                        intermediate_size=64,
                                        num_hidden_layers=1,
                                        num_attention_heads=2,
                                        num_key_value_heads=2))

    def test_qwen3_qk_norm_matches_transformers(self):
        torch = pytest.importorskip("torch")
        from transformers import Qwen3Config, Qwen3ForCausalLM

        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_state_dict,
        )

        hf_cfg = Qwen3Config(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim=32,  # decoupled from hidden_size // n_heads, like Qwen3-32B
            rope_theta=10000.0,
            rms_norm_eps=1e-6,
            tie_word_embeddings=False,
        )
        torch.manual_seed(3)
        hf_model = Qwen3ForCausalLM(hf_cfg).eval()
        cfg = config_from_hf(hf_cfg)
        assert cfg.qk_norm and not cfg.qkv_bias and cfg.hd == 32
        cfg = LlamaConfig(**{**cfg.__dict__, "dtype": jnp.float32})
        params = load_hf_state_dict(hf_model.state_dict(), cfg)

        batch, seq = 2, 12
        rng = np.random.default_rng(4)
        tokens = rng.integers(0, 128, (batch, seq))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

        # One spare page per sequence for the decode step below.
        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq + PAGE_SIZE)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        logits, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits[:, -1], rtol=2e-4, atol=2e-4
        )

        # Decode path applies qk-norm identically: next-token logits after a
        # decode step must match HF's logits with one more token appended.
        nxt = rng.integers(0, 128, (batch, 1))
        with torch.no_grad():
            hf_logits2 = hf_model(
                torch.tensor(np.concatenate([tokens, nxt], axis=1))
            ).logits.numpy()
        dec_logits, _, _ = decode_step(
            params, cfg,
            jnp.asarray(nxt[:, 0], jnp.int32),
            jnp.full((batch,), seq, jnp.int32),
            k_pages, v_pages, block_tables,
            jnp.full((batch,), seq + 1, jnp.int32),
            page_size=PAGE_SIZE, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(dec_logits), hf_logits2[:, -1], rtol=2e-4, atol=2e-4
        )


class TestPrefillDecodeConsistency:
    def test_decode_matches_prefill(self):
        cfg = TINY_LLAMA
        params = init_params(jax.random.PRNGKey(0), cfg)
        batch, seq = 2, 12
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, cfg.vocab_size, (batch, seq))

        # Full prefill of all `seq` tokens.
        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        full_logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )

        # Prefill seq-1, then decode token seq-1.
        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        valid = valid.at[:, -1].set(False)
        _, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        dec_logits, _, _ = decode_step(
            params, cfg,
            jnp.asarray(tokens[:, -1], jnp.int32),
            jnp.full((batch,), seq - 1, jnp.int32),
            k_pages, v_pages, block_tables,
            jnp.full((batch,), seq, jnp.int32),
            page_size=PAGE_SIZE, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(dec_logits), np.asarray(full_logits), rtol=2e-4, atol=2e-4
        )

    def test_decode_two_steps(self):
        cfg = TINY_LLAMA
        params = init_params(jax.random.PRNGKey(0), cfg)
        batch, seq = 1, 8
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, cfg.vocab_size, (batch, seq))

        full_k, full_v, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        full_logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            full_k, full_v, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )

        # Prefill first 6, decode tokens 6 and 7.
        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        valid = valid.at[:, 6:].set(False)
        _, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        for step in (6, 7):
            logits, k_pages, v_pages = decode_step(
                params, cfg,
                jnp.asarray(tokens[:, step], jnp.int32),
                jnp.full((batch,), step, jnp.int32),
                k_pages, v_pages, block_tables,
                jnp.full((batch,), step + 1, jnp.int32),
                page_size=PAGE_SIZE, interpret=True,
            )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full_logits), rtol=2e-4, atol=2e-4
        )

    def test_prefix_cached_suffix_prefill_matches_full(self):
        """The prefix-cache compute-skip: prefill tokens[0:8] (request A),
        then prefill only tokens[8:12] with A's pages as context (request B
        sharing the prefix) — logits must match a full 12-token prefill."""
        cfg = TINY_LLAMA
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(6)
        tokens = rng.integers(0, cfg.vocab_size, (1, 12))

        # Oracle: full prefill.
        k_pages, v_pages, bt = _alloc(cfg, 1, 12)
        pos, valid, page_ids, slot_ids = _prefill_args(bt, 1, 12)
        ref_logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(1),
        )

        # Request A: prefill the 8-token shared prefix (2 pages).
        k_pages, v_pages, bt = _alloc(cfg, 1, 12)
        pos8, valid8, page_ids8, slot_ids8 = _prefill_args(bt[:, :2], 1, 8)
        _, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens[:, :8], jnp.int32), pos8, valid8,
            k_pages, v_pages, page_ids8, slot_ids8, *_zero_ctx(1),
        )

        # Request B: suffix-only prefill attending to A's cached pages.
        suffix = jnp.asarray(tokens[:, 8:], jnp.int32)
        pos_s = jnp.arange(8, 12, dtype=jnp.int32)[None, :]
        valid_s = jnp.ones((1, 4), bool)
        page_ids_s = jnp.full((1, 4), int(bt[0, 2]), jnp.int32)
        slot_ids_s = pos_s % PAGE_SIZE
        ctx_bt = bt[:, :2]
        ctx_lens = jnp.asarray([8], jnp.int32)
        logits, _, _ = prefill(
            params, cfg, suffix, pos_s, valid_s,
            k_pages, v_pages, page_ids_s, slot_ids_s, ctx_bt, ctx_lens,
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
        )

    def test_pad_position_value_is_irrelevant(self):
        # Invalid positions are fully masked: whatever position value padding
        # carries (incl. 0, which passes the causal check) must not affect
        # valid tokens' logits.
        cfg = TINY_LLAMA
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(5)
        tokens = rng.integers(0, cfg.vocab_size, (1, 8))

        k_pages, v_pages, bt = _alloc(cfg, 1, 8)
        pos, valid, page_ids, slot_ids = _prefill_args(bt, 1, 8)
        ref_logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )

        padded = np.concatenate([tokens, rng.integers(0, cfg.vocab_size, (1, 4))], axis=1)
        k_pages, v_pages, bt = _alloc(cfg, 1, 12)
        pos12, valid12, page_ids12, slot_ids12 = _prefill_args(bt, 1, 12)
        pos12 = pos12.at[:, 8:].set(0)  # pad positions = 0, the nasty case
        valid12 = valid12.at[:, 8:].set(False)
        pad_logits, _, _ = prefill(
            params, cfg, jnp.asarray(padded, jnp.int32), pos12, valid12,
            k_pages, v_pages, page_ids12, slot_ids12, *_zero_ctx(1),
        )
        np.testing.assert_allclose(
            np.asarray(pad_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
        )

    def test_llama31_rope_scaling_config_is_jittable(self):
        from llm_d_kv_cache_manager_tpu.ops.rope import RopeScalingConfig

        cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "rope_scaling": RopeScalingConfig()})
        params = init_params(jax.random.PRNGKey(0), cfg)
        k_pages, v_pages, bt = _alloc(cfg, 1, 8)
        pos, valid, page_ids, slot_ids = _prefill_args(bt, 1, 8)
        logits, _, _ = prefill(
            params, cfg, jnp.zeros((1, 8), jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        assert logits.shape == (1, cfg.vocab_size)

    def test_padded_prefill_matches_unpadded(self):
        cfg = TINY_LLAMA
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(4)
        tokens = rng.integers(0, cfg.vocab_size, (1, 8))

        k_pages, v_pages, bt = _alloc(cfg, 1, 8)
        pos, valid, page_ids, slot_ids = _prefill_args(bt, 1, 8)
        ref_logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )

        # Same 8 tokens followed by 4 padding slots marked invalid.
        padded = np.concatenate([tokens, np.zeros((1, 4), int)], axis=1)
        k_pages, v_pages, bt = _alloc(cfg, 1, 12)
        pos, valid, page_ids, slot_ids = _prefill_args(bt, 1, 12)
        valid = valid.at[:, 8:].set(False)
        pad_logits, _, _ = prefill(
            params, cfg, jnp.asarray(padded, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        np.testing.assert_allclose(
            np.asarray(pad_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
        )


class TestMixtralMoE:
    def test_logits_match_transformers_mixtral(self):
        torch = pytest.importorskip("torch")
        from transformers import MixtralConfig, MixtralForCausalLM

        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_state_dict,
        )

        hf_cfg = MixtralConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=96,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            num_local_experts=4,
            num_experts_per_tok=2,
            rope_theta=10000.0,
            rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        torch.manual_seed(7)
        hf_model = MixtralForCausalLM(hf_cfg).eval()

        cfg = config_from_hf(hf_cfg)
        assert cfg.n_experts == 4 and cfg.n_experts_per_tok == 2
        cfg = LlamaConfig(**{**cfg.__dict__, "dtype": jnp.float32})
        params = load_hf_state_dict(hf_model.state_dict(), cfg)

        batch, seq = 2, 12
        rng = np.random.default_rng(8)
        tokens = rng.integers(0, 128, (batch, seq))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

        # One spare page per sequence for the decode step below.
        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq + PAGE_SIZE)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        logits, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits[:, -1], rtol=2e-4, atol=2e-4
        )

        # Decode path routes through the same MoE: one more token must match
        # HF on the extended sequence.
        nxt = rng.integers(0, 128, (batch, 1))
        with torch.no_grad():
            hf_logits2 = hf_model(
                torch.tensor(np.concatenate([tokens, nxt], axis=1))
            ).logits.numpy()
        dec_logits, _, _ = decode_step(
            params, cfg,
            jnp.asarray(nxt[:, 0], jnp.int32),
            jnp.full((batch,), seq, jnp.int32),
            k_pages, v_pages, block_tables,
            jnp.full((batch,), seq + 1, jnp.int32),
            page_size=PAGE_SIZE, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(dec_logits), hf_logits2[:, -1], rtol=2e-4, atol=2e-4
        )

    def test_moe_decode_matches_prefill(self):
        from llm_d_kv_cache_manager_tpu.models import TINY_MOE

        cfg = TINY_MOE
        params = init_params(jax.random.PRNGKey(0), cfg)
        batch, seq = 2, 12
        rng = np.random.default_rng(9)
        tokens = rng.integers(0, cfg.vocab_size, (batch, seq))

        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        full_logits, _, _ = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )

        k_pages, v_pages, block_tables = _alloc(cfg, batch, seq)
        pos, valid, page_ids, slot_ids = _prefill_args(block_tables, batch, seq)
        valid = valid.at[:, -1].set(False)
        _, k_pages, v_pages = prefill(
            params, cfg, jnp.asarray(tokens, jnp.int32), pos, valid,
            k_pages, v_pages, page_ids, slot_ids, *_zero_ctx(page_ids.shape[0]),
        )
        dec_logits, _, _ = decode_step(
            params, cfg,
            jnp.asarray(tokens[:, -1], jnp.int32),
            jnp.full((batch,), seq - 1, jnp.int32),
            k_pages, v_pages, block_tables,
            jnp.full((batch,), seq, jnp.int32),
            page_size=PAGE_SIZE, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(dec_logits), np.asarray(full_logits), rtol=2e-4, atol=2e-4
        )

    def test_top_k_routing_is_sparse(self):
        """Zeroing a non-selected expert's weights must not change outputs:
        proves only the top-k experts contribute, despite the masked-dense
        compute."""
        from llm_d_kv_cache_manager_tpu.models import TINY_MOE
        from llm_d_kv_cache_manager_tpu.models.llama import _moe_mlp

        cfg = TINY_MOE
        params = init_params(jax.random.PRNGKey(3), cfg)
        layer = params["layers"][0]
        rng = np.random.default_rng(10)
        x = jnp.asarray(rng.standard_normal((1, 5, cfg.hidden_size)), jnp.float32)

        router_logits = np.asarray(x @ layer["router"])  # [1, 5, E]
        ref = np.asarray(_moe_mlp(layer, cfg, x))

        # For each expert, zero its weights; if it was never in any token's
        # top-2, the output must be identical.
        topk = np.argsort(-router_logits, axis=-1)[..., : cfg.n_experts_per_tok]
        for e in range(cfg.n_experts):
            mutated = dict(layer)
            for w in ("w_gate", "w_up", "w_down"):
                mutated[w] = layer[w].at[e].set(0.0)
            got = np.asarray(_moe_mlp(mutated, cfg, x))
            if e not in topk:
                np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
            else:
                assert not np.allclose(got, ref)


class TestRoutedDispatch:
    """Grouped top-k gather dispatch vs the masked-dense oracle."""

    @pytest.mark.parametrize("tiny", ["TINY_MOE", "TINY_QWEN3_MOE"])
    @pytest.mark.parametrize("shape", [(1, 1), (2, 1), (3, 17)])
    def test_routed_matches_dense_oracle(self, tiny, shape):
        import dataclasses

        from llm_d_kv_cache_manager_tpu.models import llama
        from llm_d_kv_cache_manager_tpu.models.llama import _moe_mlp

        cfg = getattr(llama, tiny)
        assert cfg.moe_dispatch == "routed"  # the default under test
        dense_cfg = dataclasses.replace(cfg, moe_dispatch="dense")
        params = init_params(jax.random.PRNGKey(5), cfg)
        layer = params["layers"][0]
        rng = np.random.default_rng(11)
        x = jnp.asarray(rng.standard_normal((*shape, cfg.hidden_size)), jnp.float32)
        routed = np.asarray(_moe_mlp(layer, cfg, x))
        dense = np.asarray(_moe_mlp(layer, dense_cfg, x))
        np.testing.assert_allclose(routed, dense, rtol=1e-5, atol=1e-5)

    def test_unknown_dispatch_rejected(self):
        import dataclasses

        from llm_d_kv_cache_manager_tpu.models import TINY_MOE
        from llm_d_kv_cache_manager_tpu.models.llama import _moe_mlp

        cfg = dataclasses.replace(TINY_MOE, moe_dispatch="nope")
        params = init_params(jax.random.PRNGKey(0), cfg)
        x = jnp.zeros((1, 2, cfg.hidden_size), jnp.float32)
        with pytest.raises(ValueError, match="moe_dispatch"):
            _moe_mlp(params["layers"][0], cfg, x)

    def _a3b_shaped(self):
        """Qwen3-30B-A3B expert geometry (128 experts, top-8) at reduced
        hidden width — the E/k ratio is what's under test."""
        import dataclasses

        from llm_d_kv_cache_manager_tpu.models import llama

        return dataclasses.replace(
            llama.TINY_QWEN3_MOE,
            hidden_size=128,
            n_experts=128,
            n_experts_per_tok=8,
            moe_intermediate_size=64,
        )

    def test_routed_never_materializes_all_expert_activations(self):
        """Structural complexity check (backend-independent): the dense
        oracle materializes an [E, n, f] activation; the routed dispatch's
        largest intermediate must be [n*k, f] — E/k times smaller. XLA's
        TPU cost model confirms the FLOPs ratio (~15x at 128/8; see
        benchmarking/bench_moe.py, which asserts it on the real chip —
        the CPU lowering of ragged_dot is loop-dense so the ratio is not
        measurable from a CPU compile)."""
        import dataclasses

        cfg = self._a3b_shaped()
        from llm_d_kv_cache_manager_tpu.models.llama import _moe_mlp

        params = init_params(jax.random.PRNGKey(0), cfg)
        layer = params["layers"][0]
        n, k, f = 64, cfg.n_experts_per_tok, cfg.moe_inter
        x = jnp.zeros((1, n, cfg.hidden_size), jnp.float32)

        jaxpr = jax.make_jaxpr(lambda p, v: _moe_mlp(p, cfg, v))(layer, x)
        prims = {e.primitive.name for e in jaxpr.eqns}
        assert "ragged_dot" in prims or "ragged_dot_general" in prims, prims
        dense_inter = cfg.n_experts * n * f
        biggest = max(
            int(np.prod(v.aval.shape))
            for e in jaxpr.eqns
            for v in e.outvars
            if v.aval.shape
        )
        # The routed design goal: nothing bigger than the [n*k, max(d, f)]
        # gather/activation ever materializes (E/k times below dense scale;
        # the bound is inclusive because the gather is exactly that size).
        routed_scale = n * k * max(cfg.hidden_size, f)
        assert biggest <= routed_scale, (
            f"routed path materializes a {biggest}-element intermediate; "
            f"design bound is {routed_scale}, dense-oracle scale is {dense_inter}"
        )
        assert dense_inter / routed_scale >= cfg.n_experts / k / 2, (
            "reduced config no longer separates routed from dense scale"
        )

        dense_jaxpr = jax.make_jaxpr(
            lambda p, v: _moe_mlp(p, dataclasses.replace(cfg, moe_dispatch="dense"), v)
        )(layer, x)
        dense_biggest = max(
            int(np.prod(v.aval.shape))
            for e in dense_jaxpr.eqns
            for v in e.outvars
            if v.aval.shape
        )
        assert dense_biggest >= dense_inter  # the oracle really is dense

    @pytest.mark.skipif(
        jax.default_backend() != "tpu", reason="needs the TPU ragged_dot kernel"
    )
    def test_routed_flops_scale_with_top_k_not_n_experts(self):
        """XLA TPU cost model: dense/routed FLOPs ratio ~E/k at 128/8."""
        import dataclasses

        from llm_d_kv_cache_manager_tpu.models.llama import _moe_mlp

        cfg = self._a3b_shaped()
        dense_cfg = dataclasses.replace(cfg, moe_dispatch="dense")
        params = init_params(jax.random.PRNGKey(0), cfg)
        layer = params["layers"][0]
        x = jnp.zeros((1, 64, cfg.hidden_size), jnp.float32)

        def flops(c):
            fn = jax.jit(lambda p, v: _moe_mlp(p, c, v))
            an = fn.lower(layer, x).compile().cost_analysis()
            an = an[0] if isinstance(an, list) else an
            return an["flops"]

        ratio = flops(dense_cfg) / flops(cfg)
        assert ratio > 8, f"dense/routed flops ratio only {ratio:.1f}"


class TestQwen2MoeRejection:
    def test_shared_expert_moe_rejected(self):
        pytest.importorskip("torch")
        try:
            from transformers import Qwen2MoeConfig
        except ImportError:
            pytest.skip("transformers has no Qwen2Moe")
        from llm_d_kv_cache_manager_tpu.models.hf_loader import config_from_hf

        cfg = Qwen2MoeConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
            num_experts=4, shared_expert_intermediate_size=64,
        )
        with pytest.raises(NotImplementedError, match="shared-expert"):
            config_from_hf(cfg)
