"""Structured logging with verbosity levels.

Mirrors the reference's klog verbosity convention (reference
``pkg/utils/logging/levels.go:17-20``): DEBUG=4, TRACE=5. We map these onto
stdlib logging levels below ``logging.DEBUG`` so that `-v=5`-style tracing can
be enabled independently of ordinary debug output.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time

#: request-scoped structured-log context: {"request_id": ..., "trace_id":
#: ...} injected into every record emitted inside a ``log_context`` block,
#: so one request's pod logs grep end to end by id. Contextvars propagate
#: through asyncio tasks and thread-pool executors started inside the
#: context; the engine loop sets its own context around per-request work.
_LOG_CTX: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "kvcache_log_ctx", default={}
)


@contextlib.contextmanager
def log_context(**kv):
    """Attach key-values (e.g. ``request_id=...``, ``trace_id=...``) to
    every structured log record emitted within the block. Nests: inner
    contexts extend (and may override) outer ones."""
    current = _LOG_CTX.get()
    token = _LOG_CTX.set({**current, **{k: v for k, v in kv.items() if v is not None}})
    try:
        yield
    finally:
        _LOG_CTX.reset(token)

# klog-style verbosity levels, mapped into stdlib numeric levels.
# stdlib DEBUG is 10; we give TRACE a lower number so it is *more* verbose.
DEBUG = logging.DEBUG  # klog V(4)
TRACE = 5  # klog V(5)

logging.addLevelName(TRACE, "TRACE")

_PKG_LOGGER = "llm_d_kv_cache_manager_tpu"
_CONFIGURED = False


def _configure_package_logger() -> None:
    """Configure only this package's logger subtree — never the root logger,
    so embedding applications keep control of their own logging setup.

    Entry points (the online service, demos) may call
    ``logging.basicConfig`` themselves; library imports must not.
    """
    global _CONFIGURED
    if _CONFIGURED:
        return
    pkg = logging.getLogger(_PKG_LOGGER)
    level_name = os.environ.get("KVCACHE_LOG_LEVEL", "").upper()
    if level_name:
        level = TRACE if level_name == "TRACE" else getattr(logging, level_name, logging.INFO)
        pkg.setLevel(level)
        if not pkg.handlers:
            handler = logging.StreamHandler()
            handler.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
            )
            pkg.addHandler(handler)
    else:
        pkg.addHandler(logging.NullHandler())
    _CONFIGURED = True


class _KVLogger(logging.LoggerAdapter):
    """Logger adapter supporting structured key-values, klog style.

    ``log.debug("msg", keys=..., pods=...)`` renders the kwargs as
    ``msg | keys=... pods=...``.
    """

    _RESERVED = ("exc_info", "stack_info", "stacklevel", "extra")

    def process(self, msg, kwargs):
        kv = {k: kwargs.pop(k) for k in list(kwargs) if k not in self._RESERVED}
        ctx = _LOG_CTX.get()
        if ctx:
            kv = {**ctx, **kv}  # explicit call kwargs win over context
        if kv:
            msg = f"{msg} | " + " ".join(f"{k}={v!r}" for k, v in kv.items())
        return msg, kwargs

    def trace(self, msg, *args, **kwargs):
        self.log(TRACE, msg, *args, **kwargs)


def get_logger(name: str) -> _KVLogger:
    _configure_package_logger()
    if not name.startswith(_PKG_LOGGER):
        name = f"{_PKG_LOGGER}.{name}"
    return _KVLogger(logging.getLogger(name), {})


class RateLimitedWarn:
    """At-most-once-per-interval WARN per key, with a suppressed count.

    Background threads (event workers, heartbeat/self-heal loops, span
    exporters) hit the same fault thousands of times a second when a peer
    misbehaves; an unconditional ``log.exception`` per event is itself an
    outage. This emits the first occurrence immediately, then at most one
    line per ``interval_s`` per key carrying how many were swallowed in
    between — faults stay visible without the log volume scaling with the
    event rate.

    Thread-safe; uses ``time.monotonic`` (rate math must not step under
    NTP slew).
    """

    def __init__(self, log: _KVLogger, interval_s: float = 5.0):
        self._log = log
        self._interval_s = interval_s
        self._lock = threading.Lock()
        self._last_emit: dict[str, float] = {}  # guarded_by: _lock
        self._suppressed: dict[str, int] = {}  # guarded_by: _lock

    def warning(self, key: str, msg: str, *, exc_info: bool = False, **kv) -> None:
        now = time.monotonic()
        with self._lock:
            last = self._last_emit.get(key)
            if last is not None and now - last < self._interval_s:
                self._suppressed[key] = self._suppressed.get(key, 0) + 1
                return
            suppressed = self._suppressed.pop(key, 0)
            self._last_emit[key] = now
        if suppressed:
            kv["suppressed_repeats"] = suppressed
        self._log.warning(msg, exc_info=exc_info, **kv)
