"""Native (C++) in-memory index backend.

Same contract and two-level-LRU semantics as ``InMemoryIndex`` (the parity
port of the reference's ``in_memory.go``), with the hot structure in C++
behind a ctypes boundary: integer-only calls on the lookup path (model and
pod names are interned to u32 ids here, tiers to u8), one native call per
``lookup``/``add`` batch instead of per-key Python dict/lock traffic.

Passes the same backend conformance suite as every other Index
(tests/test_index_backends.py), and is selected via
``IndexConfig.native_memory`` when the shared library is built.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from ...native import lruindex as _native
from ...utils import get_logger
from .index import Index, NativeMemoryIndexConfig
from .keys import DeviceTier, Key, PodEntry

log = get_logger("kvcache.kvblock.native_memory")

_TIERS = list(DeviceTier)
_TIER_TO_ID = {t: i for i, t in enumerate(_TIERS)}


def native_available() -> bool:
    return _native.available()


class NativeMemoryIndex(Index):
    #: filter id that matches no interned pod: filters everything out while
    #: still walking (and LRU-promoting) the chain like the Python backend.
    _NO_MATCH_FILTER = 0xFFFFFFFF

    def __init__(self, config: Optional[NativeMemoryIndexConfig] = None):
        self.config = config or NativeMemoryIndexConfig()
        self._idx = _native.NativeLru(self.config.size, self.config.pod_cache_size)
        # Intern tables. Pods and models are few (fleet-sized); u32 is ample.
        self._mu = threading.Lock()
        self._model_ids: dict[str, int] = {}  # guarded_by: _mu
        self._pod_ids: dict[str, int] = {}  # guarded_by: _mu
        self._pod_names: list[str] = []  # guarded_by: _mu

    # -- interning ----------------------------------------------------------
    def _model_id(self, name: str, *, create: bool) -> Optional[int]:
        with self._mu:
            mid = self._model_ids.get(name)
            if mid is None and create:
                mid = len(self._model_ids)
                self._model_ids[name] = mid
            return mid

    def _pod_id(self, name: str, *, create: bool) -> Optional[int]:
        with self._mu:
            pid = self._pod_ids.get(name)
            if pid is None and create:
                pid = len(self._pod_names)
                self._pod_ids[name] = pid
                self._pod_names.append(name)
            return pid

    def _filter_ids(self, pod_filter: Optional[set[str]]) -> list[int]:
        if not pod_filter:
            return []
        ids = []
        for name in pod_filter:
            pid = self._pod_id(name, create=False)
            if pid is not None:
                ids.append(pid)
        # Every filter pod unknown: nothing can match, but the chain must
        # still be walked (and keys promoted) exactly as the Python backend
        # does — a no-match sentinel keeps filtering active.
        return ids or [self._NO_MATCH_FILTER]

    def _entry_ids(self, entries: Sequence[PodEntry], *, create: bool):
        pods, tiers = [], []
        for e in entries:
            pid = self._pod_id(e.pod_identifier, create=create)
            if pid is None:
                continue
            pods.append(pid)
            tiers.append(_TIER_TO_ID[e.device_tier])
        return pods, tiers

    # -- Index contract -----------------------------------------------------
    def lookup(
        self, keys: Sequence[Key], pod_filter: Optional[set[str]] = None
    ) -> dict[Key, list[str]]:
        if not keys:
            raise ValueError("no keys provided for lookup")
        filter_ids = self._filter_ids(pod_filter)
        out: dict[Key, list[str]] = {}
        # One native call per consecutive same-model run (the hot path is
        # always single-model; this keeps mixed-model batches correct).
        i, n = 0, len(keys)
        while i < n:
            j = i
            model = keys[i].model_name
            while j < n and keys[j].model_name == model:
                j += 1
            mid = self._model_id(model, create=False)
            if mid is None:
                i = j  # unknown model: every key missing — chain continues
                continue
            processed, per_key = self._idx.lookup(
                mid, [k.chunk_hash for k in keys[i:j]], filter_ids
            )
            with self._mu:
                names = self._pod_names
                for key, pods in zip(keys[i:j], per_key):
                    if pods:
                        out[key] = [names[pid] for pid, _tier in pods]
            if processed < j - i:  # present-but-empty key: stop the scan
                return out
            i = j
        return out

    def add(self, keys: Sequence[Key], entries: Sequence[PodEntry]) -> None:
        if not keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        pods, tiers = self._entry_ids(entries, create=True)
        i, n = 0, len(keys)
        while i < n:  # one native call per consecutive same-model run
            j = i
            model = keys[i].model_name
            while j < n and keys[j].model_name == model:
                j += 1
            mid = self._model_id(model, create=True)
            self._idx.add(mid, [k.chunk_hash for k in keys[i:j]], pods, tiers)
            i = j

    def evict(self, key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        mid = self._model_id(key.model_name, create=False)
        if mid is None:
            return
        pods, tiers = self._entry_ids(entries, create=False)
        if pods:
            self._idx.evict(mid, key.chunk_hash, pods, tiers)

    def size_info(self) -> dict:
        # Pods = interned identifiers, i.e. pods ever seen this process;
        # the C++ LRU does not expose a per-pod occupancy walk. Close
        # enough for the gauge's purpose (dashboards correlating routing
        # quality with index fill), and documented in docs/observability.md.
        with self._mu:
            n_pods = len(self._pod_names)
        return {"blocks": int(self._idx.size()), "pods": n_pods}

    def evict_pod(self, pod_identifier: str) -> int:
        pid = self._pod_id(pod_identifier, create=False)
        if pid is None:  # never interned = never added: nothing to sweep
            return 0
        removed = int(self._idx.evict_pod(pid))
        if removed:
            log.debug("swept pod from index", pod=pod_identifier, entries=removed)
        return removed

    def score_longest_prefix(
        self,
        keys: Sequence[Key],
        pod_filter: Optional[set[str]] = None,
    ) -> Optional[dict[str, int]]:
        """Fused lookup+score in one native call (LongestPrefixScorer
        semantics). Returns None when keys span models — the caller then
        falls back to the two-step path."""
        out = self.score_longest_prefix_with_hits(keys, pod_filter)
        return None if out is None else out[0]

    def score_longest_prefix_with_hits(
        self,
        keys: Sequence[Key],
        pod_filter: Optional[set[str]] = None,
    ) -> Optional[tuple[dict[str, int], int]]:
        if not keys:
            return {}, 0
        model = keys[0].model_name
        if any(k.model_name != model for k in keys[1:]):
            return None
        return self.score_hashes_with_hits(
            model, [k.chunk_hash for k in keys], pod_filter
        )

    def score_hashes(
        self,
        model_name: str,
        hashes: Sequence[int],
        pod_filter: Optional[set[str]] = None,
    ) -> dict[str, int]:
        """Fused scoring from raw chain hashes — the zero-object hot path
        (no Key allocation between the hash kernel and the index)."""
        scores, _hits = self.score_hashes_with_hits(model_name, hashes, pod_filter)
        return scores

    def score_hashes_with_hits(
        self,
        model_name: str,
        hashes: Sequence[int],
        pod_filter: Optional[set[str]] = None,
    ) -> tuple[dict[str, int], int]:
        """Like ``score_hashes`` but also returns the lookup-hit count (keys
        with a filter-surviving pod) so the instrumented decorator can report
        metrics identical to the two-step path."""
        if not hashes:
            return {}, 0
        mid = self._model_id(model_name, create=False)
        if mid is None:
            return {}, 0
        scored, hits = self._idx.score(
            mid, hashes, self._filter_ids(pod_filter)
        )
        with self._mu:
            names = self._pod_names
            return {names[pid]: int(s) for pid, s in scored}, hits

    def __len__(self) -> int:
        return self._idx.size()
